"""Leave-one-out evaluation for the sequential template.

Run (repo root on PYTHONPATH, like the sibling examples):
  ptpu eval examples.sequential.evaluation:evaluation \
      examples.sequential.evaluation:engine_params_generator
"""

from predictionio_tpu.controller.evaluation import (
    EngineParamsGenerator,
    Evaluation,
)
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.models.seqrec import SeqRecParams
from predictionio_tpu.templates.sequential import (
    DataSourceParams,
    HitRateAtK,
    SeqNDCGAtK,
    sequential_engine,
)

evaluation = Evaluation(
    engine=sequential_engine(),
    metric=HitRateAtK(k=10),
    other_metrics=[SeqNDCGAtK(k=10)],
)


class _Gen(EngineParamsGenerator):
    engine_params_list = [
        EngineParams(
            datasource=("", DataSourceParams(app_name="MyApp1",
                                             max_len=50,
                                             eval_query_num=10)),
            algorithms=[("seqrec", SeqRecParams(
                dim=dim, heads=2, num_blocks=blocks, max_len=50,
                num_epochs=20, batch_size=256, learning_rate=1e-3,
                n_negatives=64, seed=7))])
        for dim in (32, 64)
        for blocks in (1, 2)
    ]


engine_params_generator = _Gen()
