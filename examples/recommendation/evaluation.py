"""Shipped evaluation for the recommendation template — the reference's
``Evaluation.scala:62-107`` (RecommendationEvaluation +
ComprehensiveRecommendationEvaluation + EngineParamsList).

Run:  ptpu eval examples.recommendation.evaluation:evaluation \
          examples.recommendation.evaluation:engine_params_generator
(with the repo root on PYTHONPATH and an app named like APP_NAME below).
"""

import os

from predictionio_tpu.controller import Evaluation
from predictionio_tpu.controller.evaluation import EngineParamsGenerator
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams,
    NDCGAtK,
    PositiveCount,
    PrecisionAtK,
    recommendation_engine,
)

APP_NAME = os.environ.get("PTPU_EVAL_APP", "MyApp1")

#: Precision@10 (threshold 4.0) as the optimized metric; the full
#: reference grid k∈{1,3,10} × thresholds {0,2,4} + PositiveCount and
#: the BASELINE.md NDCG@10 as side metrics.
evaluation = Evaluation(
    engine=recommendation_engine(),
    metric=PrecisionAtK(k=10, rating_threshold=4.0),
    other_metrics=[
        *(PrecisionAtK(k=k, rating_threshold=t)
          for t in (0.0, 2.0, 4.0) for k in (1, 3, 10)
          if not (k == 10 and t == 4.0)),
        NDCGAtK(k=10, rating_threshold=2.0),
        PositiveCount(rating_threshold=2.0),
    ],
)


class _Gen(EngineParamsGenerator):
    """rank × numIterations grid (``Evaluation.scala:92-107``)."""

    engine_params_list = [
        EngineParams(
            datasource=("", DataSourceParams(app_name=APP_NAME, eval_k=3)),
            algorithms=[("als", ALSParams(rank=rank, num_iterations=it,
                                          reg=0.01, seed=3))])
        for rank in (8, 16) for it in (5, 10)
    ]


engine_params_generator = _Gen()
