#!/usr/bin/env bash
# Build the release artifacts: wheel + sdist + a self-contained tarball
# (bin/, examples/, docs/, Docker assets) an operator can unpack and run
# — the role of the reference's make-distribution.sh, minus sbt.
set -euo pipefail
cd "$(dirname "$0")"

VERSION=$(python -c "import tomllib; \
print(tomllib.load(open('pyproject.toml','rb'))['project']['version'])")
DIST="dist/predictionio-tpu-${VERSION}"

rm -rf dist
# --no-build-isolation: build with the installed setuptools (works in
# air-gapped environments; pip's isolated env would fetch from PyPI)
python -m pip wheel --no-deps --no-build-isolation -w dist . > /dev/null

mkdir -p "${DIST}"
cp -r bin examples docs Dockerfile docker README.md "${DIST}/"
cp dist/*.whl "${DIST}/"
cat > "${DIST}/install.sh" << 'EOF'
#!/usr/bin/env bash
set -euo pipefail
cd "$(dirname "$0")"
pip install ./*.whl
echo "Installed. Try: ptpu status"
EOF
chmod +x "${DIST}/install.sh" bin/ptpu || true

tar -C dist -czf "dist/predictionio-tpu-${VERSION}.tar.gz" \
    "predictionio-tpu-${VERSION}"
echo "Built:"
ls -l dist/*.tar.gz dist/*.whl
