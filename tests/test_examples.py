"""Every shipped example variant must load through the CLI build path."""

import glob

import pytest

from predictionio_tpu.cli import engine_from_variant, load_variant

VARIANTS = sorted(glob.glob("examples/*/engine.json"))


def test_examples_exist():
    assert len(VARIANTS) == 5


@pytest.mark.parametrize("path", VARIANTS)
def test_variant_loads(path):
    variant = load_variant(path)
    engine, ep = engine_from_variant(variant)
    assert ep.algorithms
    assert engine.make_algorithms(ep)
    assert engine.make_serving(ep) is not None
