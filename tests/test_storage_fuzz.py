"""Cross-backend storage fuzz: a seeded random op sequence applied
identically to every durable backend, with the memory backend as the
oracle — inserts (fresh + replace-by-id), deletes, filtered finds,
columnar reads, and property aggregation must all agree at every
checkpoint. Catches contract drift no single-scenario test would."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import ANY, EventFilter
from predictionio_tpu.data.storage.memory import MemoryEventStore

T0 = datetime(2026, 3, 1, tzinfo=timezone.utc)
APP = 3


def proj(e):
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, e.event_time_millis,
            tuple(sorted(e.properties.to_dict().items())))


@pytest.fixture(params=["sqlite", "localfs", "segmentfs", "remote", "s3"])
def dut(request, tmp_path):
    if request.param == "s3":
        from predictionio_tpu.data.storage.objectstore import (
            FakeObjectStoreServer,
            ObjectStoreClient,
            ObjectStoreEventStore,
        )
        srv = FakeObjectStoreServer(str(tmp_path / "bucket"))
        srv.start_background()
        yield ObjectStoreEventStore(ObjectStoreClient(
            f"http://127.0.0.1:{srv.port}/bucket"))
        srv.shutdown()
        return
    if request.param == "remote":
        from conftest import start_sqlite_backed_storage_server
        from predictionio_tpu.data.storage.remote import (
            RemoteClient,
            RemoteEventStore,
        )
        srv, _ = start_sqlite_backed_storage_server(tmp_path)
        yield RemoteEventStore(RemoteClient(
            f"http://127.0.0.1:{srv.port}"))
        srv.shutdown()
        return
    if request.param == "sqlite":
        from predictionio_tpu.data.storage.sqlite import (
            SQLiteClient,
            SQLiteEventStore,
        )
        client = SQLiteClient(str(tmp_path / "f.db"))
        yield SQLiteEventStore(client)
        client.close()
    elif request.param == "localfs":
        from predictionio_tpu.data.storage.localfs import (
            LocalFSClient,
            LocalFSEventStore,
        )
        client = LocalFSClient(str(tmp_path / "lfs"))
        yield LocalFSEventStore(client)
        client.close()
    else:
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        client = SegmentFSClient(str(tmp_path / "sfs"))
        yield SegmentFSEventStore(client)
        client.close()


def _rand_event(rng, k, with_id=None):
    """Deterministic random event; unique ms timestamps avoid ordering
    ties (backends may tie-break differently, which is out of contract)."""
    etype = "user" if rng.random() < 0.7 else "item"
    name = rng.choice(["rate", "view", "$set", "buy"])
    props = {}
    if name == "rate":
        props["rating"] = float(rng.integers(1, 6))
    if name == "$set":
        props["cat"] = f"c{int(rng.integers(0, 3))}"
        if rng.random() < 0.3:
            props["score"] = float(rng.integers(0, 100))
    has_target = name in ("rate", "view", "buy")
    return Event(
        event=str(name), entity_type=etype,
        entity_id=f"{etype[0]}{int(rng.integers(0, 12))}",
        target_entity_type="item" if has_target else None,
        target_entity_id=(f"i{int(rng.integers(0, 8))}"
                          if has_target else None),
        properties=DataMap(props),
        event_time=T0 + timedelta(milliseconds=int(k)),
        event_id=with_id)


def _compare(oracle, dut):
    a = sorted(proj(e) for e in oracle.find(APP))
    b = sorted(proj(e) for e in dut.find(APP))
    assert a == b
    # filtered find (time window + event names + target tri-state)
    f = EventFilter(event_names=["rate", "$set"],
                    start_time=T0 + timedelta(milliseconds=40),
                    target_entity_type=ANY)
    assert sorted(proj(e) for e in oracle.find(APP, filter=f)) == \
        sorted(proj(e) for e in dut.find(APP, filter=f))
    f2 = EventFilter(entity_type="user", target_entity_type=None)
    assert sorted(proj(e) for e in oracle.find(APP, filter=f2)) == \
        sorted(proj(e) for e in dut.find(APP, filter=f2))
    # ORDERED semantics: limit + reversed must agree between the row
    # scan and the columnar projection as exact SEQUENCES (unique
    # event times make the ordering deterministic)
    f3 = EventFilter(reversed=True, limit=7)
    ra = [proj(e) for e in oracle.find(APP, filter=f3)]
    assert ra == [proj(e) for e in dut.find(APP, filter=f3)]
    assert ra == [proj(e)
                  for e in dut.find_columnar(APP, filter=f3).to_events()]
    # columnar projection == row scan (bulk-read fields)
    cb = sorted(proj(e) for e in dut.find_columnar(APP).to_events())
    assert cb == a
    # property aggregation (latest-by-time semantics; unique times)
    for etype in ("user", "item"):
        pa = oracle.aggregate_properties(APP, entity_type=etype)
        pb = dut.aggregate_properties(APP, entity_type=etype)
        assert {k: dict(v.to_dict()) for k, v in pa.items()} == \
            {k: dict(v.to_dict()) for k, v in pb.items()}


@pytest.mark.parametrize("seed", [1, 2])
def test_random_op_sequence_matches_memory_oracle(dut, seed):
    rng = np.random.default_rng(seed)
    oracle = MemoryEventStore()
    oracle.init(APP)
    dut.init(APP)
    known_ids: list = []
    k = 0
    for phase in range(4):
        ops = []
        for _ in range(40):
            r = rng.random()
            if r < 0.55 or not known_ids:
                ops.append(("insert", None))
            elif r < 0.7:
                ops.append(("replace",
                            known_ids[int(rng.integers(0, len(known_ids)))]))
            else:
                ops.append(("delete",
                            known_ids[int(rng.integers(0, len(known_ids)))]))
        for op, eid in ops:
            if op == "insert":
                batch = [_rand_event(rng, k + j)
                         for j in range(int(rng.integers(1, 4)))]
                k += len(batch)
                ids_a = oracle.insert_batch(
                    [e.copy() for e in batch], APP)
                # same explicit ids on the DUT so replace/delete agree
                for e, i in zip(batch, ids_a):
                    dut.insert(e.copy(event_id=i), APP)
                known_ids.extend(ids_a)
            elif op == "replace":
                e = _rand_event(rng, k, with_id=eid)
                k += 1
                oracle.insert(e.copy(), APP)
                dut.insert(e.copy(), APP)
            else:
                ra = oracle.delete(eid, APP)
                rb = dut.delete(eid, APP)
                assert ra == rb
                if ra and eid in known_ids:
                    known_ids.remove(eid)
        _compare(oracle, dut)


@pytest.mark.parametrize("kind", ["sqlite", "segmentfs"])
def test_concurrent_writers_vs_columnar_readers(tmp_path, kind):
    """Writers (inserts + occasional deletes) race columnar readers on
    one store: no reader may crash, and after the dust settles the
    sidecar must converge to exactly the row store's content — the
    stamp/prefix-check/self-heal design's core claim."""
    import threading

    if kind == "sqlite":
        from predictionio_tpu.data.storage.sqlite import (
            SQLiteClient,
            SQLiteEventStore,
        )
        es = SQLiteEventStore(SQLiteClient(str(tmp_path / "c.db")))
    else:
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        es = SegmentFSEventStore(SegmentFSClient(str(tmp_path / "c")))
    es.init(APP)
    errors: list = []
    inserted: list = []
    ins_lock = threading.Lock()

    def writer(t):
        rng = np.random.default_rng(100 + t)
        try:
            for burst in range(6):
                batch = [_rand_event(rng, t * 10_000 + burst * 100 + j)
                         for j in range(25)]
                ids = es.insert_batch(batch, APP)
                with ins_lock:
                    inserted.extend(ids)
                if rng.random() < 0.5 and inserted:
                    with ins_lock:
                        victim = inserted[int(rng.integers(
                            0, len(inserted)))]
                    es.delete(victim, APP)
        except Exception as e:  # noqa: BLE001
            errors.append(("writer", e))

    def reader():
        try:
            for _ in range(8):
                b = es.find_columnar(APP, ordered=False,
                                     with_props=False)
                assert b.n >= 0
                list(es.find(APP, filter=EventFilter(limit=5)))
        except Exception as e:  # noqa: BLE001
            errors.append(("reader", e))

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    rows = sorted(proj(e) for e in es.find(APP))
    cols = sorted(proj(e) for e in es.find_columnar(APP).to_events())
    assert cols == rows
    assert len(rows) >= 4 * 6 * 25 - 4 * 6  # minus deletions


def test_channel_partitions_stay_isolated(dut):
    """Ops split across channels None/1/2: per-channel finds, columnar
    projections and aggregations must each match the oracle — channel
    bleed in any backend is a silent data-corruption class."""
    rng = np.random.default_rng(11)
    oracle = MemoryEventStore()
    # 0 included deliberately: falsy `if channel_id` checks aliased
    # channel 0 into the default channel on two backends (fixed)
    chans = [None, 0, 1, 2]
    for c in chans:
        oracle.init(APP, c)
        dut.init(APP, c)
    k = 0
    for _ in range(90):
        c = chans[int(rng.integers(0, 3))]
        e = _rand_event(rng, k)
        k += 1
        i = oracle.insert(e.copy(), APP, c)
        dut.insert(e.copy(event_id=i), APP, c)
    for c in chans:
        a = sorted(proj(e) for e in oracle.find(APP, c))
        assert a == sorted(proj(e) for e in dut.find(APP, c))
        assert a == sorted(proj(e) for e in
                           dut.find_columnar(APP, c).to_events())
        pa = oracle.aggregate_properties(APP, c, entity_type="item")
        pb = dut.aggregate_properties(APP, c, entity_type="item")
        assert {k2: dict(v.to_dict()) for k2, v in pa.items()} == \
            {k2: dict(v.to_dict()) for k2, v in pb.items()}
