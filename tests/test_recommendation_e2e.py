"""End-to-end slice (SURVEY §7): event store → recommendation template →
train → persist → deploy-load → predict → k-fold eval with metrics.

This is the minimum end-to-end target: every layer the north star touches.
"""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context, Evaluation
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams,
    NDCGAtK,
    PositiveCount,
    PrecisionAtK,
    Query,
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import (
    get_latest_completed,
    load_models_for_deploy,
    run_evaluation,
    run_train,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(scope="module")
def seeded_ctx():
    """Storage with a structured ratings pattern: users come in two taste
    groups; group A rates items 0-14 high, group B rates 15-29 high."""
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app_id = storage.apps().insert(App(0, "mlapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(42)
    events = []
    for u in range(40):
        group_items = range(0, 15) if u % 2 == 0 else range(15, 30)
        other_items = range(15, 30) if u % 2 == 0 else range(0, 15)
        liked = rng.choice(list(group_items), size=8, replace=False)
        disliked = rng.choice(list(other_items), size=4, replace=False)
        t = T0
        for i in liked:
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(4, 6))}),
                event_time=t))
            t += timedelta(minutes=1)
        for i in disliked:
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 3))}),
                event_time=t))
            t += timedelta(minutes=1)
        # some buy events (implied rating 4.0)
        events.append(Event(
            event="buy", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item",
            target_entity_id=f"i{rng.choice(list(group_items))}",
            event_time=t))
    es.insert_batch(events, app_id)
    return Context(app_name="mlapp", _storage=storage)


def engine_and_params():
    engine = recommendation_engine()
    ep = default_engine_params("mlapp", rank=8, num_iterations=8, reg=0.05,
                               seed=11)
    return engine, ep


class TestTrainDeployPredict:
    def test_full_lifecycle(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()

        instance_id = run_train(ctx, engine, ep, engine_id="reco",
                                engine_factory="templates.recommendation")
        assert instance_id

        instance = get_latest_completed(ctx, engine_id="reco")
        assert instance is not None
        assert instance.id == instance_id

        models = load_models_for_deploy(ctx, engine, instance, ep)
        assert len(models) == 1
        model = models[0]

        serving = engine.make_serving(ep)
        algo = engine.make_algorithms(ep)[0]
        q = Query(user="u0", num=5)
        result = serving.serve(q, [algo.predict(model, q)])
        assert len(result.item_scores) == 5
        # u0 is in group A (items 0-14); top recs should be group A items
        top_items = [int(s.item[1:]) for s in result.item_scores]
        in_group = sum(1 for i in top_items if i < 15)
        assert in_group >= 4, f"expected group-A items, got {top_items}"
        # scores sorted
        scores = [s.score for s in result.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_empty_result(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        result = engine.train(ctx, ep)
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(result.models[0], Query(user="ghost", num=3))
        assert pred.item_scores == ()

    def test_batch_predict_matches_single(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        queries = [Query(user="u1", num=3), Query(user="ghost", num=3),
                   Query(user="u5", num=2)]
        batch = algo.batch_predict(model, queries)
        assert [s.item for s in batch[0].item_scores] == \
               [s.item for s in algo.predict(model, queries[0]).item_scores]
        assert batch[1].item_scores == ()
        assert len(batch[2].item_scores) == 2

    def test_json_result_shape(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        j = algo.predict(model, Query(user="u2", num=2)).to_json()
        assert set(j.keys()) == {"itemScores"}
        assert set(j["itemScores"][0].keys()) == {"item", "score"}


class TestEvaluationE2E:
    def test_kfold_eval_with_metrics(self, seeded_ctx):
        ctx = seeded_ctx
        engine, _ = engine_and_params()
        grid = []
        for rank in (4, 8):
            grid.append(default_engine_params("mlapp", rank=rank,
                                              num_iterations=6, reg=0.05,
                                              seed=11).copy(
                datasource=("", DataSourceParams(app_name="mlapp", eval_k=3,
                                                 eval_query_num=10))))
        evaluation = Evaluation(
            engine=engine, metric=PrecisionAtK(k=5, rating_threshold=4.0),
            other_metrics=[NDCGAtK(k=5, rating_threshold=4.0),
                           PositiveCount(rating_threshold=4.0)])
        result = run_evaluation(ctx, evaluation, grid,
                                evaluation_class="RecommendationEvaluation")
        assert len(result.scores) == 2
        assert 0.0 <= result.best_score <= 1.0
        # taste groups are strongly separated: a working ALS should place
        # held-out relevant items in top-5 well above chance (~0.09 random;
        # top-5 legitimately includes already-rated train items, matching
        # MLlib recommendProducts which does not filter seen items)
        assert result.best_score > 0.15, result.to_one_liner()
        # evaluation instance recorded
        done = ctx.storage.evaluation_instances().get_completed()
        assert len(done) == 1
        assert "best variant" in done[0].evaluator_results
        assert done[0].evaluator_results_json
