"""End-to-end slice (SURVEY §7): event store → recommendation template →
train → persist → deploy-load → predict → k-fold eval with metrics.

This is the minimum end-to-end target: every layer the north star touches.
"""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context, Evaluation
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams,
    NDCGAtK,
    PositiveCount,
    PrecisionAtK,
    Query,
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import (
    get_latest_completed,
    load_models_for_deploy,
    run_evaluation,
    run_train,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(scope="module")
def seeded_ctx():
    """Storage with a structured ratings pattern: users come in two taste
    groups; group A rates items 0-14 high, group B rates 15-29 high."""
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app_id = storage.apps().insert(App(0, "mlapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(42)
    events = []
    for u in range(40):
        group_items = range(0, 15) if u % 2 == 0 else range(15, 30)
        other_items = range(15, 30) if u % 2 == 0 else range(0, 15)
        liked = rng.choice(list(group_items), size=8, replace=False)
        disliked = rng.choice(list(other_items), size=4, replace=False)
        t = T0
        for i in liked:
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(4, 6))}),
                event_time=t))
            t += timedelta(minutes=1)
        for i in disliked:
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 3))}),
                event_time=t))
            t += timedelta(minutes=1)
        # some buy events (implied rating 4.0)
        events.append(Event(
            event="buy", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item",
            target_entity_id=f"i{rng.choice(list(group_items))}",
            event_time=t))
    es.insert_batch(events, app_id)
    return Context(app_name="mlapp", _storage=storage)


def engine_and_params():
    engine = recommendation_engine()
    ep = default_engine_params("mlapp", rank=8, num_iterations=8, reg=0.05,
                               seed=11)
    return engine, ep


class TestTrainDeployPredict:
    def test_full_lifecycle(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()

        instance_id = run_train(ctx, engine, ep, engine_id="reco",
                                engine_factory="templates.recommendation")
        assert instance_id

        instance = get_latest_completed(ctx, engine_id="reco")
        assert instance is not None
        assert instance.id == instance_id

        models = load_models_for_deploy(ctx, engine, instance, ep)
        assert len(models) == 1
        model = models[0]

        serving = engine.make_serving(ep)
        algo = engine.make_algorithms(ep)[0]
        q = Query(user="u0", num=5)
        result = serving.serve(q, [algo.predict(model, q)])
        assert len(result.item_scores) == 5
        # u0 is in group A (items 0-14); top recs should be group A items
        top_items = [int(s.item[1:]) for s in result.item_scores]
        in_group = sum(1 for i in top_items if i < 15)
        assert in_group >= 4, f"expected group-A items, got {top_items}"
        # scores sorted
        scores = [s.score for s in result.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_empty_result(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        result = engine.train(ctx, ep)
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(result.models[0], Query(user="ghost", num=3))
        assert pred.item_scores == ()

    def test_batch_predict_matches_single(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        queries = [Query(user="u1", num=3), Query(user="ghost", num=3),
                   Query(user="u5", num=2)]
        batch = algo.batch_predict(model, queries)
        assert [s.item for s in batch[0].item_scores] == \
               [s.item for s in algo.predict(model, queries[0]).item_scores]
        assert batch[1].item_scores == ()
        assert len(batch[2].item_scores) == 2

    def test_json_result_shape(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        j = algo.predict(model, Query(user="u2", num=2)).to_json()
        assert set(j.keys()) == {"itemScores"}
        assert set(j["itemScores"][0].keys()) == {"item", "score"}


class TestEvaluationE2E:
    def test_kfold_eval_with_metrics(self, seeded_ctx):
        ctx = seeded_ctx
        engine, _ = engine_and_params()
        grid = []
        for rank in (4, 8):
            grid.append(default_engine_params("mlapp", rank=rank,
                                              num_iterations=6, reg=0.05,
                                              seed=11).copy(
                datasource=("", DataSourceParams(app_name="mlapp", eval_k=3,
                                                 eval_query_num=10))))
        evaluation = Evaluation(
            engine=engine, metric=PrecisionAtK(k=5, rating_threshold=4.0),
            other_metrics=[NDCGAtK(k=5, rating_threshold=4.0),
                           PositiveCount(rating_threshold=4.0)])
        result = run_evaluation(ctx, evaluation, grid,
                                evaluation_class="RecommendationEvaluation")
        assert len(result.scores) == 2
        assert 0.0 <= result.best_score <= 1.0
        # taste groups are strongly separated: a working ALS should place
        # held-out relevant items in top-5 well above chance (~0.09 random;
        # top-5 legitimately includes already-rated train items, matching
        # MLlib recommendProducts which does not filter seen items)
        assert result.best_score > 0.15, result.to_one_liner()
        # evaluation instance recorded
        done = ctx.storage.evaluation_instances().get_completed()
        assert len(done) == 1
        assert "best variant" in done[0].evaluator_results
        assert done[0].evaluator_results_json


class TestTemplateVariants:
    """The reference's recommendation sub-examples (SURVEY §2.2 variants:
    blacklist-items, customize-serving, customize-data-prep,
    train-with-view-event / reading-custom-events)."""

    def test_query_blacklist(self, seeded_ctx):
        ctx = seeded_ctx
        engine, ep = engine_and_params()
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        base = algo.predict(model, Query(user="u0", num=5))
        banned = base.item_scores[0].item
        filtered = algo.predict(model, Query(user="u0", num=5,
                                             black_list=[banned]))
        assert banned not in {s.item for s in filtered.item_scores}
        assert len(filtered.item_scores) == 5
        # batch path honors the same blacklist
        batch = algo.batch_predict(model, [Query(user="u0", num=5,
                                                 black_list=[banned])])
        assert banned not in {s.item for s in batch[0].item_scores}

    def test_file_blacklist_serving(self, seeded_ctx, tmp_path):
        from predictionio_tpu.templates.recommendation import (
            FileBlacklistServing,
            FileBlacklistServingParams,
        )

        ctx = seeded_ctx
        engine, ep = engine_and_params()
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(user="u0", num=5))
        disabled = pred.item_scores[0].item
        f = tmp_path / "disabled.txt"
        f.write_text(disabled + "\n")
        serving = FileBlacklistServing(
            FileBlacklistServingParams(filepath=str(f)))
        served = serving.serve(Query(user="u0", num=5), [pred])
        assert disabled not in {s.item for s in served.item_scores}

    def test_exclude_items_preparator(self, seeded_ctx):
        from predictionio_tpu.controller.params import EngineParams
        from predictionio_tpu.models.als import ALSParams
        from predictionio_tpu.templates.recommendation import (
            DataSourceParams,
            ExcludeItemsPreparatorParams,
        )

        ctx = seeded_ctx
        engine = recommendation_engine()
        ep = EngineParams(
            datasource=("", DataSourceParams(app_name="mlapp")),
            preparator=("exclude",
                        ExcludeItemsPreparatorParams(items=("i0", "i1"))),
            algorithms=[("als", ALSParams(rank=4, num_iterations=4,
                                          seed=2))])
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        # excluded items leave the model entirely: they can NEVER be
        # recommended, no matter the query size
        pred = algo.predict(model, Query(user="u0", num=30))
        returned = {s.item for s in pred.item_scores}
        assert pred.item_scores
        assert not ({"i0", "i1"} & returned), returned
        assert "i0" not in model.item_ids and "i1" not in model.item_ids

    def test_variant_json_configures_named_prep_and_serving(self,
                                                            seeded_ctx,
                                                            tmp_path):
        """The examples/README workflow: named preparator/serving with
        typed params straight from engine.json."""
        disabled = tmp_path / "disabled.txt"
        engine = recommendation_engine()
        variant = {
            "datasource": {"params": {"app_name": "mlapp"}},
            "preparator": {"name": "exclude",
                           "params": {"items": ["i3"]}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 4, "num_iterations": 4,
                                       "seed": 2}}],
            "serving": {"name": "fileblacklist",
                        "params": {"filepath": str(disabled)}},
        }
        ep = engine.params_from_variant(variant)
        model = engine.train(seeded_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        serving = engine.make_serving(ep)
        pred = algo.predict(model, Query(user="u0", num=5))
        banned = pred.item_scores[0].item
        disabled.write_text(banned + "\n")
        served = serving.serve(Query(user="u0", num=5), [pred])
        assert banned not in {s.item for s in served.item_scores}
        assert "i3" not in model.item_ids  # excluded via variant JSON

    def test_custom_event_weights(self, seeded_ctx):
        """train-with-view-event shape: implicit ALS over a single custom
        event with a fixed weight."""
        from predictionio_tpu.controller.params import EngineParams
        from predictionio_tpu.models.als import ALSParams
        from predictionio_tpu.templates.recommendation import (
            DataSourceParams,
        )

        ctx = seeded_ctx
        engine = recommendation_engine()
        ep = EngineParams(
            datasource=("", DataSourceParams(
                app_name="mlapp", event_weights={"buy": 1.0})),
            algorithms=[("als", ALSParams(rank=4, num_iterations=4,
                                          implicit_prefs=True, alpha=10.0,
                                          seed=2))])
        result = engine.train(ctx, ep)
        assert result.models[0].item_factors is not None
