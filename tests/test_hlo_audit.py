"""``ptpu audit-hlo`` tests (ISSUE 14): HLO collective parsing, the
golden collective-count regressions for the sharded entry points
(compiled live on the forced 8-device CPU mesh the whole suite runs
under), the ratchet diff/write semantics, the deliberately mis-specced
fixture that must fail with the inserted collective NAMED, and the CLI
contract."""

import json
import os

import numpy as np
import pytest

from predictionio_tpu.analysis import hlo_audit as ha
from predictionio_tpu.cli import main

jax = pytest.importorskip("jax")


def _mesh():
    from predictionio_tpu.parallel.mesh import make_serving_mesh

    return make_serving_mesh()


def _rows_sharded(mesh, arr):
    from jax.sharding import NamedSharding

    from predictionio_tpu.parallel.mesh import rows_spec

    return jax.device_put(arr, NamedSharding(mesh, rows_spec(mesh)))


class TestParseCollectives:
    def test_counts_and_shapes(self):
        hlo = """
  %x = f32[4,64]{1,0} all-gather(f32[4,8]{1,0} %a), dimensions={1}
  %y = f32[16,16]{1,0} all-reduce(f32[16,16]{1,0} %b), to_apply=%add
  %z = f32[4,64]{1,0} all-gather(f32[4,8]{1,0} %c), dimensions={1}
"""
        counts, shapes = ha.parse_collectives(hlo)
        assert counts == {"all-gather": 2, "all-reduce": 1}
        assert shapes["all-reduce"] == ["f32[16,16]{1,0}"]

    def test_start_counts_done_does_not(self):
        hlo = """
  %s = f32[8]{0} all-reduce-start(f32[8]{0} %a), to_apply=%add
  %d = f32[8]{0} all-reduce-done(f32[8]{0} %s)
  %p = (f32[2]{0}, f32[2]{0}) collective-permute(f32[2]{0} %b)
"""
        counts, _ = ha.parse_collectives(hlo)
        assert counts == {"all-reduce": 1, "collective-permute": 1}


class TestGoldenCollectiveCounts:
    """The satellite regression tests: EXACTLY the expected collective
    set for the two flagship sharded programs on the 8-device mesh —
    a new collective fails here before it ships to TPU."""

    def test_gramian_allreduce_is_one_psum(self):
        from predictionio_tpu.parallel.collectives import (
            gramian_allreduce,
        )

        mesh = _mesh()
        x = _rows_sharded(
            mesh, np.ones((8 * mesh.devices.size, 16), np.float32))
        compiled = jax.jit(
            lambda t: gramian_allreduce(t, mesh)).lower(x).compile()
        counts, _ = ha.parse_collectives(compiled.as_text())
        assert counts == {"all-reduce": 1}, counts

    def test_sharded_rank_is_two_allgathers(self):
        # per-shard local top-k, then ONE candidate all-gather for the
        # scores and ONE for the global ids — O(k·n_dev) on the wire,
        # nothing else
        from predictionio_tpu.models.als import _sharded_rank_fn

        mesh = _mesh()
        n = 8 * mesh.devices.size
        table = _rows_sharded(mesh, np.ones((n, 16), np.float32))
        vecs = np.ones((4, 16), np.float32)
        compiled = _sharded_rank_fn(mesh, 8, 8, n).lower(
            vecs, table).compile()
        counts, _ = ha.parse_collectives(compiled.as_text())
        assert counts == {"all-gather": 2}, counts


class TestRunAuditAndDiff:
    @pytest.fixture(scope="class")
    def manifest(self):
        return ha.run_audit(["gramian_allreduce", "gather_rows"])

    def test_manifest_shape(self, manifest):
        assert manifest["version"] == ha.MANIFEST_VERSION
        assert manifest["devices"] == ha.AUDIT_DEVICE_COUNT
        assert set(manifest["entries"]) == {"gramian_allreduce",
                                            "gather_rows"}
        rec = manifest["entries"]["gramian_allreduce"]
        assert rec["collectives"] == {"all-reduce": 1}
        assert rec["temp_bytes"] >= 0

    def test_identical_manifests_pass(self, manifest):
        violations, shrinkable = ha.diff_manifests(manifest, manifest)
        assert violations == [] and shrinkable == []

    def test_new_collective_fails_with_op_named(self, manifest):
        baseline = json.loads(json.dumps(manifest))
        del baseline["entries"]["gramian_allreduce"][
            "collectives"]["all-reduce"]
        violations, _ = ha.diff_manifests(manifest, baseline)
        assert len(violations) == 1
        assert "gramian_allreduce" in violations[0]
        assert "all-reduce" in violations[0]

    def test_grown_temp_fails(self, manifest):
        current = json.loads(json.dumps(manifest))
        rec = current["entries"]["gather_rows"]
        rec["temp_bytes"] = int(
            manifest["entries"]["gather_rows"]["temp_bytes"]
            * ha.TEMP_GROWTH_RATIO + ha.TEMP_SLACK_BYTES + 4096)
        violations, _ = ha.diff_manifests(current, manifest)
        assert len(violations) == 1
        assert "temp allocation" in violations[0]

    def test_unknown_entry_point_fails(self, manifest):
        current = json.loads(json.dumps(manifest))
        current["entries"]["rogue"] = {"collectives": {},
                                       "temp_bytes": 0}
        violations, _ = ha.diff_manifests(current, manifest)
        assert any("rogue" in v and "baseline" in v
                   for v in violations)

    def test_shrink_reported_not_failed(self, manifest):
        current = json.loads(json.dumps(manifest))
        del current["entries"]["gramian_allreduce"][
            "collectives"]["all-reduce"]
        violations, shrinkable = ha.diff_manifests(current, manifest)
        assert violations == []
        assert any("all-reduce" in s for s in shrinkable)

    def test_write_ratchets_never_absorbs(self, manifest, tmp_path):
        path = str(tmp_path / "baseline.json")
        ha.write_manifest(path, manifest)
        grown = json.loads(json.dumps(manifest))
        grown["entries"]["gramian_allreduce"]["collectives"][
            "all-to-all"] = 3
        ha.write_manifest(path, grown, cap=ha.load_manifest(path))
        rewritten = ha.load_manifest(path)
        assert "all-to-all" not in rewritten["entries"][
            "gramian_allreduce"]["collectives"]

    def test_committed_baseline_matches_live_compile(self, manifest):
        """The committed golden manifest reproduces on this machine
        for the audited subset — the CI gate's premise."""
        baseline = ha.load_manifest(ha.DEFAULT_BASELINE)
        for name in manifest["entries"]:
            assert manifest["entries"][name]["collectives"] == \
                baseline["entries"][name]["collectives"], name


class TestMisSpeccedFixtureFailsCI:
    def test_replicating_a_sharded_table_names_the_collective(self):
        """The acceptance fixture: force the exact bug the audit
        exists for — a row-sharded table consumed through a
        replicated out_sharding — and assert the gate fails with the
        inserted collective named."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh()
        table = _rows_sharded(
            mesh, np.ones((8 * mesh.devices.size, 16), np.float32))
        # the mis-spec: out_shardings=P() forces XLA to materialize
        # the full table on every device — the silent reshard
        bad = jax.jit(lambda t: t * 2.0,
                      out_shardings=NamedSharding(mesh, P()))
        record = ha.audit_compiled(bad.lower(table).compile())
        assert record["collectives"], \
            "mis-spec produced no collective — fixture broken"
        current = {"version": ha.MANIFEST_VERSION,
                   "devices": ha.AUDIT_DEVICE_COUNT,
                   "entries": {"serve_topk": record}}
        golden = {"version": ha.MANIFEST_VERSION,
                  "devices": ha.AUDIT_DEVICE_COUNT,
                  "entries": {"serve_topk": {"collectives": {},
                                             "temp_bytes":
                                                 record["temp_bytes"]}}}
        violations, _ = ha.diff_manifests(current, golden)
        assert violations, "the inserted collective must fail the gate"
        op = next(iter(record["collectives"]))
        assert any(op in v and "serve_topk" in v for v in violations)


class TestAuditCLI:
    def test_list_entries(self, capsys):
        assert main(["audit-hlo", "--list-entries"]) == 0
        out = capsys.readouterr().out
        assert "gramian_allreduce" in out and "sharded_rank" in out

    def test_unknown_entry_exits_2(self):
        assert main(["audit-hlo", "--entry", "nope"]) == 2

    def test_subset_against_committed_baseline(self, capsys,
                                               tmp_path):
        artifact = str(tmp_path / "audit.json")
        rc = main(["audit-hlo", "--entry", "gramian_allreduce",
                   "--format", "json", "--out", artifact])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["entries"]["gramian_allreduce"]["collectives"] == \
            {"all-reduce": 1}
        assert os.path.exists(artifact)

    def test_write_and_gate_roundtrip(self, tmp_path, capsys):
        path = str(tmp_path / "b.json")
        assert main(["audit-hlo", "--entry", "gather_rows",
                     "--baseline", path, "--write-baseline"]) == 0
        capsys.readouterr()
        assert main(["audit-hlo", "--entry", "gather_rows",
                     "--baseline", path]) == 0
