"""Sequential-recommendation template (causal self-attention next-item
prediction) — end-to-end through the DASE engine on real storage."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.seqrec import SeqRecParams
from predictionio_tpu.templates.sequential import (
    DataSourceParams,
    Query,
    sequential_engine,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(scope="module")
def seq_ctx():
    """Users walk an item cycle i → (i+1) % N — the learnable
    sequential structure (no co-occurrence signal can solve it: every
    item co-occurs with every other across users)."""
    storage = Storage(env={"PIO_STORAGE_SOURCES_M_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "seqapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(4)
    n_items = 24
    events = []
    t = T0
    for u in range(300):
        start = int(rng.integers(0, n_items))
        for j in range(int(rng.integers(6, 16))):
            events.append(Event(
                event="view", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"i{(start + j) % n_items}",
                event_time=t))
            t += timedelta(seconds=7)
    es.insert_batch(events, app_id)
    return Context(app_name="seqapp", _storage=storage)


def _train(ctx, **overrides):
    engine = sequential_engine()
    params = SeqRecParams(dim=32, heads=2, max_len=16, num_epochs=6,
                          batch_size=64, learning_rate=3e-3,
                          n_negatives=16, seed=2, **overrides)
    ep = EngineParams(
        datasource=("", DataSourceParams(app_name="seqapp",
                                         max_len=16)),
        algorithms=[("seqrec", params)])
    result = engine.train(ctx, ep)
    return engine, ep, result.models[0]


class TestSequentialTemplate:
    def test_learns_successor_structure(self, seq_ctx):
        engine, ep, model = _train(seq_ctx)
        algo = engine.make_algorithms(ep)[0]
        hits = 0
        for s in (3, 11, 19):
            pred = algo.predict(
                model, Query(items=(f"i{s}", f"i{s+1}", f"i{s+2}"),
                             num=3))
            assert pred.item_scores
            top = [x.item for x in pred.item_scores]
            assert f"i{s+2}" not in top  # history excluded
            if f"i{(s+3) % 24}" in top[:2]:
                hits += 1
        assert hits >= 2, "successor structure not learned"

    def test_user_query_reads_serving_history(self, seq_ctx):
        engine, ep, model = _train(seq_ctx)
        algo = engine.make_algorithms(ep)[0]
        algo.bind_serving(seq_ctx)
        pred = algo.predict(model, Query(user="u0", num=4))
        assert pred.item_scores
        scores = [s.score for s in pred.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_and_empty_history(self, seq_ctx):
        engine, ep, model = _train(seq_ctx)
        algo = engine.make_algorithms(ep)[0]
        algo.bind_serving(seq_ctx)
        assert algo.predict(model, Query(user="nobody")).item_scores == ()
        assert algo.predict(model, Query()).item_scores == ()

    def test_mesh_training_matches_shape(self, seq_ctx, mesh8):
        from predictionio_tpu.workflow import core as wf  # noqa: F401

        engine, ep, _ = _train(seq_ctx)
        # train again under the mesh through the same engine API
        ctx2 = Context(app_name="seqapp", _storage=seq_ctx.storage,
                       mesh=mesh8)
        result = engine.train(ctx2, ep)
        model = result.models[0]
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(items=("i5", "i6"), num=3))
        assert pred.item_scores


class TestSequentialEvaluation:
    def test_leave_one_out_hitrate(self, seq_ctx):
        from predictionio_tpu.controller.evaluation import (
            Evaluation,
            MetricEvaluator,
        )
        from predictionio_tpu.templates.sequential import (
            HitRateAtK,
            SeqNDCGAtK,
        )

        engine = sequential_engine()
        params = SeqRecParams(dim=32, heads=2, max_len=16, num_epochs=6,
                              batch_size=64, learning_rate=3e-3,
                              n_negatives=16, seed=2)
        ep = EngineParams(
            datasource=("", DataSourceParams(app_name="seqapp",
                                             max_len=16,
                                             eval_query_num=5)),
            algorithms=[("seqrec", params)])
        evaluation = Evaluation(
            engine=engine, metric=HitRateAtK(k=5),
            other_metrics=[SeqNDCGAtK(k=5)])
        result = MetricEvaluator(evaluation).evaluate(seq_ctx, [ep])
        best = result.best_score
        # cyclic successor data: the model should hit the next item in
        # the top-5 far more often than the 5/24 random baseline
        assert best > 0.5, result.to_one_liner()


class TestSequentialBatchPredict:
    def test_batch_matches_single(self, seq_ctx):
        engine, ep, model = _train(seq_ctx)
        algo = engine.make_algorithms(ep)[0]
        algo.bind_serving(seq_ctx)
        queries = [Query(items=("i3", "i4"), num=3),
                   Query(user="u1", num=2),
                   Query(user="nobody", num=2),
                   Query(items=("i9",), num=4)]
        batch = algo.batch_predict(model, queries)
        singles = [algo.predict(model, q) for q in queries]
        assert len(batch) == len(singles) == 4
        for b, s in zip(batch, singles):
            assert [x.item for x in b.item_scores] == \
                [x.item for x in s.item_scores]
        assert batch[2].item_scores == ()  # unknown user slot intact
