"""Streaming incremental training (ISSUE 10): the event→model loop.

Covers the subsystem bottom-up: the fold-in primitives in models/als
(dedupe, batched row solves, functional row swap, cold-start
insertion), the durable EVENTDATA cursor's exactly-once replay
contract, drift scoring, the coalesced bus publish, and — end to end —
a deployed QueryServer whose recommendations reflect freshly ingested
events within the fold-in interval, with restart-with-cursor replaying
exactly the unconsumed suffix.
"""

import time
import urllib.error
import urllib.request
import json as jsonlib
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.als import (
    ALSModel,
    ALSParams,
    apply_row_updates,
    dedupe_pairs,
    extend_factor_rows,
    fixed_gramian,
    fold_in_rows,
)
from predictionio_tpu.streaming import (
    CURSOR_ENTITY_TYPE,
    DriftMonitor,
    EventCursor,
    StreamConfig,
    StreamTrainer,
    fold_in_events,
    project_ratings,
)
from predictionio_tpu.cache.bus import InvalidationBus
from predictionio_tpu.templates.recommendation import (
    Query,
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import (
    get_latest_completed,
    load_models_for_deploy,
    run_train,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
RANK = 8


def _mem_storage(app_name="mlapp"):
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, app_name))
    storage.events().init(app_id)
    return storage, app_id


def _rate(user, item, rating, t):
    return Event(event="rate", entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 properties=DataMap({"rating": float(rating)}),
                 event_time=t)


def _seed_two_taste_groups(storage, app_id, n_users=30):
    """Group A (even users) likes items 0-14, group B likes 15-29."""
    rng = np.random.default_rng(42)
    events, t = [], T0
    for u in range(n_users):
        group = range(0, 15) if u % 2 == 0 else range(15, 30)
        for i in rng.choice(list(group), size=8, replace=False):
            events.append(_rate(f"u{u}", f"i{i}", 5.0, t))
            t += timedelta(minutes=1)
    storage.events().insert_batch(events, app_id)
    return t


def _toy_model(n_users=10, n_items=20, implicit=False, seed=0,
               **params_kw):
    rng = np.random.default_rng(seed)
    params = ALSParams(rank=RANK, reg=0.1, implicit_prefs=implicit,
                       scale_reg_by_count=False, **params_kw)
    return ALSModel(
        user_factors=rng.normal(size=(n_users, RANK)).astype(np.float32),
        item_factors=rng.normal(size=(n_items, RANK)).astype(np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=params)


# ---------------------------------------------------------------------------
# fold-in primitives (models/als.py)
# ---------------------------------------------------------------------------
class TestDedupePairs:
    def test_last_write_wins(self):
        r, c, v = dedupe_pairs(np.array([0, 0, 1, 0]),
                               np.array([5, 5, 2, 5]),
                               np.array([1.0, 2.0, 3.0, 4.0]))
        got = {(int(a), int(b)): float(x) for a, b, x in zip(r, c, v)}
        assert got == {(0, 5): 4.0, (1, 2): 3.0}

    def test_empty(self):
        r, c, v = dedupe_pairs(np.array([]), np.array([]), np.array([]))
        assert len(r) == len(c) == len(v) == 0

    def test_burst_does_not_multiply_implicit_weight(self):
        """REGRESSION (ISSUE 10 satellite): a burst of identical events
        must fold to the same row as a single event — without dedupe,
        each duplicate stacks another alpha*r of confidence into the
        implicit normal equations and skews the row."""
        model = _toy_model(implicit=True, alpha=4.0)
        G = fixed_gramian(model.item_factors, model.params)

        def solve(items, vals):
            i, v, n = (np.asarray(items, np.int32)[None, :],
                       np.asarray(vals, np.float32)[None, :],
                       np.array([len(items)], np.int32))
            return fold_in_rows(model.item_factors, i, v, n,
                                model.params, G=G)[0]

        once = solve([3], [1.0])
        # the deduped path: 5 identical events collapse to one pair
        rows, cols, vals = dedupe_pairs(
            np.zeros(5, np.int64), np.full(5, 3, np.int64),
            np.ones(5, np.float32))
        deduped = solve(cols, vals)
        np.testing.assert_allclose(deduped, once, rtol=1e-5)
        # and the counterfactual really differs (the bug was real)
        burst = solve([3] * 5, [1.0] * 5)
        assert np.abs(burst - once).max() > 1e-4


class TestFoldInRows:
    def test_matches_closed_form_explicit(self):
        model = _toy_model()
        V = np.asarray(model.item_factors)
        idx = np.array([[0, 1, 2]], np.int32)
        val = np.array([[5.0, 3.0, 1.0]], np.float32)
        out = fold_in_rows(V, idx, val, np.array([3], np.int32),
                           model.params)
        F = V[[0, 1, 2]]
        ref = np.linalg.solve(
            F.T @ F + model.params.reg * np.eye(RANK),
            F.T @ np.array([5.0, 3.0, 1.0], np.float32))
        np.testing.assert_allclose(out[0], ref, atol=1e-4)

    def test_padding_is_inert(self):
        """Rows in one batch must not contaminate each other, and the
        pow2 padding slots (index 0 / value 0 / masked) change
        nothing."""
        model = _toy_model()
        V = np.asarray(model.item_factors)
        idx = np.array([[0, 1, 2]], np.int32)
        val = np.array([[5.0, 3.0, 1.0]], np.float32)
        alone = fold_in_rows(V, idx, val, np.array([3], np.int32),
                             model.params)
        batch_idx = np.array([[0, 1, 2], [7, 8, 0]], np.int32)
        batch_val = np.array([[5.0, 3.0, 1.0], [2.0, 2.0, 0.0]],
                             np.float32)
        together = fold_in_rows(V, batch_idx, batch_val,
                                np.array([3, 2], np.int32), model.params)
        np.testing.assert_allclose(together[0], alone[0], atol=1e-5)

    def test_cached_gramian_equivalent(self):
        model = _toy_model(implicit=True, alpha=2.0)
        V = np.asarray(model.item_factors)
        idx = np.array([[4, 9]], np.int32)
        val = np.array([[1.0, 1.0]], np.float32)
        cnt = np.array([2], np.int32)
        G = fixed_gramian(V, model.params)
        np.testing.assert_allclose(
            fold_in_rows(V, idx, val, cnt, model.params, G=G),
            fold_in_rows(V, idx, val, cnt, model.params), atol=1e-6)

    def test_empty_batch(self):
        model = _toy_model()
        out = fold_in_rows(np.asarray(model.item_factors),
                           np.zeros((0, 1), np.int32),
                           np.zeros((0, 1), np.float32),
                           np.zeros(0, np.int32), model.params)
        assert out.shape == (0, RANK)


class TestRowUpdates:
    def test_apply_is_functional(self):
        model = _toy_model()
        before = np.asarray(model.user_factors).copy()
        rows = np.ones((2, RANK), np.float32)
        out = apply_row_updates(model, "user", np.array([1, 4]), rows)
        np.testing.assert_allclose(np.asarray(out.user_factors)[[1, 4]],
                                   rows)
        # the OLD model (possibly still serving) is untouched
        np.testing.assert_allclose(np.asarray(model.user_factors),
                                   before)
        # unrelated rows carried over
        np.testing.assert_allclose(np.asarray(out.user_factors)[0],
                                   before[0])

    def test_extend_claims_padding_then_grows(self):
        model = _toy_model()
        # pad the table as training does for even sharding
        padded = np.vstack([np.asarray(model.user_factors),
                            np.zeros((6, RANK), np.float32)])
        model = ALSModel(user_factors=padded,
                         item_factors=model.item_factors,
                         n_users=model.n_users, n_items=model.n_items,
                         user_ids=model.user_ids,
                         item_ids=model.item_ids, params=model.params)
        rows = np.full((2, RANK), 0.5, np.float32)
        out = extend_factor_rows(model, "user", ["ua", "ub"], rows)
        assert out.n_users == 12
        # padding rows were claimed — no reallocation
        assert out.user_factors.shape[0] == padded.shape[0]
        assert out.user_ids["ua"] == 10 and out.user_ids["ub"] == 11
        np.testing.assert_allclose(
            np.asarray(out.user_factors)[10:12], rows)
        # now exhaust capacity: growth kicks in, zero-padded
        many = [f"x{i}" for i in range(8)]
        out2 = extend_factor_rows(
            out, "user", many, np.ones((8, RANK), np.float32))
        assert out2.n_users == 20
        assert out2.user_factors.shape[0] >= 20

    def test_extend_rejects_known_key(self):
        model = _toy_model()
        with pytest.raises(ValueError, match="already indexed"):
            extend_factor_rows(model, "user", ["u3"],
                               np.ones((1, RANK), np.float32))


# ---------------------------------------------------------------------------
# event projection + fold_in_events
# ---------------------------------------------------------------------------
class TestProjection:
    def test_rate_buy_and_junk(self):
        t = T0
        evs = [
            _rate("u1", "i1", 4.0, t),
            Event(event="buy", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i2",
                  event_time=t),
            Event(event="view", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i3",
                  event_time=t),                      # not in weights
            Event(event="rate", entity_type="user", entity_id="u1",
                  event_time=t),                      # no target item
            Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i4",
                  properties=DataMap({"rating": "junk"}), event_time=t),
        ]
        assert project_ratings(evs) == [("u1", "i1", 4.0),
                                        ("u1", "i2", 4.0)]

    def test_custom_weights(self):
        ev = Event(event="view", entity_type="user", entity_id="u1",
                   target_entity_type="item", target_entity_id="i9",
                   event_time=T0)
        assert project_ratings([ev], {"view": 1.5}) == \
            [("u1", "i9", 1.5)]


class TestFoldInEvents:
    def _seeded(self):
        storage, app_id = _mem_storage()
        t = _seed_two_taste_groups(storage, app_id)
        return storage, app_id, t

    def test_idempotent_under_replay(self):
        """A row is a pure function of its full history: folding the
        SAME events twice lands on the same factors (what makes
        at-least-once cursor delivery effectively exactly-once)."""
        storage, app_id, t = self._seeded()
        model = _toy_model(n_users=30, n_items=30)
        evs = [_rate("u0", "i1", 5.0, t),
               _rate("u0", "i2", 4.0, t + timedelta(seconds=1))]
        storage.events().insert_batch(evs, app_id)
        m1, r1 = fold_in_events(model, evs, storage, app_id)
        m2, r2 = fold_in_events(m1, evs, storage, app_id)
        assert r1.users_updated == r2.users_updated == 1
        np.testing.assert_allclose(np.asarray(m1.user_factors),
                                   np.asarray(m2.user_factors),
                                   atol=1e-5)

    def test_cold_user_and_cold_item_in_one_pass(self):
        storage, app_id, t = self._seeded()
        model = _toy_model(n_users=30, n_items=30)
        evs = [_rate("brand_new_user", "brand_new_item", 5.0, t)]
        storage.events().insert_batch(evs, app_id)
        m, rep = fold_in_events(model, evs, storage, app_id)
        assert rep.users_inserted == 1 and rep.items_inserted == 1
        assert "brand_new_user" in m.user_ids
        assert "brand_new_item" in m.item_ids
        # both rows landed: the user's row was solved against a table
        # that already includes the new item
        assert m.n_users == 31 and m.n_items == 31

    def test_irrelevant_events_reported(self):
        storage, app_id, t = self._seeded()
        model = _toy_model(n_users=30, n_items=30)
        ev = Event(event="view", entity_type="user", entity_id="u0",
                   target_entity_type="item", target_entity_id="i1",
                   event_time=t)
        m, rep = fold_in_events(model, [ev], storage, app_id)
        assert rep.events_relevant == 0
        assert m is model


# ---------------------------------------------------------------------------
# the durable cursor
# ---------------------------------------------------------------------------
class TestEventCursor:
    def test_fresh_cursor_reads_whole_log(self):
        storage, app_id = _mem_storage()
        _seed_two_taste_groups(storage, app_id, n_users=4)
        cur = EventCursor(storage, app_id, "c1")
        pend = cur.pending(event_names=["rate"], entity_type="user")
        assert len(pend) == 32
        # oldest first — the fold-in consumes in event order
        times = [e.event_time for e in pend]
        assert times == sorted(times)

    def test_restart_replays_exactly_unconsumed_suffix(self):
        storage, app_id = _mem_storage()
        _seed_two_taste_groups(storage, app_id, n_users=4)
        cur = EventCursor(storage, app_id, "c1")
        first = cur.pending(event_names=["rate"], entity_type="user",
                            limit=20)
        cur.advance(first)
        cur.save()
        # crash + restart: a NEW cursor object, same consumer
        cur2 = EventCursor(storage, app_id, "c1")
        assert cur2.consumed_total == 20
        rest = cur2.pending(event_names=["rate"], entity_type="user")
        assert len(rest) == 12  # no loss...
        first_ids = {e.event_id for e in first}
        assert not (first_ids & {e.event_id for e in rest})  # no double

    def test_timestamp_ties(self):
        """Events sharing one timestamp consume one at a time without
        loss or double-apply (the seen-set tie-break)."""
        storage, app_id = _mem_storage()
        for j in range(3):
            storage.events().insert(_rate(f"u{j}", "i0", 3.0, T0),
                                    app_id)
        cur = EventCursor(storage, app_id, "c1")
        seen_users = []
        for _ in range(3):
            batch = cur.pending(event_names=["rate"],
                                entity_type="user", limit=1)
            assert len(batch) == 1
            seen_users.append(batch[0].entity_id)
            cur.advance(batch)
            cur.save()
            cur = EventCursor(storage, app_id, "c1")  # restart each time
        assert sorted(seen_users) == ["u0", "u1", "u2"]
        assert cur.pending(event_names=["rate"],
                           entity_type="user") == []

    def test_cursor_records_never_consumed(self):
        storage, app_id = _mem_storage()
        storage.events().insert(_rate("u0", "i0", 3.0, T0), app_id)
        cur = EventCursor(storage, app_id, "c1")
        cur.advance(cur.pending(limit=10))
        cur.save()
        # the cursor record itself (entity_type pio_stream, epoch
        # event_time) must not appear in any consumer's pending scan
        cur2 = EventCursor(storage, app_id, "other-consumer")
        pend = cur2.pending(limit=100)
        assert all(e.entity_type != CURSOR_ENTITY_TYPE for e in pend)
        assert len(pend) == 1

    def test_corrupt_cursor_restarts_from_log_start(self):
        storage, app_id = _mem_storage()
        storage.events().insert(_rate("u0", "i0", 3.0, T0), app_id)
        cur = EventCursor(storage, app_id, "c1")
        cur.advance(cur.pending(limit=10))
        cur.save()
        storage.events().insert(
            Event(event="$set", entity_type=CURSOR_ENTITY_TYPE,
                  entity_id="c1", properties=DataMap({"garbage": True}),
                  event_time=datetime(1970, 1, 1, tzinfo=timezone.utc),
                  event_id=cur.cursor_event_id), app_id)
        cur3 = EventCursor(storage, app_id, "c1")
        assert len(cur3.pending(limit=10)) == 1  # re-reads the log


# ---------------------------------------------------------------------------
# drift
# ---------------------------------------------------------------------------
class TestDriftMonitor:
    def test_healthy_stream_stays_quiet(self):
        d = DriftMonitor(threshold=1.0, baseline_min_samples=32)
        rng = np.random.default_rng(0)
        for _ in range(20):
            d.observe(list(rng.normal(4.0, 0.5, size=16)), 0.05)
        assert d.score() < 1.0 and not d.retrain_due

    def test_distribution_shift_triggers(self):
        d = DriftMonitor(threshold=1.0, baseline_min_samples=32,
                         window=64)
        for _ in range(4):
            d.observe([4.0 + 0.1 * i for i in range(16)], 0.05)
        for _ in range(8):
            d.observe([1.0] * 16, 0.05)  # ratings collapsed
        assert d.shift_score() > 1.0 and d.retrain_due

    def test_rising_residual_triggers(self):
        d = DriftMonitor(threshold=1.0, residual_scale=0.5,
                         residual_halflife=2)
        for _ in range(12):
            d.observe([4.0], 2.0)  # solves stopped explaining events
        assert d.residual_score() > 1.0 and d.retrain_due

    def test_reset_on_new_base(self):
        d = DriftMonitor(threshold=1.0, residual_halflife=2)
        for _ in range(12):
            d.observe([4.0], 2.0)
        assert d.retrain_due
        d.reset()
        assert d.score() == 0.0 and not d.retrain_due


# ---------------------------------------------------------------------------
# coalesced bus publish (ISSUE 10 satellite)
# ---------------------------------------------------------------------------
class TestPublishMany:
    def test_per_item_delivery_and_stats(self):
        bus = InvalidationBus()
        got = []

        class Sub:
            def on_event(self, app_id, et, eid, name=""):
                got.append((app_id, et, eid, name))

        sub = Sub()
        bus.subscribe(sub)
        n = bus.publish_many(7, [("user", "u1", "rate"),
                                 ("user", "u2", "buy")])
        assert n == 2
        assert got == [(7, "user", "u1", "rate"),
                       (7, "user", "u2", "buy")]
        st = bus.stats()
        assert st["published"] == 2 and st["delivered"] == 2

    def test_empty_and_dead_ref(self):
        bus = InvalidationBus()
        assert bus.publish_many(1, []) == 0

        class Sub:
            def on_event(self, *a, **k):
                pass

        sub = Sub()
        bus.subscribe(sub)
        del sub
        import gc
        gc.collect()
        assert bus.publish_many(1, [("user", "u", "rate")]) == 0
        assert bus.subscriber_count() == 0

    def test_batch_ingest_publishes_coalesced(self):
        """The event server's batch route delivers every accepted
        event to bus subscribers (via ONE publish_many)."""
        from predictionio_tpu.server.eventserver import build_app
        from predictionio_tpu.server.http import AppServer
        from predictionio_tpu.data.storage.base import AccessKey

        storage, app_id = _mem_storage("busapp")
        storage.access_keys().insert(
            AccessKey(key="k1", app_id=app_id, events=[]))
        bus = InvalidationBus()
        got = []

        class Sub:
            def on_event(self, app_id, et, eid, name=""):
                got.append((et, eid, name))

        sub = Sub()
        bus.subscribe(sub)
        srv = AppServer(build_app(storage, bus=bus), "127.0.0.1",
                        0).start_background()
        try:
            body = jsonlib.dumps([
                {"event": "rate", "entityType": "user", "entityId": "u1",
                 "targetEntityType": "item", "targetEntityId": "i1",
                 "properties": {"rating": 5}},
                {"event": "buy", "entityType": "user", "entityId": "u2",
                 "targetEntityType": "item", "targetEntityId": "i2"},
            ]).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/batch/events.json?"
                f"accessKey=k1", data=body, method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                results = jsonlib.loads(resp.read())
            assert [r["status"] for r in results] == [201, 201]
            assert ("user", "u1", "rate") in got
            assert ("user", "u2", "buy") in got
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# the trainer against a live QueryServer
# ---------------------------------------------------------------------------
def _deploy(storage, app_id, serving_cache=False):
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
    )

    ctx = Context(app_name="mlapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("mlapp", rank=RANK, num_iterations=6,
                               reg=0.05, seed=11)
    run_train(ctx, engine, ep, engine_id="reco",
              engine_factory="templates.recommendation")
    inst = get_latest_completed(ctx, engine_id="reco")
    models = load_models_for_deploy(ctx, engine, inst, ep)
    qs = QueryServer(ctx, engine, ep, models, inst,
                     ServerConfig(serving_cache=serving_cache,
                                  warm_start=False))
    return qs


@pytest.fixture(scope="module")
def deployed():
    storage, app_id = _mem_storage()
    t_end = _seed_two_taste_groups(storage, app_id)
    qs = _deploy(storage, app_id, serving_cache=True)
    return storage, app_id, qs, t_end


class TestStreamTrainer:
    def _trainer(self, qs, **kw):
        kw.setdefault("canary_probes", 2)
        kw.setdefault("interval_ms", 50)
        return StreamTrainer(qs, StreamConfig(app_name="mlapp", **kw),
                             bus=InvalidationBus())

    def test_event_to_servable(self, deployed):
        """The headline contract: a new user's events become servable
        recommendations through one fold-in pass, with lineage,
        metrics and cursor all advancing."""
        storage, app_id, qs, t = deployed
        tr = self._trainer(qs, consumer="t-servable")
        tr.consume_once()  # drain the seed log
        gen0 = qs.stream_lineage()["incrementalGeneration"]
        t = t + timedelta(seconds=1)
        for k, i in enumerate((0, 1, 2, 3, 4)):  # group-A taste
            storage.events().insert(
                _rate("u_fresh", f"i{i}", 5.0,
                      t + timedelta(seconds=k)), app_id)
        n = tr.consume_once()
        assert n == 5
        lin = qs.stream_lineage()
        assert lin["incrementalGeneration"] == gen0 + 1
        assert lin["baseInstanceId"] == qs.instance.id
        assert lin["stalenessSec"] < 60
        # the model the server now serves knows u_fresh
        _, model = qs.stream_snapshot(0)
        algo = qs.algorithms[0]
        pred = algo.predict(model, Query(user="u_fresh", num=5))
        tops = [int(s.item[1:]) for s in pred.item_scores]
        assert sum(1 for i in tops if i < 15) >= 4, tops
        assert tr.status()["lastBatch"]["usersInserted"] == 1

    def test_restart_replays_unconsumed_suffix_once(self, deployed):
        storage, app_id, qs, t = deployed
        tr = self._trainer(qs, consumer="t-restart")
        tr.consume_once()
        t = t + timedelta(minutes=5)
        for k in range(3):
            storage.events().insert(
                _rate("u_replay", f"i{k}", 5.0,
                      t + timedelta(seconds=k)), app_id)
        # crash BEFORE consuming: a fresh trainer (same consumer)
        # picks up exactly the 3 events, exactly once
        tr2 = self._trainer(qs, consumer="t-restart")
        assert tr2.consume_once() == 3
        assert tr2.consume_once() == 0  # nothing replays twice

    def test_rebind_race_aborts_apply(self, deployed):
        """An apply against a stale base instance id must refuse (the
        reload/promote won; the cursor retries against the new base)."""
        storage, app_id, qs, t = deployed
        _, model = qs.stream_snapshot(0)
        assert qs.apply_stream_delta(0, model, ["u0"],
                                     "some-stale-instance") is False
        assert qs.apply_stream_delta(
            0, model, ["u0"], qs.instance.id) is True

    def test_canary_gate_rejects_bad_delta(self, deployed):
        """A delta the probe gate refuses must not reach the binding —
        but the cursor still advances (re-solving yields the same
        rows) and the reject is counted."""
        from predictionio_tpu.rollout.policy import Decision

        storage, app_id, qs, t = deployed
        tr = self._trainer(qs, consumer="t-reject")
        tr.consume_once()
        gen0 = qs.stream_lineage()["incrementalGeneration"]
        tr.policy = type(tr.policy)(min_queries=1)
        tr._canary_check = lambda *a, **k: Decision(
            "rollback", "forced by test")
        t = t + timedelta(minutes=10)
        storage.events().insert(_rate("u2", "i3", 1.0, t), app_id)
        n = tr.consume_once()
        assert n == 1
        assert tr.rejects == 1
        assert qs.stream_lineage()["incrementalGeneration"] == gen0
        assert tr.consume_once() == 0  # consumed despite the reject

    def test_fold_in_invalidates_touched_cache_entries(self, deployed):
        storage, app_id, qs, t = deployed
        from predictionio_tpu.cache import canonical_key

        tr = self._trainer(qs, consumer="t-cache")
        tr.consume_once()
        # prime the query cache for u4 and an untouched user u6
        r_before = qs.serve({"user": "u4", "num": 3})
        qs.serve({"user": "u6", "num": 3})
        key4 = (qs.instance.id, canonical_key({"user": "u4", "num": 3}))
        key6 = (qs.instance.id, canonical_key({"user": "u6", "num": 3}))
        assert qs.cache.query.lookup(key4)[0]
        assert qs.cache.query.lookup(key6)[0]
        t = t + timedelta(minutes=20)
        storage.events().insert(_rate("u4", "i20", 5.0, t), app_id)
        assert tr.consume_once() == 1
        found4, _ = qs.cache.query.lookup(key4)
        found6, _ = qs.cache.query.lookup(key6)
        assert not found4   # touched entity: invalidated
        assert found6       # untouched entity: still cached

    def test_drift_fires_retrain_hook_once(self, deployed):
        storage, app_id, qs, t = deployed
        fired = []
        tr = StreamTrainer(
            qs, StreamConfig(app_name="mlapp", consumer="t-drift",
                             canary_probes=0, drift_threshold=0.5),
            bus=InvalidationBus(), on_retrain=fired.append)
        tr.consume_once()
        # poison the drift monitor directly (unit-scale residuals)
        for _ in range(12):
            tr.drift.observe([4.0], 5.0)
        t = t + timedelta(minutes=30)
        storage.events().insert(_rate("u8", "i1", 4.0, t), app_id)
        tr.consume_once()
        assert len(fired) == 1 and fired[0]["retrainDue"]
        # a second pass does NOT re-fire for the same base
        storage.events().insert(
            _rate("u8", "i2", 4.0, t + timedelta(seconds=1)), app_id)
        tr.consume_once()
        assert len(fired) == 1

    def test_bus_wake_and_threaded_loop(self, deployed):
        """The daemon loop: a bus publish wakes it and the fold-in
        lands within the freshness budget, no manual consume calls."""
        storage, app_id, qs, t = deployed
        bus = InvalidationBus()
        tr = StreamTrainer(
            qs, StreamConfig(app_name="mlapp", consumer="t-loop",
                             canary_probes=0, interval_ms=10_000),
            bus=bus)
        try:
            tr.start()
            deadline = time.monotonic() + 30
            while tr.applies == 0 and time.monotonic() < deadline:
                time.sleep(0.05)  # initial catch-up drain
            applies0 = tr.applies
            t = t + timedelta(minutes=40)
            storage.events().insert(
                _rate("u_woken", "i1", 5.0, t), app_id)
            bus.publish(app_id, "user", "u_woken", "rate")
            deadline = time.monotonic() + 30
            while tr.applies == applies0 and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            # woken by the bus, NOT the (10s) poll: the fold landed
            assert tr.applies > applies0
            _, model = qs.stream_snapshot(0)
            assert "u_woken" in model.user_ids
        finally:
            tr.stop()
        assert not tr.running


class TestServerStreamRoutes:
    def test_http_lifecycle_and_freshness(self):
        """ISSUE 10 acceptance: over real HTTP — start the stream,
        ingest, and watch /queries.json reflect the events within the
        fold-in interval; /status.json carries lineage + stream."""
        from predictionio_tpu.server.engineserver import (
            create_engine_server,
        )

        storage, app_id = _mem_storage()
        t = _seed_two_taste_groups(storage, app_id)
        qs = _deploy(storage, app_id)
        srv = create_engine_server(qs, "127.0.0.1", 0).start_background()

        def call(method, path, body=None):
            data = (jsonlib.dumps(body).encode()
                    if body is not None
                    else (b"" if method == "POST" else None))
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}{path}", data=data,
                method=method)
            with urllib.request.urlopen(req, timeout=30) as resp:
                return jsonlib.loads(resp.read())

        try:
            # stream.json before start: off, with lineage
            st = call("GET", "/stream.json")
            assert st["running"] is False
            assert st["lineage"]["incrementalGeneration"] == 0
            resp = call("POST", "/stream/start",
                        {"appName": "mlapp", "intervalMs": 50,
                         "canaryProbes": 2})
            assert "started" in resp["message"].lower()
            t0 = time.monotonic()
            # ingest straight into the store (the event server's bus
            # is a separate process in production; the poll covers it)
            t = t + timedelta(seconds=1)
            for k, i in enumerate((0, 1, 2, 3, 4)):
                storage.events().insert(
                    _rate("u_http", f"i{i}", 5.0,
                          t + timedelta(seconds=k)), app_id)
            deadline = time.monotonic() + 30
            tops = []
            while time.monotonic() < deadline:
                got = call("POST", "/queries.json",
                           {"user": "u_http", "num": 5})
                tops = [int(s["item"][1:]) for s in got["itemScores"]]
                if len(tops) == 5:
                    break
                time.sleep(0.1)
            servable_sec = time.monotonic() - t0
            assert len(tops) == 5, "events never became servable"
            assert sum(1 for i in tops if i < 15) >= 4, tops
            assert servable_sec < 30
            status = call("GET", "/status.json")
            assert status["lineage"]["incrementalGeneration"] >= 1
            assert status["stream"]["running"] is True
            assert status["stream"]["appName"] == "mlapp"
            # metrics exported
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics",
                    timeout=30) as resp:
                text = resp.read().decode()
            assert "pio_stream_events_consumed_total" in text
            assert "pio_stream_freshness_seconds" in text
            assert "pio_stream_cursor_lag" in text
            # double-start → 409
            try:
                call("POST", "/stream/start", {"appName": "mlapp"})
                raised = None
            except urllib.error.HTTPError as e:
                raised = e.code
            assert raised == 409
            assert call("POST", "/stream/stop")["message"]
            assert call("GET", "/stream.json")["running"] is False
        finally:
            qs.stop_stream()
            srv.shutdown()

    def test_streaming_deploy_config_fails_fast_without_app(self):
        from predictionio_tpu.server.engineserver import ServerConfig

        storage, app_id = _mem_storage()
        _seed_two_taste_groups(storage, app_id, n_users=6)
        ctx = Context(app_name="mlapp", _storage=storage)
        engine = recommendation_engine()
        ep = default_engine_params("mlapp", rank=RANK,
                                   num_iterations=4, seed=11)
        run_train(ctx, engine, ep, engine_id="reco",
                  engine_factory="templates.recommendation")
        inst = get_latest_completed(ctx, engine_id="reco")
        models = load_models_for_deploy(ctx, engine, inst, ep)
        from predictionio_tpu.server.engineserver import QueryServer

        with pytest.raises(ValueError, match="app name"):
            QueryServer(ctx, engine, ep, models, inst,
                        ServerConfig(streaming=True, warm_start=False))
