"""Columnar bulk-read path (the PEvents analogue): encode/select/shard
equivalence with the row path, the on-disk segment sidecar, and the
SQLite-backed delta sync."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.columnar import (
    ColumnarBatch,
    ColumnarDicts,
    SegmentLog,
    StringDict,
    columnar_from_events,
)
from predictionio_tpu.data.storage import App, EventFilter, Storage
from predictionio_tpu.data.store import EventStoreFacade
from predictionio_tpu.models.data import (
    ratings_from_columnar,
    ratings_from_events,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


def synth_events(n=500, seed=0):
    rng = np.random.default_rng(seed)
    events = []
    for k in range(n):
        kind = rng.integers(0, 4)
        t = T0 + timedelta(seconds=int(rng.integers(0, 100000)))
        if kind == 0:
            events.append(Event(
                event="rate", entity_type="user",
                entity_id=f"u{rng.integers(0, 40)}",
                target_entity_type="item",
                target_entity_id=f"i{rng.integers(0, 30)}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
                event_time=t))
        elif kind == 1:
            events.append(Event(
                event="buy", entity_type="user",
                entity_id=f"u{rng.integers(0, 40)}",
                target_entity_type="item",
                target_entity_id=f"i{rng.integers(0, 30)}", event_time=t))
        elif kind == 2:
            events.append(Event(
                event="$set", entity_type="item",
                entity_id=f"i{rng.integers(0, 30)}",
                properties=DataMap({"categories": ["c1"],
                                    "price": float(rng.integers(1, 50))}),
                event_time=t))
        else:
            events.append(Event(
                event="view", entity_type="user",
                entity_id=f"u{rng.integers(0, 40)}",
                target_entity_type="item",
                target_entity_id=f"i{rng.integers(0, 30)}", event_time=t))
    return events


def proj(e: Event):
    """The columnar projection of an event (no ids/tags/prId)."""
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, tuple(sorted(e.properties.to_dict().items(),
                                             key=str)), e.event_time_millis)


class TestColumnarBatch:
    def test_roundtrip(self):
        events = synth_events(300)
        batch = columnar_from_events(events)
        back = list(batch.to_events())
        assert [proj(e) for e in back] == [proj(e) for e in events]

    def test_select_matches_row_filter(self):
        events = synth_events(400, seed=1)
        batch = columnar_from_events(events)
        filters = [
            EventFilter(event_names=["rate", "buy"]),
            EventFilter(entity_type="user", target_entity_type="item"),
            EventFilter(entity_id="u3"),
            EventFilter(start_time=T0 + timedelta(seconds=20000),
                        until_time=T0 + timedelta(seconds=80000)),
            EventFilter(target_entity_type=None),
            EventFilter(target_entity_id="i7"),
            EventFilter(event_names=["rate"], reversed=True, limit=5),
        ]
        for f in filters:
            rows = [e for e in events if f.matches(e)]
            rows.sort(key=lambda e: e.event_time_millis,
                      reverse=f.reversed)
            if f.limit is not None:
                rows = rows[: f.limit]
            got = list(batch.select(f).to_events())
            assert [proj(e) for e in got] == [proj(e) for e in rows], f

    def test_unknown_filter_values_match_nothing(self):
        batch = columnar_from_events(synth_events(50))
        assert batch.select(EventFilter(entity_id="nope")).n == 0
        assert batch.select(EventFilter(event_names=["ghost"])).n == 0

    def test_shards_cover_everything(self):
        batch = columnar_from_events(synth_events(101))
        parts = [batch.shard(i, 4) for i in range(4)]
        assert sum(p.n for p in parts) == batch.n
        merged = ColumnarBatch.concat(parts)
        assert [proj(e) for e in merged.to_events()] \
            == [proj(e) for e in batch.to_events()]

    def test_float_prop_extracted_and_lazy(self):
        events = synth_events(200, seed=2)
        batch = columnar_from_events(events, float_props=("rating",))
        col = batch.float_props["rating"]
        for i, e in enumerate(events):
            want = e.properties.to_dict().get("rating")
            if want is None:
                assert np.isnan(col[i])
            else:
                assert col[i] == want
        # a prop not extracted at encode time parses lazily from the blob
        price = batch.float_prop("price")
        for i, e in enumerate(events):
            want = e.properties.to_dict().get("price")
            assert (np.isnan(price[i]) if want is None
                    else price[i] == want)

    def test_string_dict_stable_codes(self):
        sd = StringDict()
        a = sd.encode(["x", "y", "x", None])
        b = sd.encode(["z", "y"])
        assert a.tolist() == [0, 1, 0, -1]
        assert b.tolist() == [2, 1]
        assert sd.values == ["x", "y", "z"]


class TestRatingsFromColumnar:
    def trips(self, coo, user_ids, item_ids):
        inv_u, inv_i = user_ids.inverse, item_ids.inverse
        return sorted((inv_u[int(u)], inv_i[int(i)], float(v))
                      for u, i, v in zip(coo.users, coo.items, coo.ratings))

    def test_matches_row_path(self):
        events = [e for e in synth_events(600, seed=3)
                  if e.event in ("rate", "buy", "view")]
        events.sort(key=lambda e: e.event_time_millis)
        batch = columnar_from_events(events)
        for weights in (None, {"rate": None, "buy": 4.0, "view": 1.0},
                        {"view": 1.0}):
            coo_r, u_r, i_r = ratings_from_events(
                iter(events), event_weights=weights)
            coo_c, u_c, i_c = ratings_from_columnar(
                batch, event_weights=weights)
            assert self.trips(coo_c, u_c, i_c) \
                == self.trips(coo_r, u_r, i_r), weights
            assert set(u_c.keys()) == set(u_r.keys())
            assert set(i_c.keys()) == set(i_r.keys())

    def test_fixed_bimaps_drop_unknowns(self):
        from predictionio_tpu.data.bimap import BiMap

        events = [Event(event="buy", entity_type="user", entity_id=u,
                        target_entity_type="item", target_entity_id=i,
                        event_time=T0)
                  for u, i in [("a", "x"), ("b", "y"), ("c", "x")]]
        batch = columnar_from_events(events)
        user_ids = BiMap({"a": 0, "b": 1})
        item_ids = BiMap({"x": 0})
        coo, _, _ = ratings_from_columnar(batch, user_ids=user_ids,
                                          item_ids=item_ids)
        assert self.trips(coo, user_ids, item_ids) == [("a", "x", 4.0)]


class TestSegmentLog:
    def test_append_load_roundtrip(self, tmp_path):
        events = synth_events(250, seed=4)
        dicts = ColumnarDicts()
        b1 = columnar_from_events(events[:100], dicts)
        log = SegmentLog(str(tmp_path / "log"))
        log.append(b1, watermark=100, prev_dict_counts={})
        counts = dicts.counts()
        b2 = columnar_from_events(events[100:], dicts)
        log.append(b2, watermark=250, prev_dict_counts=counts)
        loaded, manifest = log.load()
        assert manifest["count"] == 250
        assert manifest["watermark"] == 250
        assert [proj(e) for e in loaded.to_events()] \
            == [proj(e) for e in events]

    def test_dict_values_with_newlines_and_backslashes(self, tmp_path):
        dicts = ColumnarDicts()
        weird = ["a\nb", "c\\n", "d\\", "plain"]
        events = [Event(event="buy", entity_type="user", entity_id=w,
                        target_entity_type="item", target_entity_id="i",
                        event_time=T0) for w in weird]
        log = SegmentLog(str(tmp_path / "log"))
        log.append(columnar_from_events(events, dicts), watermark=4,
                   prev_dict_counts={})
        loaded, _ = log.load()
        assert [e.entity_id for e in loaded.to_events()] == weird

    def test_invalidate(self, tmp_path):
        log = SegmentLog(str(tmp_path / "log"))
        log.append(columnar_from_events(synth_events(20)), watermark=20,
                   prev_dict_counts={})
        log.invalidate()
        batch, manifest = log.load()
        assert batch is None and manifest is None


@pytest.fixture
def sq(tmp_path):
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
    })
    app_id = storage.apps().insert(App(0, "colapp"))
    storage.events().init(app_id)
    return storage, app_id


class TestSQLiteSidecar:
    def check_matches_rows(self, storage, app_id):
        es = storage.events()
        rows = sorted(proj(e) for e in es.find(app_id))
        cols = sorted(proj(e) for e in
                      es.find_columnar(app_id).to_events())
        assert cols == rows

    def test_sync_delta_and_cache(self, sq, tmp_path):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch(synth_events(120, seed=5), app_id)
        self.check_matches_rows(storage, app_id)
        sidecar = tmp_path / "pio.db.columnar"
        assert sidecar.is_dir()
        n_segs = len(list(sidecar.glob("*/seg-*")))
        # new events -> one more segment, not a rebuild
        es.insert_batch(synth_events(30, seed=6), app_id)
        self.check_matches_rows(storage, app_id)
        assert len(list(sidecar.glob("*/seg-*"))) == n_segs + 1

    def test_delete_invalidates(self, sq):
        storage, app_id = sq
        es = storage.events()
        ids = es.insert_batch(synth_events(50, seed=7), app_id)
        self.check_matches_rows(storage, app_id)
        es.delete(ids[3], app_id)
        self.check_matches_rows(storage, app_id)

    def test_replace_invalidates(self, sq):
        storage, app_id = sq
        es = storage.events()
        ids = es.insert_batch(synth_events(50, seed=8), app_id)
        self.check_matches_rows(storage, app_id)
        # INSERT OR REPLACE of an existing id rewrites history
        es.insert(Event(event="buy", entity_type="user", entity_id="uX",
                        target_entity_type="item", target_entity_id="iX",
                        event_time=T0, event_id=ids[0]), app_id)
        self.check_matches_rows(storage, app_id)

    def test_fresh_process_reuses_segments(self, sq, tmp_path):
        storage, app_id = sq
        storage.events().insert_batch(synth_events(80, seed=9), app_id)
        self.check_matches_rows(storage, app_id)
        # a second client (fresh process role) must load, not re-encode
        cold = Storage(env={
            "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQ_PATH": str(tmp_path / "pio.db"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
        })
        sidecar = tmp_path / "pio.db.columnar"
        segs_before = sorted(str(p) for p in sidecar.glob("*/seg-*"))
        self.check_matches_rows(cold, app_id)
        assert sorted(str(p) for p in sidecar.glob("*/seg-*")) \
            == segs_before

    def test_rating_prop_pushed_down(self, sq):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch(synth_events(60, seed=10), app_id)
        batch = es.find_columnar(app_id)
        assert "rating" in batch.float_props  # json_extract path
        coo_c, u_c, i_c = ratings_from_columnar(
            batch.select(EventFilter(event_names=["rate", "buy"],
                                     entity_type="user")))
        coo_r, u_r, i_r = ratings_from_events(
            es.find(app_id, filter=EventFilter(
                event_names=["rate", "buy"], entity_type="user")))
        t = TestRatingsFromColumnar()
        assert t.trips(coo_c, u_c, i_c) == t.trips(coo_r, u_r, i_r)

    def test_facade_find_columnar(self, sq):
        storage, app_id = sq
        storage.events().insert_batch(synth_events(40, seed=11), app_id)
        fac = EventStoreFacade(storage)
        batch = fac.find_columnar("colapp", entity_type="user",
                                  target_entity_type="item",
                                  event_names=["rate", "buy"])
        rows = list(fac.find("colapp", entity_type="user",
                             target_entity_type="item",
                             event_names=["rate", "buy"]))
        assert sorted(proj(e) for e in batch.to_events()) \
            == sorted(proj(e) for e in rows)


class TestMemoryFallback:
    def test_memory_backend_columnar(self):
        storage = Storage(env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        app_id = storage.apps().insert(App(0, "memapp"))
        es = storage.events()
        es.init(app_id)
        es.insert_batch(synth_events(70, seed=12), app_id)
        rows = sorted(proj(e) for e in es.find(app_id))
        cols = sorted(proj(e) for e in
                      es.find_columnar(app_id).to_events())
        assert cols == rows


class TestColumnarAggregation:
    def test_matches_row_aggregation(self, sq):
        from datetime import timedelta

        storage, app_id = sq
        es = storage.events()
        events = synth_events(300, seed=13)
        # add $unset/$delete traffic so every op type is exercised
        events += [
            Event(event="$unset", entity_type="item", entity_id="i1",
                  properties=DataMap({"price": None}),
                  event_time=T0 + timedelta(days=40)),
            Event(event="$delete", entity_type="item", entity_id="i2",
                  event_time=T0 + timedelta(days=41)),
            Event(event="$set", entity_type="item", entity_id="i2",
                  properties=DataMap({"price": 9.0}),
                  event_time=T0 + timedelta(days=42)),
        ]
        es.insert_batch(events, app_id)
        from predictionio_tpu.data.aggregation import (
            AGGREGATION_EVENTS,
            aggregate_properties,
        )
        rows = aggregate_properties(es.find(app_id, None, EventFilter(
            entity_type="item", event_names=list(AGGREGATION_EVENTS))))
        cols = es.aggregate_properties(app_id, entity_type="item")
        assert set(cols) == set(rows)
        for k in rows:
            assert cols[k].to_dict() == rows[k].to_dict()
            assert cols[k].first_updated == rows[k].first_updated
            assert cols[k].last_updated == rows[k].last_updated


class TestSeqWatermarkSoundness:
    """AUTOINCREMENT seq vs SQLite rowid reuse (review r2 finding): a
    delete-then-reinsert at the old max rowid must not fool the sidecar
    into serving stale events."""

    def test_replace_newest_row_is_seen(self, sq):
        storage, app_id = sq
        es = storage.events()
        ids = es.insert_batch(synth_events(30, seed=20), app_id)
        _ = es.find_columnar(app_id)  # sync at watermark
        # REPLACE the newest row: old schema would reuse its rowid and the
        # prefix count would look unchanged
        es.insert(Event(event="buy", entity_type="user",
                        entity_id="replaced", target_entity_type="item",
                        target_entity_id="X", event_time=T0,
                        event_id=ids[-1]), app_id)
        got = {e.entity_id for e in es.find_columnar(app_id).to_events()}
        assert "replaced" in got

    def test_delete_then_insert_at_tail(self, sq):
        storage, app_id = sq
        es = storage.events()
        ids = es.insert_batch(synth_events(20, seed=21), app_id)
        _ = es.find_columnar(app_id)
        es.delete(ids[-1], app_id)
        es.insert(Event(event="buy", entity_type="user",
                        entity_id="fresh", target_entity_type="item",
                        target_entity_id="Y", event_time=T0), app_id)
        rows = sorted(proj(e) for e in es.find(app_id))
        cols = sorted(proj(e) for e in
                      es.find_columnar(app_id).to_events())
        assert cols == rows

    def test_legacy_rowid_table_migrates(self, tmp_path):
        import sqlite3 as s3

        db = str(tmp_path / "legacy.db")
        conn = s3.connect(db)
        conn.execute("""
            CREATE TABLE events_1 (
                id TEXT PRIMARY KEY, event TEXT NOT NULL,
                entity_type TEXT NOT NULL, entity_id TEXT NOT NULL,
                target_entity_type TEXT, target_entity_id TEXT,
                properties TEXT, event_time INTEGER NOT NULL,
                tags TEXT, pr_id TEXT, creation_time INTEGER NOT NULL)""")
        conn.execute(
            "INSERT INTO events_1 VALUES ('e1','rate','user','u0','item',"
            "'i0','{\"rating\": 3.0}',1760000000000,'[]',NULL,"
            "1760000000000)")
        conn.commit()
        conn.close()
        storage = Storage(env={
            "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQ_PATH": db,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
        })
        es = storage.events()
        batch = es.find_columnar(1)  # triggers migration
        assert batch.n == 1
        assert list(batch.to_events())[0].entity_id == "u0"
        # old data + new writes coexist after migration
        es.insert(Event(event="buy", entity_type="user", entity_id="u1",
                        target_entity_type="item", target_entity_id="i1",
                        event_time=T0), app_id=1)
        assert es.find_columnar(1).n == 2

    def test_non_numeric_rating_not_coerced(self, sq):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch([
            Event(event="rate", entity_type="user", entity_id="u0",
                  target_entity_type="item", target_entity_id="i0",
                  properties=DataMap({"rating": 4.0}), event_time=T0),
            Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"rating": "N/A"}), event_time=T0),
            Event(event="rate", entity_type="user", entity_id="u2",
                  target_entity_type="item", target_entity_id="i2",
                  properties=DataMap({"rating": True}), event_time=T0),
        ], app_id)
        batch = es.find_columnar(app_id)
        col = batch.float_props["rating"]
        by_user = {batch.dicts.entity_ids.values[batch.entity_id[i]]:
                   col[i] for i in range(batch.n)}
        assert by_user["u0"] == 4.0
        assert np.isnan(by_user["u1"])  # string must NOT become 0.0
        assert np.isnan(by_user["u2"])  # bool must NOT become 1.0


class TestPropsDeferredSidecar:
    """Round-3: the first encode skips the property JSON (training never
    reads it); props-needing readers upgrade segments in place."""

    def test_first_encode_defers_props_then_upgrades(self, sq, tmp_path):
        import json as _json

        storage, app_id = sq
        es = storage.events()
        es.insert_batch(synth_events(150, seed=11), app_id)
        # training-style first read: no props wanted
        b = es.find_columnar(app_id, ordered=False, with_props=False)
        assert b.n == 150
        manifest = _json.loads(
            (tmp_path / "pio.db.columnar" / "events_1" /
             "manifest.json").read_text())
        assert any(not s["props"] for s in manifest["segments"])
        # props-wanting read upgrades segments and returns real props
        bp = es.find_columnar(app_id)
        rows = sorted(proj(e) for e in es.find(app_id))
        cols = sorted(proj(e) for e in bp.to_events())
        assert cols == rows
        manifest = _json.loads(
            (tmp_path / "pio.db.columnar" / "events_1" /
             "manifest.json").read_text())
        assert all(s["props"] for s in manifest["segments"])

    def test_aggregation_after_deferred_encode(self, sq):
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.datamap import DataMap

        storage, app_id = sq
        es = storage.events()
        es.insert_batch(
            [Event(event="$set", entity_type="user", entity_id=f"u{i}",
                   properties=DataMap({"plan": "pro", "k": i}))
             for i in range(40)], app_id)
        es.find_columnar(app_id, ordered=False, with_props=False)
        props = es.aggregate_properties(app_id, entity_type="user")
        assert props["u7"]["plan"] == "pro"
        assert props["u7"]["k"] == 7


class TestBulkHelpers:
    def test_iso_to_millis_keeps_milliseconds(self):
        """pandas' DatetimeIndex resolution is INFERRED (datetime64[us]
        here); a raw asi8 // 1e6 silently produced epoch SECONDS —
        regression for the segmentfs sidecar time column (found by the
        cross-backend fuzzer)."""
        from predictionio_tpu.data.columnar import bulk_iso_to_millis
        out = list(bulk_iso_to_millis(
            ["2026-03-01T00:00:00.000Z", "2026-03-01T00:00:00.037Z",
             "2026-03-01T12:34:56.789Z"]))
        assert out == [1772323200000, 1772323200037, 1772368496789]

    def test_iso_to_millis_fallback_matches_pandas(self):
        import predictionio_tpu.data.columnar as col
        strings = ["2026-03-01T00:00:00.000Z",
                   "2026-03-01T00:00:00.037Z"]
        a = list(col.bulk_iso_to_millis(strings))
        saved = col._pd
        try:
            col._pd = None
            b = list(col.bulk_iso_to_millis(strings))
        finally:
            col._pd = saved
        assert a == b

    def test_old_format_sidecar_stamped_in_place(self, sq):
        """v1→v2 changed only the ISO→millis conversion, which the
        SQLite encoder never used (INTEGER millis straight from SQL) —
        a v1 sqlite sidecar is byte-identical to v2 and gets STAMPED,
        not re-encoded (a 20M-row re-encode for correct data would be
        pure waste)."""
        import json as _json

        storage, app_id = sq
        es = storage.events()
        es.insert_batch(synth_events(25, seed=9), app_id)
        b1 = es.find_columnar(app_id, ordered=False, with_props=False)
        d = es._columnar_dir(app_id, None)
        mpath = d + "/manifest.json"
        man = _json.loads(open(mpath).read())
        assert man.get("format") == 2
        segs_before = [sg["name"] for sg in man["segments"]]
        # simulate a v1 sidecar: strip the format field
        del man["format"]
        open(mpath, "w").write(_json.dumps(man))
        es.client.columnar_cache.clear()
        b2 = es.find_columnar(app_id, ordered=False, with_props=False)
        assert b2.n == b1.n == 25
        man2 = _json.loads(open(mpath).read())
        assert man2.get("format") == 2
        assert [sg["name"] for sg in man2["segments"]] == segs_before

    def test_old_format_segmentfs_sidecar_reencoded(self, tmp_path):
        """segmentfs DID write corrupt v1 event_time columns (the
        epoch-seconds bug): its v1 sidecars must be re-encoded."""
        import json as _json

        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        es = SegmentFSEventStore(SegmentFSClient(str(tmp_path)))
        es.init(1)
        es.insert_batch(synth_events(20, seed=3), 1)
        b1 = es.find_columnar(1, ordered=False, with_props=False)
        mpath = tmp_path / "events" / "app_1" / "columnar" / "manifest.json"
        man = _json.loads(mpath.read_text())
        segs_before = [sg["name"] for sg in man["segments"]]
        del man["format"]
        mpath.write_text(_json.dumps(man))
        es.c.replay_cache.clear()
        b2 = es.find_columnar(1, ordered=False, with_props=False)
        assert b2.n == b1.n == 20
        man2 = _json.loads(mpath.read_text())
        assert man2.get("format") == 2
        assert [sg["name"] for sg in man2["segments"]] != segs_before
