"""JSON ⇄ dataclass conversion, incl. nested dataclasses (the reference
``JsonExtractor`` handled nested case classes)."""

from dataclasses import dataclass, field
from typing import List, Optional

import pytest

from predictionio_tpu.utils.jsonutil import from_jsonable, to_jsonable


@dataclass
class Filter:
    categories: List[str]
    max_price: Optional[float] = None


@dataclass
class Query:
    user: str
    num: int = 10
    filter: Optional[Filter] = None


def test_roundtrip_flat():
    q = from_jsonable(Query, {"user": "u1", "num": 3})
    assert q == Query(user="u1", num=3)
    assert to_jsonable(q) == {"user": "u1", "num": 3, "filter": None}


def test_nested_dataclass_parsed():
    q = from_jsonable(Query, {"user": "u1",
                              "filter": {"categories": ["a", "b"]}})
    assert isinstance(q.filter, Filter)
    assert q.filter.categories == ["a", "b"]
    assert to_jsonable(q)["filter"] == {"categories": ["a", "b"],
                                        "max_price": None}


def test_unknown_field_rejected():
    with pytest.raises(ValueError, match="unknown field"):
        from_jsonable(Query, {"user": "u1", "bogus": 1})
    with pytest.raises(ValueError, match="unknown field"):
        from_jsonable(Query, {"user": "u1",
                              "filter": {"categories": [], "nope": 2}})


def test_non_mapping_rejected():
    with pytest.raises(ValueError, match="expected JSON object"):
        from_jsonable(Query, [1, 2])


def test_camelcase_wire_format_accepted():
    """The reference's wire format is camelCase; snake_case dataclasses
    must accept it (e.g. similarproduct whiteList/categoryBlackList)."""
    from predictionio_tpu.templates.similarproduct import Query as SPQuery

    q = from_jsonable(SPQuery, {"items": ["i0"], "num": 3,
                                "whiteList": ["i1"],
                                "categoryBlackList": ["c0"]})
    assert q.white_list == ("i1",)
    assert q.category_black_list == ("c0",)


def test_python_keyword_field_alias():
    from predictionio_tpu.templates.classification import NaiveBayesParams

    p = from_jsonable(NaiveBayesParams, {"lambda": 2.0})
    assert p.lambda_ == 2.0


def test_als_lambda_alias_from_engine_json():
    """The reference's engine.json spells regularization "lambda"
    (recommendation-engine/engine.json); ALSParams.reg must accept it."""
    from predictionio_tpu.models.als import ALSParams
    from predictionio_tpu.utils.jsonutil import from_jsonable

    p = from_jsonable(ALSParams, {"rank": 4, "numIterations": 2,
                                  "lambda": 0.25, "seed": 3})
    assert p.reg == 0.25
    p2 = from_jsonable(ALSParams, {"lambda_": 0.5})
    assert p2.reg == 0.5
