"""Step-level checkpoint/resume (Checkpointer + ALS resume)."""

import numpy as np
import pytest

from predictionio_tpu.models.als import ALSParams, RatingsCOO, train_als
from predictionio_tpu.workflow.checkpoint import Checkpointer


def ratings_fixture():
    rng = np.random.default_rng(4)
    nnz = 800
    return RatingsCOO(
        users=rng.integers(0, 30, nnz).astype(np.int32),
        items=rng.integers(0, 20, nnz).astype(np.int32),
        ratings=rng.uniform(1, 5, nnz).astype(np.float32),
        n_users=30, n_items=20)


class TestCheckpointer:
    def test_save_restore_latest(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = {"a": np.arange(5.0), "b": 3}
        ckpt.save(2, state)
        ckpt.save(4, {"a": np.arange(5.0) * 2, "b": 7})
        assert ckpt.latest_step() == 4
        got = ckpt.restore(4, like=state)
        np.testing.assert_array_equal(np.asarray(got["a"]),
                                      np.arange(5.0) * 2)
        assert int(got["b"]) == 7
        ckpt.close()

    def test_maybe_save_cadence(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path / "ck"))
        assert not ckpt.maybe_save(1, {"x": 1}, every=2)
        assert ckpt.maybe_save(2, {"x": 1}, every=2)
        assert not ckpt.maybe_save(3, {"x": 1}, every=0)
        assert ckpt.latest_step() == 2
        ckpt.close()


class TestALSResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        ratings = ratings_fixture()
        base = ALSParams(rank=6, num_iterations=6, seed=2)

        # uninterrupted reference run
        U_ref, V_ref = train_als(ratings, base)

        # interrupted: 3 iterations with checkpointing, then a fresh call
        # (new process semantics) resumes from step 3 and finishes
        ckdir = str(tmp_path / "als_ck")
        train_als(ratings, ALSParams(rank=6, num_iterations=3, seed=2),
                  checkpoint_dir=ckdir, checkpoint_every=1)
        U2, V2 = train_als(ratings, base, checkpoint_dir=ckdir,
                           checkpoint_every=1)

        np.testing.assert_allclose(np.asarray(U_ref), np.asarray(U2),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(V_ref), np.asarray(V2),
                                   rtol=1e-4, atol=1e-5)

    def test_completed_checkpoint_short_circuits(self, tmp_path):
        ratings = ratings_fixture()
        params = ALSParams(rank=4, num_iterations=2, seed=1)
        ckdir = str(tmp_path / "als_done")
        U1, V1 = train_als(ratings, params, checkpoint_dir=ckdir,
                           checkpoint_every=1)
        # re-run: latest step == num_iterations → no further updates
        U2, V2 = train_als(ratings, params, checkpoint_dir=ckdir,
                           checkpoint_every=1)
        np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(V1), np.asarray(V2),
                                   rtol=1e-5)


class TestCheckpointGuards:
    def test_foreign_checkpoint_rejected(self, tmp_path):
        ratings = ratings_fixture()
        ckdir = str(tmp_path / "guard")
        train_als(ratings, ALSParams(rank=4, num_iterations=2, seed=1),
                  checkpoint_dir=ckdir)
        with pytest.raises(ValueError, match="different ALS run"):
            train_als(ratings, ALSParams(rank=6, num_iterations=2, seed=1),
                      checkpoint_dir=ckdir)

    def test_checkpoint_dir_without_every_still_saves(self, tmp_path):
        ratings = ratings_fixture()
        ckdir = str(tmp_path / "implied")
        train_als(ratings, ALSParams(rank=4, num_iterations=3, seed=1),
                  checkpoint_dir=ckdir)  # checkpoint_every defaults on
        assert Checkpointer(ckdir).latest_step() == 3

    def test_larger_step_than_budget_ignored(self, tmp_path):
        ratings = ratings_fixture()
        ckdir = str(tmp_path / "budget")
        train_als(ratings, ALSParams(rank=4, num_iterations=5, seed=1),
                  checkpoint_dir=ckdir)
        # a shorter run must NOT return the 5-iteration factors
        U3, V3 = train_als(ratings,
                           ALSParams(rank=4, num_iterations=3, seed=1),
                           checkpoint_dir=ckdir)
        U3_ref, V3_ref = train_als(ratings,
                                   ALSParams(rank=4, num_iterations=3,
                                             seed=1))
        np.testing.assert_allclose(np.asarray(U3), np.asarray(U3_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_bad_matmul_dtype_rejected(self):
        with pytest.raises(ValueError, match="matmul_dtype"):
            ALSParams(matmul_dtype="bf16")
