"""CLI console, admin API, and dashboard tests
(SURVEY C23/C24/C25 parity)."""

import json
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.cli import main
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import Storage

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)

MEM_ENV = {
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
}


@pytest.fixture()
def storage():
    return Storage(env=MEM_ENV)


def run(storage, *argv) -> int:
    return main(list(argv), storage=storage)


class TestAppCommands:
    def test_app_lifecycle(self, storage, capsys):
        assert run(storage, "app", "new", "myapp",
                   "--description", "demo") == 0
        out = capsys.readouterr().out
        assert "Access Key:" in out
        # duplicate rejected
        assert run(storage, "app", "new", "myapp") == 1
        assert run(storage, "app", "list") == 0
        assert "myapp" in capsys.readouterr().out
        assert run(storage, "app", "show", "myapp") == 0
        assert run(storage, "app", "delete", "myapp", "-f") == 0
        assert storage.apps().get_by_name("myapp") is None

    def test_channels(self, storage):
        run(storage, "app", "new", "chapp")
        assert run(storage, "app", "channel-new", "chapp", "mobile") == 0
        assert any(c.name == "mobile" for c in storage.channels()
                   .get_by_app_id(storage.apps().get_by_name("chapp").id))
        # invalid channel name
        assert run(storage, "app", "channel-new", "chapp",
                   "bad name!") == 1
        assert run(storage, "app", "channel-delete", "chapp", "mobile",
                   "-f") == 0

    def test_accesskey_commands(self, storage, capsys):
        run(storage, "app", "new", "akapp")
        assert run(storage, "accesskey", "new", "akapp", "view", "buy",
                   "--key", "SECRET") == 0
        assert run(storage, "accesskey", "list", "--app", "akapp") == 0
        out = capsys.readouterr().out
        assert "SECRET" in out and "view,buy" in out
        assert run(storage, "accesskey", "delete", "SECRET") == 0

    def test_data_delete(self, storage):
        run(storage, "app", "new", "dapp")
        app_id = storage.apps().get_by_name("dapp").id
        storage.events().insert(Event(
            event="view", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i1",
            event_time=T0), app_id)
        assert run(storage, "app", "data-delete", "dapp", "-f") == 0
        from predictionio_tpu.data.storage.base import EventFilter
        assert list(storage.events().find(app_id, None, EventFilter())) == []


class TestStatusVersionTemplate:
    def test_status(self, storage, capsys):
        assert run(storage, "status") == 0
        assert "ready to go" in capsys.readouterr().out

    def test_version(self, storage, capsys):
        assert run(storage, "version") == 0

    def test_template_list(self, storage, capsys):
        assert run(storage, "template") == 0
        assert "recommendation" in capsys.readouterr().out


def seed_ratings(storage, app_name="cliapp"):
    run(storage, "app", "new", app_name)
    app_id = storage.apps().get_by_name(app_name).id
    rng = np.random.default_rng(2)
    events = []
    t = T0
    for u in range(20):
        pool = range(0, 8) if u % 2 == 0 else range(8, 16)
        for i in rng.choice(list(pool), size=5, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": 5.0}), event_time=t))
            t += timedelta(minutes=1)
    storage.events().insert_batch(events, app_id)
    return app_id


def write_variant(tmp_path, app_name="cliapp"):
    variant = {
        "id": "cli-engine",
        "version": "1",
        "engineFactory":
            "predictionio_tpu.templates.recommendation:"
            "recommendation_engine",
        "datasource": {"params": {"app_name": app_name}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 8, "num_iterations": 5,
                                   "seed": 4}}],
    }
    path = tmp_path / "engine.json"
    path.write_text(json.dumps(variant))
    return str(path)


class TestTrainBatchPredict:
    def test_build_train_batchpredict(self, storage, tmp_path, capsys):
        seed_ratings(storage)
        ej = write_variant(tmp_path)
        assert run(storage, "build", "--engine-json", ej) == 0
        assert run(storage, "train", "--engine-json", ej) == 0
        out = capsys.readouterr().out
        assert "Training completed" in out
        qfile = tmp_path / "queries.jsonl"
        qfile.write_text('{"user": "u0", "num": 3}\n'
                         '{"user": "u1", "num": 2}\n')
        ofile = tmp_path / "out.jsonl"
        assert run(storage, "batchpredict", "--engine-json", ej,
                   "--input", str(qfile), "--output", str(ofile)) == 0
        lines = [json.loads(l) for l in
                 ofile.read_text().strip().splitlines()]
        assert len(lines) == 2
        assert len(lines[0]["prediction"]["itemScores"]) == 3

    def test_export_import_roundtrip(self, storage, tmp_path):
        app_id = seed_ratings(storage, "exapp")
        out = tmp_path / "events.jsonl"
        assert run(storage, "export", "--app", "exapp",
                   "--output", str(out)) == 0
        n_lines = len(out.read_text().strip().splitlines())
        assert n_lines == 100  # 20 users × 5 ratings
        run(storage, "app", "new", "imapp")
        assert run(storage, "import", "--app", "imapp",
                   "--input", str(out)) == 0
        from predictionio_tpu.data.storage.base import EventFilter
        im_id = storage.apps().get_by_name("imapp").id
        got = list(storage.events().find(im_id, None, EventFilter()))
        assert len(got) == n_lines


class TestAdminServer:
    def test_admin_routes(self, storage):
        from predictionio_tpu.server.adminserver import build_app
        from predictionio_tpu.server.http import Request

        app = build_app(storage)

        def call(method, path, body=None):
            req = Request(method=method, path=path, query={}, headers={},
                          body=json.dumps(body).encode() if body else b"")
            resp = app.handle(req)
            return resp.status, (json.loads(resp.encoded())
                                 if resp.encoded() else None)

        status, body = call("GET", "/")
        assert status == 200 and body["status"] == "alive"
        status, body = call("POST", "/cmd/app", {"name": "adminapp"})
        assert body["status"] == 1 and body["key"]
        status, body = call("POST", "/cmd/app", {"name": "adminapp"})
        assert body["status"] == 0  # duplicate
        status, body = call("GET", "/cmd/app")
        assert any(a["name"] == "adminapp" for a in body["apps"])
        status, body = call("DELETE", "/cmd/app/adminapp/data")
        assert body["status"] == 1
        status, body = call("DELETE", "/cmd/app/adminapp")
        assert body["status"] == 1
        assert storage.apps().get_by_name("adminapp") is None
        status, body = call("DELETE", "/cmd/app/ghost")
        assert status == 404


class TestDashboard:
    def test_dashboard_routes(self, storage):
        from predictionio_tpu.data.storage.base import (
            STATUS_EVALCOMPLETED, EvaluationInstance)
        from predictionio_tpu.server.dashboard import build_app
        from predictionio_tpu.server.http import Request

        iid = storage.evaluation_instances().insert(EvaluationInstance(
            id="", status=STATUS_EVALCOMPLETED, start_time=T0, end_time=T0,
            evaluation_class="my.Eval",
            evaluator_results="Precision@10: 0.5",
            evaluator_results_html="<html>ok</html>",
            evaluator_results_json='{"metric": 0.5}'))
        app = build_app(storage)

        def call(path):
            return app.handle(Request(method="GET", path=path, query={},
                                      headers={}, body=b""))

        index = call("/")
        assert index.status == 200
        assert "my.Eval" in index.encoded().decode()
        txt = call(f"/engine_instances/{iid}/evaluator_results.txt")
        assert txt.encoded().decode() == "Precision@10: 0.5"
        html = call(f"/engine_instances/{iid}/evaluator_results.html")
        assert "ok" in html.encoded().decode()
        js = call(f"/engine_instances/{iid}/evaluator_results.json")
        assert json.loads(js.encoded())["metric"] == 0.5
        cors = call(f"/engine_instances/{iid}/local_evaluator_results.json")
        assert cors.headers.get("Access-Control-Allow-Origin") == "*"
        assert call("/engine_instances/nope/evaluator_results.txt")\
            .status == 404


class TestEvalCommand:
    def test_eval(self, storage, tmp_path, capsys, monkeypatch):
        seed_ratings(storage, "evapp")
        mod = tmp_path / "cli_eval_mod.py"
        mod.write_text('''
from predictionio_tpu.controller import Evaluation
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams, PrecisionAtK, recommendation_engine)

evaluation = Evaluation(engine=recommendation_engine(),
                        metric=PrecisionAtK(k=3, rating_threshold=2.0))
engine_params_list = [
    EngineParams(
        datasource=("", DataSourceParams(app_name="evapp", eval_k=2)),
        algorithms=[("als", ALSParams(rank=r, num_iterations=4, seed=1))])
    for r in (4, 8)]


class Gen:
    engine_params_list = engine_params_list


gen = Gen()
''')
        monkeypatch.syspath_prepend(str(tmp_path))
        assert run(storage, "eval", "cli_eval_mod:evaluation",
                   "cli_eval_mod:gen") == 0
        out = capsys.readouterr().out
        assert "Precision@3" in out or "0." in out


class TestTrainWorkflowFlags:
    def test_stop_after_read(self, storage, tmp_path, capsys):
        """--stop-after-read leaves the instance in INIT (reference
        WorkflowParams semantics)."""
        seed_ratings(storage, "flagapp")
        ej = write_variant(tmp_path, "flagapp")
        assert run(storage, "train", "--engine-json", ej,
                   "--stop-after-read") == 0
        from predictionio_tpu.data.storage.base import STATUS_INIT
        instances = storage.engine_instances().get_all()
        assert instances
        assert all(i.status == STATUS_INIT for i in instances)

    def test_stop_after_prepare(self, storage, tmp_path):
        seed_ratings(storage, "flagapp2")
        ej = write_variant(tmp_path, "flagapp2")
        assert run(storage, "train", "--engine-json", ej,
                   "--stop-after-prepare") == 0
        from predictionio_tpu.data.storage.base import STATUS_INIT
        assert all(i.status == STATUS_INIT
                   for i in storage.engine_instances().get_all())

    def test_skip_sanity_check_trains(self, storage, tmp_path, capsys):
        """An app with no events fails the sanity check — unless the
        flag actually reaches the workflow."""
        run(storage, "app", "new", "emptyapp")
        ej = write_variant(tmp_path, "emptyapp")
        with pytest.raises(ValueError, match="no ratings"):
            run(storage, "train", "--engine-json", ej)
        # with the flag the sanity check is SKIPPED: the failure moves
        # past it into the algorithm (a different, later error)
        with pytest.raises(ValueError, match="non-empty ratings matrix"):
            run(storage, "train", "--engine-json", ej,
                "--skip-sanity-check")
        # success path: flag on a HEALTHY app still trains to COMPLETED
        seed_ratings(storage, "flagok")
        ej2 = write_variant(tmp_path, "flagok")
        assert run(storage, "train", "--engine-json", ej2,
                   "--skip-sanity-check") == 0
        assert "Training completed" in capsys.readouterr().out


class TestAdminDashboardAuth:
    def test_admin_accesskey_guard(self, storage):
        from predictionio_tpu.server.adminserver import build_app
        from predictionio_tpu.server.http import Request

        app = build_app(storage, accesskey="SECRET")

        def call(path, query=None):
            return app.handle(Request(method="GET", path=path,
                                      query=query or {}, headers={},
                                      body=b"")).status

        assert call("/") == 200               # liveness stays open
        assert call("/cmd/app") == 401
        assert call("/cmd/app", {"accessKey": "SECRET"}) == 200

    def test_dashboard_accesskey_guard(self, storage):
        from predictionio_tpu.server.dashboard import build_app
        from predictionio_tpu.server.http import Request

        app = build_app(storage, accesskey="SECRET")

        def call(path, query=None):
            return app.handle(Request(method="GET", path=path,
                                      query=query or {}, headers={},
                                      body=b"")).status

        assert call("/") == 401
        assert call("/", {"accessKey": "SECRET"}) == 200

    def test_dashboard_session_cookie_keeps_links_clean(self, storage):
        """First authenticated request mints an HttpOnly session cookie;
        generated links never embed the accessKey (browser history /
        proxy logs / Referer leakage — ADVICE r1)."""
        from datetime import datetime, timezone

        from predictionio_tpu.data.storage.base import (
            STATUS_EVALCOMPLETED,
            EvaluationInstance,
        )
        from predictionio_tpu.server.dashboard import build_app
        from predictionio_tpu.server.http import Request

        t = datetime(2026, 1, 1, tzinfo=timezone.utc)
        storage.evaluation_instances().insert(EvaluationInstance(
            id="", status=STATUS_EVALCOMPLETED, start_time=t, end_time=t,
            evaluator_results="r"))
        app = build_app(storage, accesskey="SECRET")
        resp = app.handle(Request(method="GET", path="/",
                                  query={"accessKey": "SECRET"},
                                  headers={}, body=b""))
        html = resp.encoded().decode()
        assert "accessKey" not in html        # links carry no secret
        cookie = resp.headers.get("Set-Cookie", "")
        assert "HttpOnly" in cookie
        # the minted cookie authenticates follow-up requests on its own
        token = cookie.split(";")[0]
        resp2 = app.handle(Request(method="GET", path="/", query={},
                                   headers={"Cookie": token}, body=b""))
        assert resp2.status == 200
        # and a bogus cookie does not
        resp3 = app.handle(Request(
            method="GET", path="/", query={},
            headers={"Cookie": "pio_dashboard_session=forged"}, body=b""))
        assert resp3.status == 401


class TestStartStopAll:
    """`ptpu start-all` / `stop-all` (VERDICT r3 missing #3): the
    bin/pio-start-all role — daemons with pidfiles, ports answering,
    double-start refused, stop-all reaps everything."""

    def test_round_trip(self, storage, tmp_path, capsys):
        import os
        import socket

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        ev_p, ad_p, db_p = free_port(), free_port(), free_port()
        pid_dir = str(tmp_path / "pids")
        env_before = dict(os.environ)
        os.environ.update(MEM_ENV)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            rc = run(storage, "start-all", "--ip", "127.0.0.1",
                     "--pid-dir", pid_dir,
                     "--eventserver-port", str(ev_p),
                     "--adminserver-port", str(ad_p),
                     "--dashboard-port", str(db_p),
                     "--start-timeout", "60")
            assert rc == 0, capsys.readouterr()
            for name, port in (("eventserver", ev_p),
                               ("adminserver", ad_p),
                               ("dashboard", db_p)):
                assert os.path.exists(
                    os.path.join(pid_dir, f"{name}.pid"))
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=5):
                    pass
            pids = {n: int(open(os.path.join(pid_dir, f"{n}.pid"))
                           .read())
                    for n in ("eventserver", "adminserver",
                              "dashboard")}
            # double start must refuse, not spawn twins
            rc2 = run(storage, "start-all", "--ip", "127.0.0.1",
                      "--pid-dir", pid_dir,
                      "--eventserver-port", str(ev_p),
                      "--adminserver-port", str(ad_p),
                      "--dashboard-port", str(db_p))
            assert rc2 == 1
            for n, pid in pids.items():
                assert int(open(os.path.join(pid_dir, f"{n}.pid"))
                           .read()) == pid
        finally:
            rc3 = run(storage, "stop-all", "--pid-dir", pid_dir)
            os.environ.clear()
            os.environ.update(env_before)
        assert rc3 == 0
        import errno
        for n, pid in pids.items():
            assert not os.path.exists(
                os.path.join(pid_dir, f"{n}.pid"))
            try:
                os.kill(pid, 0)
                alive = True
            except ProcessLookupError:
                alive = False
            assert not alive, f"{n} pid {pid} survived stop-all"


def test_deploy_batching_defaults_match_config():
    """`ptpu deploy`'s batching flag defaults must equal ServerConfig's
    field defaults (the CLI uses literals so storage-only commands
    never import the server stack / jax — this test is the sync)."""
    from predictionio_tpu.cli import build_parser
    from predictionio_tpu.server.engineserver import MicroBatcher, ServerConfig

    args = build_parser().parse_args(
        ["deploy", "--engine-json", "engine.json"])
    cfg = ServerConfig()
    assert args.max_batch == cfg.max_batch
    assert args.batch_window_ms == cfg.batch_window_ms
    assert args.batch_pipeline == cfg.batch_pipeline
    assert args.serving_mode == cfg.serving_mode
    # staged-pipeline knobs (ISSUE 9) stay in sync the same way
    assert args.pipeline == cfg.serving_pipeline
    assert args.queue_deadline_ms == cfg.queue_deadline_ms
    assert args.assemble_workers == cfg.assemble_workers
    assert args.readback_workers == cfg.readback_workers
    assert args.pipeline_depth == cfg.pipeline_depth
    # serving fast-path knobs (ISSUE 13) stay in sync the same way
    assert args.serving_quant == cfg.serving_quant
    assert args.serving_topk == cfg.serving_topk
    # tracing knobs (ISSUE 12) stay in sync the same way
    assert (not args.no_trace) == cfg.tracing
    assert args.trace_ring == cfg.trace_ring
    assert args.trace_slow_ms == cfg.trace_slow_ms
    assert args.access_log_sample == cfg.access_log_sample
    # hot-key telemetry (ISSUE 17) stays in sync the same way
    assert args.hot_keys_k == cfg.hot_keys_k
    import inspect

    sig = inspect.signature(MicroBatcher.__init__)
    assert sig.parameters["max_batch"].default == cfg.max_batch
