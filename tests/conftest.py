"""Test configuration: force an 8-device virtual CPU mesh.

The reference's unit tests run Spark with a `local` master
(`core/src/test/.../workflow/BaseTest.scala`); the TPU build does better —
multi-device semantics are exercised on every test run via XLA's virtual
host devices, so `shard_map`/`pjit` sharding is covered without TPU hardware.
Must run before jax initializes its backends, hence os.environ at import.
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS (the session may point at a
# real TPU; unit tests must be deterministic f32 on the virtual mesh). The
# env var alone is not enough when a TPU PJRT plugin is installed — the
# config update below is authoritative.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from predictionio_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8, "expected 8 virtual CPU devices"
    return make_mesh(data=4, model=2)


def start_sqlite_backed_storage_server(tmp_path, secret=None):
    """Shared bootstrap for remote-backend tests: a sqlite-backed
    Storage served by a real storage server on a loopback port.
    Returns (server, backing_storage); caller shuts the server down."""
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.server.storageserver import (
        create_storage_server,
    )

    backing = Storage(env={
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": str(tmp_path / "backing.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
    })
    srv = create_storage_server(backing, host="127.0.0.1", port=0,
                                secret=secret)
    srv.start_background()
    return srv, backing


@pytest.fixture(autouse=True)
def _fail_on_lock_inversions():
    """Instrumented-lock CI mode: when the suite runs with
    PTPU_DEBUG_LOCKS=1 (the separate workflow step that re-runs the
    cache/rollout stress tests), any lock-order inversion or
    non-reentrant re-entry the DebugLock registry records during a test
    fails THAT test — an ordering regression dies in CI, not in
    production. A no-op (plain locks, no registry reads) otherwise."""
    from predictionio_tpu.concurrency import (
        lock_registry,
        locks_instrumented,
    )

    if not locks_instrumented():
        yield
        return
    reg = lock_registry()
    before_inv = len(reg.inversions)
    before_re = len(reg.reentries)
    yield
    inversions = reg.inversions[before_inv:]
    reentries = reg.reentries[before_re:]
    problems = [f"lock-order inversion: acquiring {i['acquiring']!r} "
                f"while holding {i['held']!r} at {i['site']} "
                f"(prior order established at {i['prior_site']})"
                for i in inversions]
    problems += [f"same-thread re-entry on {r['lock']!r} at {r['site']}"
                 for r in reentries]
    assert not problems, "\n".join(problems)
