"""Test configuration: force an 8-device virtual CPU mesh.

The reference's unit tests run Spark with a `local` master
(`core/src/test/.../workflow/BaseTest.scala`); the TPU build does better —
multi-device semantics are exercised on every test run via XLA's virtual
host devices, so `shard_map`/`pjit` sharding is covered without TPU hardware.
Must run before jax initializes its backends, hence os.environ at import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from predictionio_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8, "expected 8 virtual CPU devices"
    return make_mesh(data=4, model=2)
