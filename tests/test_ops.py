"""Unit tests for the hot-op kernels in ``predictionio_tpu.ops``.

The Pallas SPD solver is validated in interpreter mode on CPU against
the XLA Cholesky path and a float64 numpy reference — the same kernel
runs compiled on TPU (dispatch in ``solve_spd_batch``).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from predictionio_tpu.ops.solve import (
    _solve_spd_pallas,
    gramian,
    solve_spd_batch,
)


def _spd_batch(n, r, seed=0, reg=0.1):
    rng = np.random.default_rng(seed)
    W = rng.standard_normal((n, max(r // 4, 2), r)).astype(np.float32)
    A = np.einsum("nkr,nks->nrs", W, W).astype(np.float32)
    A += reg * np.eye(r, dtype=np.float32)
    b = rng.standard_normal((n, r)).astype(np.float32)
    return A, b


@pytest.mark.parametrize("n,r", [(4, 8), (130, 64), (256, 10), (1, 16),
                                 (70, 128)])
def test_pallas_solver_matches_float64(n, r):
    """Lane-batched Cholesky kernel (interpret mode) vs float64 numpy,
    covering batch sizes off the 128-lane multiple and ranks off the
    8-sublane multiple (both hit the padding paths)."""
    A, b = _spd_batch(n, r)
    ref = np.linalg.solve(A.astype(np.float64),
                          b.astype(np.float64)[..., None])[..., 0]
    out = np.asarray(_solve_spd_pallas(jnp.asarray(A), jnp.asarray(b),
                                       interpret=True))
    assert out.shape == (n, r)
    # r=128 systems are worse-conditioned; a couple of elements land
    # just past 1e-3 absolute in f32 — still parity with the XLA path
    np.testing.assert_allclose(out, ref, rtol=2e-3,
                               atol=(3e-3 if r >= 128 else 1e-3))


def test_rank_routing_vmem_budget():
    """VMEM budget routing: scratch variant to rp=88, aliased in-place
    variant to rp=128 (the measured chip OOM boundary), XLA beyond."""
    from predictionio_tpu.ops.solve import _RP_ALIAS, _RP_SCRATCH

    assert _RP_SCRATCH == 88 and _RP_ALIAS == 128
    # scratch variant footprint: block + scratch
    assert 2 * _RP_SCRATCH**2 * 128 * 4 <= 12 * 2**20
    # aliased variant footprint: one block only
    assert _RP_ALIAS**2 * 128 * 4 <= 12 * 2**20
    # rank 192 must not assert inside the pallas path: the public entry
    # routes it to XLA
    A, b = _spd_batch(9, 192)
    out = np.asarray(solve_spd_batch(jnp.asarray(A), jnp.asarray(b)))
    ref = np.linalg.solve(A.astype(np.float64),
                          b.astype(np.float64)[..., None])[..., 0]
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_pallas_solver_matches_xla_path():
    """The two dispatch targets of solve_spd_batch agree (same jitter)."""
    A, b = _spd_batch(37, 24, seed=3)
    xla = np.asarray(solve_spd_batch(jnp.asarray(A), jnp.asarray(b)))
    r = A.shape[-1]
    pal = np.asarray(_solve_spd_pallas(
        jnp.asarray(A) + 1e-6 * jnp.eye(r), jnp.asarray(b),
        interpret=True))
    np.testing.assert_allclose(pal, xla, rtol=2e-3, atol=2e-4)


def test_pallas_solver_empty_history_rows():
    """Rows whose normal matrix is just λI (empty histories) solve to
    b/λ without NaNs — the padding-lane regime inside the kernel."""
    r = 16
    lam = 0.5
    A = np.broadcast_to(lam * np.eye(r, dtype=np.float32),
                        (5, r, r)).copy()
    b = np.ones((5, r), dtype=np.float32)
    out = np.asarray(_solve_spd_pallas(jnp.asarray(A), jnp.asarray(b),
                                       interpret=True))
    np.testing.assert_allclose(out, b / lam, rtol=1e-5)
    assert np.isfinite(out).all()


def test_gramian():
    F = np.arange(12, dtype=np.float32).reshape(4, 3)
    np.testing.assert_allclose(np.asarray(gramian(jnp.asarray(F))),
                               F.T @ F, rtol=1e-6)


class TestGramVariants:
    """ops/gram.py: the pair-packed MXU gram must equal the baseline."""

    def test_pair_matches_einsum(self):
        import jax.numpy as jnp

        from predictionio_tpu.ops.gram import gram_pairs, gram_weighted
        rng = np.random.default_rng(0)
        F = jnp.asarray(rng.standard_normal((2, 6, 17, 8)), jnp.float32)
        w = jnp.asarray(rng.random((2, 6, 17)), jnp.float32)
        np.testing.assert_allclose(np.asarray(gram_pairs(F, w)),
                                   np.asarray(gram_weighted(F, w)),
                                   rtol=1e-5, atol=1e-5)

    def test_pair_bf16_close(self):
        import jax.numpy as jnp

        from predictionio_tpu.ops.gram import gram_pairs, gram_weighted
        rng = np.random.default_rng(1)
        F = jnp.asarray(rng.standard_normal((1, 4, 9, 16)), jnp.float32)
        w = jnp.asarray(rng.random((1, 4, 9)), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(gram_pairs(F, w, bf16=True)),
            np.asarray(gram_weighted(F, w)), rtol=3e-2, atol=3e-2)

    def test_train_als_pair_mode_matches(self):
        from predictionio_tpu.models.als import (
            ALSParams, RatingsCOO, train_als)
        rng = np.random.default_rng(3)
        coo = RatingsCOO(rng.integers(0, 30, 600).astype(np.int32),
                         rng.integers(0, 20, 600).astype(np.int32),
                         rng.random(600).astype(np.float32) * 4 + 1,
                         30, 20)
        base = ALSParams(rank=8, num_iterations=3, seed=5,
                         implicit_prefs=True, alpha=20.0)
        import dataclasses
        pair = dataclasses.replace(base, gram_mode="pair")
        U1, V1 = train_als(coo, base)
        U2, V2 = train_als(coo, pair)
        # the pair layout reassociates the f32 contraction; per-iteration
        # divergence is ~5e-5 rel and compounds through the solves
        np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                                   rtol=5e-2, atol=2e-3)
        np.testing.assert_allclose(np.asarray(V1), np.asarray(V2),
                                   rtol=5e-2, atol=2e-3)

    def test_gram_table_pallas_interpret(self):
        """Fused VMEM-table gather+gram kernel vs the einsum reference
        (interpret mode — Mosaic lowering is probed at runtime on TPU)."""
        import jax.numpy as jnp

        from predictionio_tpu.ops.gram import gram_table_pallas
        rng = np.random.default_rng(4)
        m, r, B, L = 200, 16, 21, 24
        tab = rng.standard_normal((m, r)).astype(np.float32)
        idx = rng.integers(0, m, (B, L)).astype(np.int32)
        wa = rng.random((B, L)).astype(np.float32)
        wb = rng.random((B, L)).astype(np.float32)
        A, b = gram_table_pallas(jnp.asarray(tab), jnp.asarray(idx),
                                 jnp.asarray(wa), jnp.asarray(wb),
                                 interpret=True)
        F = tab[idx]
        np.testing.assert_allclose(
            np.asarray(A), np.einsum("blr,bls,bl->brs", F, F, wa),
            rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(b), np.einsum("blr,bl->br", F, wb),
            rtol=1e-4, atol=1e-4)
