"""ML-20M surrogate marginals (VERDICT r3 task 6) — the documented
exact constraints hold at CI scale, and full-scale constants are the
published ones."""

import numpy as np

from benchmarks.ml20m_surrogate import (
    N_RATINGS,
    RATING_HISTOGRAM,
    generate,
    verify_marginals,
)


def test_exact_published_constants():
    assert N_RATINGS == 20_000_263
    assert sum(RATING_HISTOGRAM.values()) == N_RATINGS
    assert set(RATING_HISTOGRAM) == {0.5, 1.0, 1.5, 2.0, 2.5, 3.0,
                                     3.5, 4.0, 4.5, 5.0}


def test_one_percent_scale_marginals():
    users, items, stars, ts, n_users, n_movies = generate(0.01, seed=20)
    stats = verify_marginals(users, items, stars, ts, n_users,
                             n_movies, 0.01)
    assert stats["n_ratings"] == 200_003  # round(N_RATINGS * 0.01)
    assert stats["n_users"] == 1_385
    assert abs(stats["mean_per_user"] - 144.4) < 0.5
    # per-user timestamps are non-decreasing
    order = np.lexsort((np.arange(len(users)), users))
    same_user = users[order][1:] == users[order][:-1]
    assert np.all(ts[order][1:][same_user] >= ts[order][:-1][same_user])
    # values come only from the half-star alphabet
    assert set(np.unique(stars)) <= set(RATING_HISTOGRAM)


def test_determinism():
    a = generate(0.01, seed=20)
    b = generate(0.01, seed=20)
    for x, y in zip(a[:4], b[:4]):
        np.testing.assert_array_equal(x, y)
