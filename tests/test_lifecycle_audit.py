"""``ptpu audit-lifecycle`` tests (ISSUE 20): /proc snapshot + settle
semantics, the manifest ratchet (shrink-only writes, violation/
shrinkable diffs), the CLI contract, and the acceptance fixture — a
deliberately leaked thread that must fail BOTH the static
``leaked-thread`` rule and the runtime gate."""

import json
import threading
import time

import pytest

from predictionio_tpu.analysis import check_source
from predictionio_tpu.analysis import lifecycle_audit as la
from predictionio_tpu.cli import main


class TestSnapshot:
    def test_counts_are_sane(self):
        snap = la.snapshot()
        assert set(snap) == set(la.RESOURCES)
        assert snap["threads"] >= 1
        assert all(isinstance(v, int) and v >= 0 for v in snap.values())

    def test_spawned_thread_is_visible(self):
        before = la.snapshot()
        stop = threading.Event()
        t = threading.Thread(target=stop.wait, daemon=True)
        t.start()
        try:
            assert la.snapshot()["threads"] >= before["threads"] + 1
        finally:
            stop.set()
            t.join(timeout=5.0)

    def test_leak_clamps_at_zero(self):
        before = {"threads": 5, "fds": 10, "sockets": 2}
        after = {"threads": 7, "fds": 8, "sockets": 2}
        assert la._leak(before, after) == {
            "threads": 2, "fds": 0, "sockets": 0}

    def test_settle_absorbs_a_thread_mid_exit(self):
        # a thread that finishes moments after the cycle is lag, not
        # a leak — the settle loop waits it out
        before = la.snapshot()
        t = threading.Thread(target=lambda: time.sleep(0.2),
                             daemon=True)
        t.start()
        after = la._settle(before, settle_sec=5.0)
        assert not any(la._leak(before, after).values())


class TestManifestRatchet:
    def _manifest(self, **entries):
        return {"version": la.MANIFEST_VERSION, "cycles": 3,
                "entries": {
                    name: {"threads": rec[0], "fds": rec[1],
                           "sockets": rec[2]}
                    for name, rec in entries.items()}}

    def test_write_load_round_trip(self, tmp_path):
        path = str(tmp_path / "b.json")
        m = self._manifest(a=(0, 0, 0))
        la.write_manifest(path, m)
        assert la.load_manifest(path) == m

    def test_load_rejects_wrong_version(self, tmp_path):
        path = tmp_path / "bad.json"
        path.write_text(json.dumps({"version": 99, "entries": {}}))
        with pytest.raises(ValueError):
            la.load_manifest(str(path))

    def test_capped_write_is_shrink_only(self, tmp_path):
        # counts clamp to the recorded allowance; entries the old
        # baseline never held are dropped
        path = str(tmp_path / "b.json")
        cap = self._manifest(a=(2, 1, 0))
        fresh = self._manifest(a=(5, 0, 0), b=(1, 1, 1))
        la.write_manifest(path, fresh, cap=cap)
        doc = la.load_manifest(path)
        assert doc["entries"] == {
            "a": {"threads": 2, "fds": 0, "sockets": 0}}

    def test_diff_flags_leak_above_allowance(self):
        cur = self._manifest(a=(3, 0, 0))
        base = self._manifest(a=(0, 0, 0))
        violations, shrinkable = la.diff_manifests(cur, base)
        assert len(violations) == 1
        assert "a:" in violations[0] and "threads" in violations[0]
        assert "--baseline-grow" in violations[0]
        assert shrinkable == []

    def test_diff_flags_unknown_entry(self):
        cur = self._manifest(new_entry=(0, 0, 0))
        base = self._manifest()
        violations, _ = la.diff_manifests(cur, base)
        assert len(violations) == 1
        assert "not in the baseline" in violations[0]

    def test_diff_reports_shrinkable(self):
        cur = self._manifest(a=(0, 0, 0))
        base = self._manifest(a=(2, 0, 0))
        violations, shrinkable = la.diff_manifests(cur, base)
        assert violations == []
        assert len(shrinkable) == 1 and "recorded 2" in shrinkable[0]

    def test_format_text(self):
        m = self._manifest(clean=(0, 0, 0), leaky=(2, 0, 1))
        text = la.format_text(m)
        assert "clean: clean over 3 cycles" in text
        assert "leaky: LEAKING over 3 cycles" in text
        assert "threads +2" in text and "sockets +1" in text


class TestRunAudit:
    def test_injected_clean_entry(self):
        registry = {"noop": (lambda: (lambda: None), "does nothing")}
        m = la.run_audit(entry_points=registry, cycles=2,
                         settle_sec=0.2)
        assert m["cycles"] == 2
        assert m["entries"]["noop"] == {
            "threads": 0, "fds": 0, "sockets": 0}

    def test_unknown_entry_raises(self):
        with pytest.raises(la.AuditError, match="unknown entry"):
            la.run_audit(["nope"],
                         entry_points={"a": (lambda: None, "")})

    def test_broken_builder_is_env_error(self):
        def boom():
            raise RuntimeError("no storage")

        with pytest.raises(la.AuditError, match="entry setup failed"):
            la.run_audit(entry_points={"a": (boom, "")},
                         settle_sec=0.2)

    def test_committed_baseline_covers_registry(self):
        # the golden manifest in the tree gates every entry point —
        # adding an entry without recording it fails the gate in CI
        doc = la.load_manifest(la.DEFAULT_BASELINE)
        assert set(doc["entries"]) == set(la.ENTRY_POINTS)


#: the acceptance fixture: a scrape daemon whose handle nobody joins.
#: The SAME source is judged twice — by the static rule (the AST sees
#: the missing join path) and by the runtime gate (the process shows
#: one surviving thread per start→stop cycle).
LEAKY_SRC = '''
import threading
import time


class LeakyPoller:
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        pass  # the bug: no stop event, no join

    def _run(self):
        while True:
            time.sleep(0.05)
'''


class TestLeakedFixtureFailsBothGates:
    def test_static_rule_flags_the_fixture(self):
        findings = check_source(
            LEAKY_SRC, path="predictionio_tpu/server/leaky.py")
        assert [f.rule for f in findings] == ["leaked-thread"]

    def test_runtime_gate_counts_the_leak(self):
        ns: dict = {}
        exec(LEAKY_SRC, ns)
        poller_cls = ns["LeakyPoller"]

        def build():
            def cycle():
                p = poller_cls()
                p.start()
                p.stop()

            return cycle

        m = la.run_audit(
            entry_points={"leaky": (build, "leaks 1 thread/cycle")},
            cycles=3, settle_sec=0.3)
        assert m["entries"]["leaky"]["threads"] >= 3
        baseline = {"version": la.MANIFEST_VERSION, "cycles": 3,
                    "entries": {"leaky": {"threads": 0, "fds": 0,
                                          "sockets": 0}}}
        violations, _ = la.diff_manifests(m, baseline)
        assert any("leaky" in v and "threads" in v
                   for v in violations)


class TestCLI:
    def test_list_entries(self, capsys):
        assert main(["audit-lifecycle", "--list-entries"]) == 0
        out = capsys.readouterr().out
        for name in la.ENTRY_POINTS:
            assert name in out

    def test_unknown_entry_is_env_error(self):
        assert main(["audit-lifecycle", "--entry", "nope"]) == 2

    def test_no_baseline_skips_gate(self, tmp_path, capsys):
        rc = main(["audit-lifecycle", "--entry", "storage_server",
                   "--cycles", "1",
                   "--baseline", str(tmp_path / "none.json")])
        assert rc == 0
        assert "gate skipped" in capsys.readouterr().err

    def test_write_then_gate_green(self, tmp_path, capsys):
        path = str(tmp_path / "b.json")
        assert main(["audit-lifecycle", "--entry", "storage_server",
                     "--cycles", "1", "--baseline", path,
                     "--write-baseline"]) == 0
        doc = la.load_manifest(path)
        assert "storage_server" in doc["entries"]
        rc = main(["audit-lifecycle", "--entry", "storage_server",
                   "--cycles", "1", "--baseline", path,
                   "--out", str(tmp_path / "artifact.json")])
        assert rc == 0
        assert "released its threads" in capsys.readouterr().err
        artifact = json.loads(
            (tmp_path / "artifact.json").read_text())
        assert artifact["version"] == la.MANIFEST_VERSION

    def test_entry_missing_from_baseline_fails(self, tmp_path,
                                               capsys):
        path = tmp_path / "b.json"
        path.write_text(json.dumps(
            {"version": la.MANIFEST_VERSION, "cycles": 1,
             "entries": {}}))
        rc = main(["audit-lifecycle", "--entry", "storage_server",
                   "--cycles", "1", "--baseline", str(path)])
        assert rc == 1
        assert "not in the baseline" in capsys.readouterr().err
