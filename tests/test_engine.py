"""Engine/workflow semantics tests.

Strategy parity with the reference's fixture engine family
(`core/src/test/.../controller/SampleEngine.scala`): numbered
DataSource/Preparator/Algorithm/Serving components whose outputs encode
their params and inputs, so tests assert the exact data flow of
Engine.train/eval, the evaluator's model selection, and prefix memoization
(`FastEvalEngineTest` cache-hit counting).
"""

from dataclasses import dataclass

import pytest

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    Context,
    DataSource,
    Engine,
    EngineParams,
    Evaluation,
    FirstServing,
    MetricEvaluator,
    Preparator,
    SanityCheck,
    Serving,
    engine_params_from_variant,
)
from predictionio_tpu.controller.engine import SimpleEngine

CALLS = {"read": 0, "prepare": 0, "train": 0}


def reset_calls():
    for k in CALLS:
        CALLS[k] = 0


@dataclass(frozen=True)
class DSParams:
    id: int = 0
    folds: int = 2
    error: bool = False


@dataclass(frozen=True)
class TD(SanityCheck):
    """Training data that self-checks (like the reference's sample TDs)."""

    id: int
    error: bool = False

    def sanity_check(self):
        if self.error:
            raise ValueError("datasource error flag")


class DS(DataSource):
    def __init__(self, params: DSParams = DSParams()):
        self.params = params

    def read_training(self, ctx):
        CALLS["read"] += 1
        return TD(self.params.id, self.params.error)

    def read_eval(self, ctx):
        CALLS["read"] += 1
        return [(TD(self.params.id), ("ei", f),
                 [((f, q), (f, q)) for q in range(3)])
                for f in range(self.params.folds)]


@dataclass(frozen=True)
class PParams:
    id: int = 0


class Prep(Preparator):
    def __init__(self, params: PParams = PParams()):
        self.params = params

    def prepare(self, ctx, td):
        CALLS["prepare"] += 1
        return ("pd", td, self.params.id)


@dataclass(frozen=True)
class AParams:
    id: int = 0


class Algo(Algorithm):
    def __init__(self, params: AParams = AParams()):
        self.params = params

    def train(self, ctx, pd):
        CALLS["train"] += 1
        return ("model", pd, self.params.id)

    def predict(self, model, q):
        return ("pred", model[2], q)


class Algo2(Algo):
    pass


class ServeSum(Serving):
    def serve(self, query, predictions):
        return ("served", query, tuple(p[1] for p in predictions))


def make_engine():
    return Engine(
        datasource_classes=DS,
        preparator_classes=Prep,
        algorithm_classes={"a1": Algo, "a2": Algo2},
        serving_classes=ServeSum,
        datasource_params_class=DSParams,
        preparator_params_class=PParams,
        algorithm_params_classes={"a1": AParams, "a2": AParams},
    )


def ep(ds=0, prep=0, algos=(("a1", 0),)):
    return EngineParams(
        datasource=("", DSParams(id=ds)),
        preparator=("", PParams(id=prep)),
        algorithms=tuple((name, AParams(id=i)) for name, i in algos),
        serving=("", None))


class TestEngineTrain:
    def test_dataflow(self):
        reset_calls()
        r = make_engine().train(Context(), ep(ds=3, prep=5,
                                              algos=(("a1", 7), ("a2", 9))))
        assert r.models == [
            ("model", ("pd", TD(3), 5), 7),
            ("model", ("pd", TD(3), 5), 9),
        ]
        assert CALLS == {"read": 1, "prepare": 1, "train": 2}

    def test_sanity_check_raises(self):
        with pytest.raises(ValueError, match="datasource error flag"):
            make_engine().train(
                Context(), ep().copy(datasource=("", DSParams(error=True))))

    def test_sanity_check_skipped(self):
        r = make_engine().train(
            Context(skip_sanity_check=True),
            ep().copy(datasource=("", DSParams(error=True))))
        assert len(r.models) == 1

    def test_stop_after_read(self):
        reset_calls()
        r = make_engine().train(Context(stop_after_read=True), ep())
        assert r.models == []
        assert CALLS == {"read": 1, "prepare": 0, "train": 0}

    def test_unknown_algorithm_name(self):
        with pytest.raises(KeyError, match="algorithm"):
            make_engine().train(Context(), ep(algos=(("nope", 0),)))


class TestEngineEval:
    def test_eval_structure(self):
        res = make_engine().eval(Context(), ep(ds=1, algos=(("a1", 2),
                                                            ("a2", 4))))
        assert len(res) == 2  # folds
        ei, qpa = res[0]
        assert ei == ("ei", 0)
        assert len(qpa) == 3
        q, p, a = qpa[0]
        # serving combined both algorithms' params ids
        assert p == ("served", (0, 0), (2, 4))
        assert a == (0, 0)


class PrecisionMetric(AverageMetric):
    """Score 1.0 when the served prediction carries the query, else 0."""

    def calculate_point(self, ei, q, p, a):
        return 1.0 if p[1] == q else 0.0


class ParamSensitiveMetric(AverageMetric):
    """Higher algorithm param id ⇒ better score (to test selection)."""

    def calculate_point(self, ei, q, p, a):
        return float(sum(p[2]))


class TestMetricEvaluator:
    def test_best_selection(self):
        engine = make_engine()
        grid = [ep(algos=(("a1", i),)) for i in (1, 5, 3)]
        ev = Evaluation(engine=engine, metric=ParamSensitiveMetric())
        result = MetricEvaluator(ev).evaluate(Context(), grid)
        assert result.best_index == 1
        assert result.best_score == 5.0
        assert result.best_engine_params.algorithms[0][1].id == 5
        assert "best variant 1" in result.to_one_liner()

    def test_prefix_memoization(self):
        # same datasource+preparator across 3 params sets: read/prepare once;
        # two distinct algo params: 2 trainings per fold, not 3
        reset_calls()
        engine = make_engine()
        grid = [ep(algos=(("a1", 1),)), ep(algos=(("a1", 2),)),
                ep(algos=(("a1", 1),))]
        ev = Evaluation(engine=engine, metric=ParamSensitiveMetric())
        MetricEvaluator(ev).evaluate(Context(), grid)
        assert CALLS["read"] == 1
        assert CALLS["prepare"] == 2       # once per fold
        assert CALLS["train"] == 4         # 2 distinct params × 2 folds

    def test_other_metrics_reported(self):
        engine = make_engine()
        ev = Evaluation(engine=engine, metric=ParamSensitiveMetric(),
                        other_metrics=[PrecisionMetric()])
        result = MetricEvaluator(ev).evaluate(Context(), [ep()])
        assert result.scores[0].other_scores == [1.0]
        assert result.other_metric_headers == ["PrecisionMetric"]


class TestVariantParsing:
    def test_engine_json_shape(self):
        variant = {
            "id": "default",
            "engineFactory": "my.Engine",
            "datasource": {"params": {"id": 4}},
            "preparator": {"params": {"id": 2}},
            "algorithms": [
                {"name": "a1", "params": {"id": 9}},
                {"name": "a2", "params": {"id": 1}},
            ],
        }
        engine = make_engine()
        parsed = engine.params_from_variant(variant)
        assert parsed.datasource[1] == DSParams(id=4)
        assert parsed.preparator[1] == PParams(id=2)
        assert parsed.algorithms == (("a1", AParams(id=9)),
                                     ("a2", AParams(id=1)))

    def test_unknown_param_rejected(self):
        variant = {"datasource": {"params": {"nope": 1}}}
        with pytest.raises(ValueError, match="unknown field"):
            make_engine().params_from_variant(variant)

    def test_simple_engine(self):
        se = SimpleEngine(datasource_class=DS, algorithm_class=Algo)
        r = se.train(Context(), EngineParams())
        assert r.models == [("model", TD(0), 0)]
        assert isinstance(se.make_serving(EngineParams()), FirstServing)


class TestRetrainOnDeploy:
    def test_none_persistent_model_retrains(self):
        """An algorithm whose make_persistent_model returns None (the
        reference's Unit-model semantics) must be retrained by
        prepare_deploy (controller/Engine.scala:210-232)."""
        calls = {"train": 0}

        class EphemeralAlgo(Algo):
            def make_persistent_model(self, model, iid, ax):
                return None

            def train(self, ctx, pd):
                calls["train"] += 1
                return super().train(ctx, pd)

        engine = Engine(
            datasource_classes=DS,
            preparator_classes=Prep,
            algorithm_classes={"a1": EphemeralAlgo},
            serving_classes=ServeSum,
            datasource_params_class=DSParams,
            preparator_params_class=PParams,
        )
        params = ep()
        ctx = Context()
        result = engine.train(ctx, params)
        algo = engine.make_algorithms(params)[0]
        stored = algo.make_persistent_model(result.models[0], "iid", 0)
        assert stored is None
        trained_before = calls["train"]
        models = engine.prepare_deploy(ctx, params, [None], "iid")
        assert calls["train"] == trained_before + 1  # retrained
        assert models[0] is not None
