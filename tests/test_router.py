"""Autoscaling tier tests (ISSUE 18, docs/autoscaling.md): hash-ring
placement properties (the ≤1/N remap bound, order-independent
affinity), sketch-confirmed hot-key spill, the router's proxy behavior
over real backends (affinity, retry, ejection, drain), the replica
lifecycle state machine with injected fakes, and the autoscaler's
policy arithmetic (burn/headroom triggers, hysteresis, cooldown,
heal) under a fake clock."""

import hashlib
import json
import random
import threading
import time

import pytest

from predictionio_tpu import faults
from predictionio_tpu.obs import MetricsRegistry
from predictionio_tpu.router import (
    Autoscaler,
    AutoscalePolicy,
    HashRing,
    QueryRouter,
    ReplicaLifecycle,
    RouterConfig,
    key_point,
)
from predictionio_tpu.server.http import (
    AppServer,
    HTTPApp,
    HTTPError,
    Response,
    json_response,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()


MEMBERS = [f"10.0.0.{i}:8000" for i in range(10)]
KEYS = [f"user-{i}" for i in range(2000)]


# ---------------------------------------------------------------------------
# HashRing — consistency properties (ISSUE 18 satellite)
# ---------------------------------------------------------------------------

class TestHashRing:
    def test_key_point_is_sha256_derived(self):
        # same derivation as rollout.splitter.cohort_bucket: the first
        # 8 bytes of sha256, big-endian — deterministic across
        # processes (hash() would randomize per run)
        d = hashlib.sha256("user-1".encode("utf-8")).digest()
        assert key_point("user-1") == int.from_bytes(d[:8], "big")

    def test_assign_returns_a_member(self):
        ring = HashRing(MEMBERS)
        for k in KEYS[:100]:
            assert ring.assign(k) in MEMBERS

    def test_affinity_independent_of_membership_order(self):
        shuffled = list(MEMBERS)
        random.Random(7).shuffle(shuffled)
        a, b = HashRing(MEMBERS), HashRing(shuffled)
        assert [a.assign(k) for k in KEYS] == \
            [b.assign(k) for k in KEYS]

    def test_remove_remaps_only_the_lost_members_keys(self):
        ring = HashRing(MEMBERS)
        before = {k: ring.assign(k) for k in KEYS}
        victim = MEMBERS[3]
        ring.remove(victim)
        moved = 0
        for k in KEYS:
            after = ring.assign(k)
            if before[k] == victim:
                assert after != victim
                moved += 1
            else:
                # consistent hashing's defining property: keys NOT on
                # the removed member do not move at all
                assert after == before[k]
        # the victim held ~1/N of keys (vnode placement is uniform
        # enough at 64 vnodes to stay well inside 3x)
        assert 0 < moved <= 3 * len(KEYS) / len(MEMBERS)

    def test_add_remaps_at_most_about_1_over_n(self):
        ring = HashRing(MEMBERS)
        before = {k: ring.assign(k) for k in KEYS}
        ring.add("10.0.0.99:8000")
        moved = 0
        for k in KEYS:
            after = ring.assign(k)
            if after != before[k]:
                # a moved key can ONLY have moved to the new member
                assert after == "10.0.0.99:8000"
                moved += 1
        n = len(MEMBERS) + 1
        assert 0 < moved <= 3 * len(KEYS) / n

    def test_preference_lists_distinct_members(self):
        ring = HashRing(MEMBERS)
        for k in KEYS[:50]:
            pref = ring.preference(k, 4)
            assert len(pref) == 4
            assert len(set(pref)) == 4
            assert pref[0] == ring.assign(k)

    def test_preference_capped_at_member_count(self):
        ring = HashRing(MEMBERS[:2])
        assert len(ring.preference("k", 5)) == 2

    def test_empty_ring(self):
        ring = HashRing()
        assert ring.assign("k") is None
        assert ring.preference("k", 3) == []


# ---------------------------------------------------------------------------
# QueryRouter placement (no sockets)
# ---------------------------------------------------------------------------

def _router(**cfg) -> QueryRouter:
    r = QueryRouter(RouterConfig(**cfg))
    for m in ("127.0.0.1:9001", "127.0.0.1:9002", "127.0.0.1:9003"):
        r.add(m)
    return r


class TestRouterPlacement:
    def test_cold_key_routes_to_affinity(self):
        r = _router()
        ring = HashRing(r.members(), vnodes=r.config.vnodes)
        assert r.route_key("42") == ring.assign("42")
        # stable across calls
        assert r.route_key("42") == r.route_key("42")

    def test_spill_requires_sketch_confirmation(self):
        # ISSUE 18 satellite: spill triggers ONLY for keys the
        # Space-Saving sketch confirms hot (error-adjusted lower
        # bound over spill_share of traffic) — never for a cold key,
        # never before spill_min_total observations
        r = _router(spill_share=0.2, spill_min_total=50,
                    spill_fanout=2)
        for _ in range(10):
            r.hot.record("viral")
        _, spilled = r.candidates("viral")
        assert not spilled            # under spill_min_total
        for i in range(100):
            r.hot.record("viral")
            r.hot.record(f"cold-{i}")
        cands, spilled = r.candidates("viral")
        assert spilled
        assert len(set(cands[:2])) == 2   # fanout-wide spill set
        _, spilled = r.candidates("cold-1")
        assert not spilled            # 1 observation is not a hot spot

    def test_drain_stops_new_assignments(self):
        r = _router()
        first = r.route_key("42")
        r.drain(first)
        assert r.route_key("42") != first
        assert first not in r.members()
        # the backend still exists for its in-flight accounting
        assert r.inflight(first) == 0
        st = {b["replica"]: b["state"]
              for b in r.status()["replicas"]}
        assert st[first] == "draining"

    def test_remove_forgets_the_backend(self):
        r = _router()
        victim = r.route_key("42")
        assert r.remove(victim)
        assert victim not in r.members()
        assert victim not in {b["replica"]
                              for b in r.status()["replicas"]}

    def test_health_veto_reroutes(self):
        r = _router()
        first = r.route_key("42")
        r.set_health(lambda name: name != first)
        assert r.route_key("42") != first
        # veto everything -> no opinion wins, traffic still flows
        r.set_health(lambda name: False)
        assert r.route_key("42") is not None

    def test_keyless_queries_rotate(self):
        r = _router()
        seen = {r.route_key(None) for _ in range(10)}
        assert seen == set(r.members())


# ---------------------------------------------------------------------------
# QueryRouter forwarding over real backends
# ---------------------------------------------------------------------------

def _backend(name: str, behavior: str = "ok"):
    app = HTTPApp(name=f"backend-{name}")
    hits = []

    @app.route("POST", "/queries.json")
    def q(req):
        hits.append(json.loads(req.body.decode("utf-8")))
        if behavior == "shed":
            return Response(status=503, body={"error": "shed"},
                            headers={"Retry-After": "0.05"})
        return json_response({"replica": name})

    srv = AppServer(app, host="127.0.0.1", port=0)
    srv.start_background()
    return srv, hits


@pytest.fixture()
def trio():
    servers = [_backend(f"b{i}") for i in range(3)]
    router = QueryRouter(RouterConfig(retries=1, eject_failures=2,
                                      timeout_sec=5.0),
                         registry=MetricsRegistry())
    for srv, _ in servers:
        router.add(f"127.0.0.1:{srv.port}")
    yield router, servers
    for srv, _ in servers:
        srv.shutdown()


def _fwd(router, user="7"):
    body = json.dumps({"user": user, "num": 1}).encode("utf-8")
    return router.forward("/queries.json", body, {})


class TestRouterForward:
    def test_affinity_lands_on_one_backend(self, trio):
        router, servers = trio
        for _ in range(6):
            resp = _fwd(router)
            assert resp.status == 200
        counts = [len(hits) for _, hits in servers]
        assert sorted(counts) == [0, 0, 6]
        assert resp.headers["X-Routed-To"] == router.route_key("7")

    def test_transport_failure_retries_next_replica(self, trio):
        router, servers = trio
        target = router.route_key("7")
        faults.inject("router.forward", "error",
                      match={"replica": target})
        resp = _fwd(router)
        assert resp.status == 200
        assert resp.headers["X-Routed-To"] != target
        assert resp.headers["X-Routed-Retry"] == "1"
        fam = router.registry.get("pio_router_retries_total")
        assert sum(c.value for _, c in fam.children()) == 1.0

    def test_repeated_failures_eject_the_replica(self, trio):
        router, servers = trio
        target = router.route_key("7")
        faults.inject("router.forward", "error",
                      match={"replica": target})
        for _ in range(3):
            assert _fwd(router).status == 200
        fam = router.registry.get("pio_router_ejections_total")
        ejected = {dict(items).get("replica"): c.value
                   for items, c in fam.children()}
        assert ejected.get(target, 0) >= 1.0
        # while ejected the replica is skipped outright: no retry hop
        faults.clear()
        faults.inject("router.forward", "error",
                      match={"replica": target})
        resp = _fwd(router)
        assert resp.status == 200
        assert "X-Routed-Retry" not in resp.headers

    def test_all_replicas_dead_is_503(self, trio):
        router, servers = trio
        faults.inject("router.forward", "error")
        with pytest.raises(HTTPError) as err:
            _fwd(router)
        assert err.value.status == 503

    def test_503_shed_retries_on_next(self):
        shedder, _ = _backend("shed", behavior="shed")
        ok, ok_hits = _backend("ok")
        router = QueryRouter(RouterConfig(retries=1),
                             registry=MetricsRegistry())
        # force preference order: shedder first
        router.add(f"127.0.0.1:{shedder.port}")
        router.add(f"127.0.0.1:{ok.port}")
        try:
            hit_ok = 0
            for i in range(8):
                resp = _fwd(router, user=str(i))
                assert resp.status == 200
                if json.loads(resp.encoded())["replica"] == "ok":
                    hit_ok += 1
            assert hit_ok == 8  # every shed hop landed on the survivor
        finally:
            shedder.shutdown()
            ok.shutdown()

    def test_draining_backend_finishes_inflight(self, trio):
        router, servers = trio
        target = router.route_key("7")
        router.drain(target)
        resp = _fwd(router)   # re-routed, not failed
        assert resp.status == 200
        assert resp.headers["X-Routed-To"] != target


# ---------------------------------------------------------------------------
# ReplicaLifecycle state machine (injected fakes, no sockets)
# ---------------------------------------------------------------------------

class _FakeRouter:
    def __init__(self):
        self.added, self.drained, self.removed = [], [], []
        self.inflight_by = {}

    def add(self, base):
        self.added.append(base)

    def drain(self, name):
        self.drained.append(name)

    def remove(self, name):
        self.removed.append(name)

    def inflight(self, name):
        return self.inflight_by.get(name, 0)


class _FakeAgg:
    def __init__(self):
        self.added, self.removed = [], []

    def add_replica(self, base):
        self.added.append(base)

    def remove_replica(self, name):
        self.removed.append(name)


def _lifecycle(spawn, warm, **kw):
    router, agg = _FakeRouter(), _FakeAgg()
    lc = ReplicaLifecycle(
        spawn, router=router, aggregator=agg,
        probe=lambda base, t: {"servingWarm": warm.get(
            base.split("://", 1)[1], False)},
        notify_drain=lambda base, t: None,
        poll_interval_sec=0.01, **kw)
    return lc, router, agg


class TestReplicaLifecycle:
    def test_warm_gates_ring_entry(self):
        warm = {}
        lc, router, agg = _lifecycle(
            lambda: ("127.0.0.1:9500", lambda: None), warm,
            warm_timeout_sec=5.0)
        lc.scale_out("test")
        time.sleep(0.05)
        assert lc.count("warming") == 1
        assert router.added == []        # NOT in the ring yet
        warm["127.0.0.1:9500"] = True
        assert lc.await_ready(1, timeout_sec=5.0)
        assert router.added == ["http://127.0.0.1:9500"]
        assert agg.added == ["http://127.0.0.1:9500"]
        lc.close()

    def test_warm_timeout_is_dead_not_ready(self):
        stopped = []
        lc, router, agg = _lifecycle(
            lambda: ("127.0.0.1:9501", lambda: stopped.append(1)),
            {}, warm_timeout_sec=0.05)
        lc.scale_out("test")
        deadline = time.time() + 5
        while time.time() < deadline and not stopped:
            time.sleep(0.01)
        assert stopped == [1]
        assert router.added == []
        assert lc.live_count() == 0
        lc.close()

    def test_spawn_failure_is_contained(self):
        def bad_spawn():
            raise RuntimeError("no capacity")
        lc, router, agg = _lifecycle(bad_spawn, {})
        lc.scale_out("test")
        time.sleep(0.1)
        assert lc.live_count() == 0
        assert router.added == []
        lc.close()

    def test_drain_waits_for_inflight_then_stops(self):
        stopped = []
        warm = {"127.0.0.1:9502": True}
        lc, router, agg = _lifecycle(
            lambda: ("127.0.0.1:9502", lambda: stopped.append(1)),
            warm, drain_deadline_sec=5.0)
        lc.scale_out("t")
        assert lc.await_ready(1, 5.0)
        router.inflight_by["127.0.0.1:9502"] = 2
        assert lc.scale_in(reason="test") == "127.0.0.1:9502"
        assert router.drained == ["127.0.0.1:9502"]
        time.sleep(0.08)
        assert not stopped               # in-flight work still running
        router.inflight_by["127.0.0.1:9502"] = 0
        deadline = time.time() + 5
        while time.time() < deadline and not stopped:
            time.sleep(0.01)
        assert stopped == [1]
        assert router.removed == ["127.0.0.1:9502"]
        assert agg.removed == ["127.0.0.1:9502"]
        lc.close()

    def test_drain_deadline_forces_the_stop(self):
        stopped = []
        warm = {"127.0.0.1:9503": True}
        lc, router, agg = _lifecycle(
            lambda: ("127.0.0.1:9503", lambda: stopped.append(1)),
            warm, drain_deadline_sec=0.05)
        lc.scale_out("t")
        assert lc.await_ready(1, 5.0)
        router.inflight_by["127.0.0.1:9503"] = 99   # never drains
        lc.scale_in(reason="stuck")
        deadline = time.time() + 5
        while time.time() < deadline and not stopped:
            time.sleep(0.01)
        assert stopped == [1]
        lc.close()

    def test_mark_dead_skips_drain(self):
        stopped = []
        warm = {"127.0.0.1:9504": True}
        lc, router, agg = _lifecycle(
            lambda: ("127.0.0.1:9504", lambda: stopped.append(1)),
            warm)
        lc.scale_out("t")
        assert lc.await_ready(1, 5.0)
        assert lc.mark_dead("127.0.0.1:9504", "chaos")
        assert stopped == [1]
        assert router.removed == ["127.0.0.1:9504"]
        assert lc.live_count() == 0
        lc.close()

    def test_adopt_warm_joins_immediately(self):
        lc, router, agg = _lifecycle(lambda: ("x", None), {})
        lc.adopt("127.0.0.1:9505")
        assert lc.count("ready") == 1
        assert router.added == ["http://127.0.0.1:9505"]
        lc.close()

    def test_transition_metrics(self):
        reg = MetricsRegistry()
        warm = {"127.0.0.1:9506": True}
        router, agg = _FakeRouter(), _FakeAgg()
        lc = ReplicaLifecycle(
            lambda: ("127.0.0.1:9506", lambda: None),
            router=router, aggregator=agg, registry=reg,
            probe=lambda b, t: {"servingWarm": True},
            notify_drain=lambda b, t: None,
            poll_interval_sec=0.01)
        lc.scale_out("t")
        assert lc.await_ready(1, 5.0)
        fam = reg.get("pio_autoscale_transitions_total")
        by_state = {dict(items)["to"]: c.value
                    for items, c in fam.children()}
        assert by_state.get("ready") == 1.0
        gauge = reg.get("pio_autoscale_replicas")
        vals = {dict(items)["state"]: c.value
                for items, c in gauge.children()}
        assert vals["ready"] == 1.0
        lc.close()


# ---------------------------------------------------------------------------
# Autoscaler policy (fake clock, fake signals)
# ---------------------------------------------------------------------------

class _FakeSLO:
    def __init__(self):
        self.fast = []

    def fast_burning(self):
        return list(self.fast)


class _SignalAgg:
    """Just the aggregator surface the autoscaler consumes."""

    def __init__(self):
        self.headroom = None
        self.qps = 0.0
        self.knee = 100.0
        self.slo = _FakeSLO()
        self.health = {}

    def capacity_signals(self):
        return {"qps": self.qps, "kneeQps": self.knee,
                "headroom": self.headroom}

    def replica_health(self, name):
        return self.health.get(name, "up")


def _autoscaled(policy=None, n=2):
    agg = _SignalAgg()
    router, fagg = _FakeRouter(), _FakeAgg()
    warm = {}
    counter = iter(range(9600, 9700))

    def spawn():
        spec = f"127.0.0.1:{next(counter)}"
        warm[spec] = True
        return spec, lambda: None

    lc = ReplicaLifecycle(
        spawn, router=router, aggregator=fagg,
        probe=lambda base, t: {"servingWarm": warm.get(
            base.split("://", 1)[1], False)},
        notify_drain=lambda base, t: None,
        poll_interval_sec=0.01, drain_deadline_sec=0.05)
    for i in range(n):
        lc.adopt(f"127.0.0.1:{9590 + i}")
    clk = [1000.0]
    asc = Autoscaler(agg, lc, policy or AutoscalePolicy(
        min_replicas=1, max_replicas=4, headroom_floor=0.15,
        headroom_ceiling=0.60, scale_in_sustain_sec=10.0,
        cooldown_sec=30.0), clock=lambda: clk[0])
    return asc, agg, lc, clk


def _settle(lc, n, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline and lc.live_count() != n:
        time.sleep(0.01)
    assert lc.live_count() == n, lc.counts()


class TestAutoscaler:
    def test_holds_with_no_signals(self):
        asc, agg, lc, clk = _autoscaled()
        d = asc.evaluate()
        assert d["action"] == "hold"
        assert d["target"] == 2
        lc.close()

    def test_scale_out_on_fast_burn(self):
        asc, agg, lc, clk = _autoscaled()
        agg.slo.fast = ["queries-availability"]
        d = asc.evaluate()
        assert d["action"] == "scale_out"
        assert "fast burn" in d["reason"]
        _settle(lc, 3)
        lc.close()

    def test_scale_out_on_low_headroom(self):
        asc, agg, lc, clk = _autoscaled()
        agg.headroom = 0.05
        d = asc.evaluate()
        assert d["action"] == "scale_out"
        assert "headroom" in d["reason"]
        _settle(lc, 3)
        lc.close()

    def test_no_model_means_no_headroom_action(self):
        asc, agg, lc, clk = _autoscaled()
        agg.headroom = None      # no CAPACITY.json
        assert asc.evaluate()["action"] == "hold"
        lc.close()

    def test_scale_in_needs_sustained_ceiling(self):
        asc, agg, lc, clk = _autoscaled()
        agg.headroom = 0.9
        assert asc.evaluate()["action"] == "hold"   # not sustained yet
        clk[0] += 5.0
        assert asc.evaluate()["action"] == "hold"   # still inside window
        clk[0] += 6.0
        d = asc.evaluate()                          # 11s over ceiling
        assert d["action"] == "scale_in"
        _settle(lc, 1)
        lc.close()

    def test_cooldown_blocks_consecutive_policy_actions(self):
        asc, agg, lc, clk = _autoscaled(n=2)
        agg.headroom = 0.05
        assert asc.evaluate()["action"] == "scale_out"
        _settle(lc, 3)
        d = asc.evaluate()
        assert d["action"] == "hold"                # cooling down
        clk[0] += 31.0
        assert asc.evaluate()["action"] == "scale_out"
        lc.close()

    def test_hysteresis_band_prevents_flap(self):
        # headroom between floor and ceiling must trigger NOTHING in
        # either direction, ever
        asc, agg, lc, clk = _autoscaled()
        agg.headroom = 0.4
        for _ in range(5):
            clk[0] += 60.0
            assert asc.evaluate()["action"] == "hold"
        lc.close()

    def test_max_replicas_caps_scale_out(self):
        asc, agg, lc, clk = _autoscaled(
            policy=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                   cooldown_sec=0.0), n=2)
        agg.slo.fast = ["x"]
        assert asc.evaluate()["action"] == "hold"
        assert lc.live_count() == 2
        lc.close()

    def test_min_replicas_floors_scale_in(self):
        asc, agg, lc, clk = _autoscaled(
            policy=AutoscalePolicy(min_replicas=2, max_replicas=4,
                                   scale_in_sustain_sec=0.0,
                                   cooldown_sec=0.0), n=2)
        agg.headroom = 0.95
        clk[0] += 1.0
        assert asc.evaluate()["action"] == "hold"
        assert lc.live_count() == 2
        lc.close()

    def test_burning_vetoes_scale_in(self):
        asc, agg, lc, clk = _autoscaled(
            policy=AutoscalePolicy(min_replicas=1, max_replicas=4,
                                   scale_in_sustain_sec=0.0,
                                   cooldown_sec=0.0))
        agg.headroom = 0.95
        agg.slo.fast = ["queries-latency"]
        clk[0] += 1.0
        d = asc.evaluate()
        assert d["action"] != "scale_in"
        lc.close()

    def test_replace_dead_bypasses_cooldown(self):
        asc, agg, lc, clk = _autoscaled()
        agg.headroom = 0.05
        asc.evaluate()                               # starts cooldown
        _settle(lc, 3)
        corpse = lc.names("ready")[0]
        agg.health[corpse] = "down"
        d = asc.evaluate()
        assert d["action"] == "replace"
        assert corpse in d["reason"]
        _settle(lc, 3)                               # replaced
        assert corpse not in lc.names()
        lc.close()

    def test_manual_target_converges_and_logs(self):
        asc, agg, lc, clk = _autoscaled()
        assert asc.request_target(9, "ops") == 4     # clamped to max
        d = asc.evaluate()
        assert d["action"] == "manual"
        _settle(lc, 4)
        st = asc.status()
        assert st["target"] == 4
        assert any(x["action"] == "manual" for x in st["decisions"])
        lc.close()

    def test_scale_in_records_intentional_exits(self):
        asc, agg, lc, clk = _autoscaled(
            policy=AutoscalePolicy(min_replicas=1, max_replicas=4,
                                   scale_in_sustain_sec=0.0,
                                   cooldown_sec=0.0))
        agg.headroom = 0.95
        clk[0] += 1.0
        asc.evaluate()
        _settle(lc, 1)
        deadline = time.time() + 5
        while time.time() < deadline and not asc.status()["removed"]:
            time.sleep(0.01)
        removed = asc.status()["removed"]
        assert len(removed) == 1     # the decision-log source ptpu
        lc.close()                   # fleet status consults

    def test_decisions_are_bounded_and_sequenced(self):
        asc, agg, lc, clk = _autoscaled()
        agg.slo.fast = ["x"]
        seqs = []
        for _ in range(3):
            clk[0] += 31.0
            seqs.append(asc.evaluate()["seq"])
        assert seqs == sorted(seqs)
        assert len(asc.status()["decisions"]) <= asc.LOG_LIMIT
        lc.close()
