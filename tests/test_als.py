"""ALS correctness tests: packing, normal-equation exactness vs a dense
numpy reference, convergence on synthetic low-rank data, implicit mode,
and sharded-vs-single-device equivalence on the 8-device CPU mesh."""

import numpy as np
import pytest

from predictionio_tpu.models.als import (
    ALSModel,
    ALSParams,
    RatingsCOO,
    recommend_batch,
    recommend_products,
    train_als,
)
from predictionio_tpu.ops.ragged import pack_histories


def make_synthetic(n_users=60, n_items=40, rank=4, density=0.4, seed=0,
                   noise=0.01):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    V = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = U @ V.T
    mask = rng.random((n_users, n_items)) < density
    users, items = np.nonzero(mask)
    vals = full[users, items] + noise * rng.normal(size=users.shape)
    return RatingsCOO(users.astype(np.int32), items.astype(np.int32),
                      vals.astype(np.float32), n_users, n_items), full, mask


class TestPackHistories:
    def test_basic(self):
        rows = np.array([0, 2, 0, 2, 2])
        cols = np.array([5, 6, 7, 8, 9])
        vals = np.array([1., 2., 3., 4., 5.])
        h = pack_histories(rows, cols, vals, n_rows=3)
        assert h.indices.shape == (3, 3)
        assert h.counts.tolist() == [2, 0, 3]
        assert sorted(h.indices[0, :2].tolist()) == [5, 7]
        assert h.indices[1].tolist() == [0, 0, 0]
        assert sorted(h.indices[2].tolist()) == [6, 8, 9]

    def test_max_len_cap(self):
        rows = np.array([0, 0, 0, 0])
        cols = np.array([1, 2, 3, 4])
        vals = np.ones(4)
        h = pack_histories(rows, cols, vals, n_rows=1, max_len=2)
        assert h.max_len == 2
        assert h.counts.tolist() == [2]

    def test_pad_rows_to(self):
        rows = np.array([0, 1, 2])
        h = pack_histories(rows, rows, np.ones(3), n_rows=3, pad_rows_to=8)
        assert h.n_rows == 8
        assert h.counts[3:].tolist() == [0] * 5


def explicit_als_reference(ratings, rank, iters, reg, seed,
                           scale_reg=True):
    """Dense numpy ALS-WR — the oracle the TPU path must match."""
    import jax
    ku, ki = jax.random.split(jax.random.key(seed))
    U = np.asarray(jax.random.normal(ku, (ratings.n_users, rank))) / np.sqrt(rank)
    V = np.asarray(jax.random.normal(ki, (ratings.n_items, rank))) / np.sqrt(rank)
    R = np.zeros((ratings.n_users, ratings.n_items), dtype=np.float64)
    M = np.zeros_like(R)
    R[ratings.users, ratings.items] = ratings.ratings
    M[ratings.users, ratings.items] = 1.0
    for _ in range(iters):
        for u in range(ratings.n_users):
            m = M[u] > 0
            n_u = max(m.sum(), 1)
            Vm = V[m]
            A = Vm.T @ Vm + (reg * n_u if scale_reg else reg) * np.eye(rank) \
                + 1e-6 * np.eye(rank)
            U[u] = np.linalg.solve(A, Vm.T @ R[u, m]) if m.any() else \
                np.linalg.solve(A, np.zeros(rank))
        for i in range(ratings.n_items):
            m = M[:, i] > 0
            n_i = max(m.sum(), 1)
            Um = U[m]
            A = Um.T @ Um + (reg * n_i if scale_reg else reg) * np.eye(rank) \
                + 1e-6 * np.eye(rank)
            V[i] = np.linalg.solve(A, Um.T @ R[m, i]) if m.any() else \
                np.linalg.solve(A, np.zeros(rank))
    return U, V


class TestExplicitALS:
    def test_matches_dense_reference(self):
        ratings, _, _ = make_synthetic(n_users=20, n_items=15, rank=3)
        params = ALSParams(rank=3, num_iterations=3, reg=0.1, seed=7)
        U, V = train_als(ratings, params)
        U_ref, V_ref = explicit_als_reference(ratings, 3, 3, 0.1, seed=7)
        np.testing.assert_allclose(np.asarray(U)[:20], U_ref, rtol=2e-3,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(V)[:15], V_ref, rtol=2e-3,
                                   atol=2e-4)

    def test_convergence_on_low_rank(self):
        ratings, full, mask = make_synthetic(seed=1)
        params = ALSParams(rank=4, num_iterations=10, reg=0.01, seed=3)
        U, V = train_als(ratings, params)
        pred = np.asarray(U)[:ratings.n_users] @ np.asarray(V)[:ratings.n_items].T
        rmse = np.sqrt(((pred - full)[mask] ** 2).mean())
        assert rmse < 0.08, f"train RMSE too high: {rmse}"

    def test_blocked_updates_match_single_block(self):
        ratings, _, _ = make_synthetic(n_users=40, n_items=30, rank=3, seed=6)
        p1 = ALSParams(rank=3, num_iterations=3, reg=0.05, seed=5)
        p2 = ALSParams(rank=3, num_iterations=3, reg=0.05, seed=5,
                       block_rows=7)  # forces multi-block path
        U1, V1 = train_als(ratings, p1)
        U2, V2 = train_als(ratings, p2)
        np.testing.assert_allclose(np.asarray(U2), np.asarray(U1),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(V2), np.asarray(V1),
                                   rtol=1e-4, atol=1e-6)

    def test_blocked_sharded_matches(self, mesh8):
        ratings, _, _ = make_synthetic(n_users=48, n_items=32, rank=3, seed=7)
        p = ALSParams(rank=3, num_iterations=2, reg=0.05, seed=5,
                      block_rows=2)
        U1, V1 = train_als(ratings, ALSParams(rank=3, num_iterations=2,
                                              reg=0.05, seed=5))
        U8, V8 = train_als(ratings, p, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(U8)[:48], np.asarray(U1)[:48],
                                   rtol=1e-3, atol=1e-5)

    def test_sharded_matches_single_device(self, mesh8):
        ratings, _, _ = make_synthetic(n_users=32, n_items=24, rank=3, seed=2)
        params = ALSParams(rank=3, num_iterations=3, reg=0.05, seed=5)
        U1, V1 = train_als(ratings, params)
        U8, V8 = train_als(ratings, params, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(U8)[:32], np.asarray(U1)[:32],
                                   rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(V8)[:24], np.asarray(V1)[:24],
                                   rtol=1e-3, atol=1e-5)


class TestImplicitALS:
    def test_ranks_observed_above_unobserved(self):
        # user 0 interacts with items 0..4 heavily; never with 15..19
        users, items, vals = [], [], []
        rng = np.random.default_rng(0)
        for u in range(30):
            liked = rng.choice(10, size=5, replace=False) if u % 2 == 0 \
                else rng.choice(np.arange(10, 20), size=5, replace=False)
            for i in liked:
                users.append(u)
                items.append(i)
                vals.append(1.0)
        ratings = RatingsCOO(np.array(users, np.int32),
                             np.array(items, np.int32),
                             np.array(vals, np.float32), 30, 20)
        params = ALSParams(rank=8, num_iterations=10, reg=0.01, alpha=40.0,
                           implicit_prefs=True, seed=1)
        U, V = train_als(ratings, params)
        pred = np.asarray(U)[:30] @ np.asarray(V)[:20].T
        # even-indexed users prefer items 0-9 on average
        even_pref = pred[0::2, :10].mean() - pred[0::2, 10:].mean()
        odd_pref = pred[1::2, 10:].mean() - pred[1::2, :10].mean()
        assert even_pref > 0.3
        assert odd_pref > 0.3

    def test_implicit_sharded_matches(self, mesh8):
        rng = np.random.default_rng(3)
        nnz = 200
        ratings = RatingsCOO(
            rng.integers(0, 25, nnz).astype(np.int32),
            rng.integers(0, 18, nnz).astype(np.int32),
            np.ones(nnz, np.float32), 25, 18)
        params = ALSParams(rank=4, num_iterations=2, reg=0.1, alpha=10.0,
                           implicit_prefs=True, seed=2)
        U1, V1 = train_als(ratings, params)
        U8, V8 = train_als(ratings, params, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(U8)[:25], np.asarray(U1)[:25],
                                   rtol=1e-3, atol=1e-5)


class TestRecommend:
    def _model(self):
        ratings, _, _ = make_synthetic(seed=4)
        params = ALSParams(rank=4, num_iterations=5, reg=0.01, seed=0)
        U, V = train_als(ratings, params)
        return ALSModel(user_factors=U, item_factors=V,
                        n_users=ratings.n_users, n_items=ratings.n_items,
                        params=params), ratings

    def test_topk_shapes_and_order(self):
        model, ratings = self._model()
        ids, scores = recommend_products(model, 0, 10)
        assert ids.shape == (10,)
        assert all(scores[i] >= scores[i + 1] for i in range(9))
        assert all(0 <= i < ratings.n_items for i in ids)

    def test_topk_matches_numpy(self):
        model, ratings = self._model()
        ids, scores = recommend_products(model, 3, 5)
        full = np.asarray(model.user_factors)[3] @ \
            np.asarray(model.item_factors)[:ratings.n_items].T
        np_top = np.argsort(-full)[:5]
        np.testing.assert_array_equal(ids, np_top)

    def test_batch_matches_single(self):
        model, _ = self._model()
        ids_b, scores_b = recommend_batch(model, np.array([0, 3, 7]), 4)
        for row, u in enumerate([0, 3, 7]):
            ids_s, scores_s = recommend_products(model, u, 4)
            np.testing.assert_array_equal(ids_b[row], ids_s)
            np.testing.assert_allclose(scores_b[row], scores_s, rtol=1e-6)

    def test_batch_axis_padded_to_pow2_shapes(self):
        """Serving-path jit-cache bound: arbitrary micro-batch sizes
        must collapse onto power-of-two compiled shapes (each novel
        [B, r] shape is a fresh XLA compile — measured 10-20s through
        the device tunnel, the round-4 microbatch p90 pathology)."""
        from predictionio_tpu.models.als import _topk_scores

        model, _ = self._model()
        # force the device path regardless of model size heuristics
        import predictionio_tpu.models.als as als

        orig = als._serve_on_host
        als._serve_on_host = lambda *a, **k: False
        try:
            before = _topk_scores._cache_size()
            for batch in ([0], [0, 1], [0, 1, 2], [0, 1, 2, 3],
                          [0] * 5, [0] * 7):
                ids, _ = recommend_batch(model, np.array(batch), 3)
                assert ids.shape[0] == len(batch)
            added = _topk_scores._cache_size() - before
            # sizes {1,2,3,4,5,7} collapse to padded {1,2,4,8}
            assert added <= 4, f"cache grew by {added} (> 4 shapes)"
        finally:
            als._serve_on_host = orig

    def test_padded_items_never_recommended(self, mesh8):
        ratings, _, _ = make_synthetic(n_users=16, n_items=10, seed=5)
        params = ALSParams(rank=3, num_iterations=2, seed=0)
        U, V = train_als(ratings, params, mesh=mesh8)
        model = ALSModel(user_factors=np.asarray(U), item_factors=np.asarray(V),
                         n_users=16, n_items=10, params=params)
        assert np.asarray(V).shape[0] >= 16  # actually padded
        ids, _ = recommend_products(model, 0, 10)
        assert ids.max() < 10


class TestBF16MatmulPath:
    def test_bf16_preserves_preference_structure(self):
        """bfloat16 MXU einsums (f32 accumulation) must not degrade the
        learned preference structure."""
        users, items, vals = [], [], []
        rng = np.random.default_rng(0)
        for u in range(30):
            liked = rng.choice(10, size=5, replace=False) if u % 2 == 0 \
                else rng.choice(np.arange(10, 20), size=5, replace=False)
            for i in liked:
                users.append(u)
                items.append(i)
                vals.append(1.0)
        ratings = RatingsCOO(np.array(users, np.int32),
                             np.array(items, np.int32),
                             np.array(vals, np.float32), 30, 20)
        params = ALSParams(rank=8, num_iterations=10, reg=0.01, alpha=40.0,
                           implicit_prefs=True, seed=1,
                           matmul_dtype="bfloat16")
        U, V = train_als(ratings, params)
        pred = np.asarray(U)[:30] @ np.asarray(V)[:20].T
        even_pref = pred[0::2, :10].mean() - pred[0::2, 10:].mean()
        odd_pref = pred[1::2, 10:].mean() - pred[1::2, :10].mean()
        assert even_pref > 0.3
        assert odd_pref > 0.3

    def test_bf16_close_to_f32_explicit(self):
        ratings, _, _ = make_synthetic(seed=3)
        f32 = ALSParams(rank=4, num_iterations=6, reg=0.05, seed=2)
        b16 = ALSParams(rank=4, num_iterations=6, reg=0.05, seed=2,
                        matmul_dtype="bfloat16")
        U1, V1 = train_als(ratings, f32)
        U2, V2 = train_als(ratings, b16)
        p1 = np.asarray(U1) @ np.asarray(V1).T
        p2 = np.asarray(U2) @ np.asarray(V2).T
        # predictions agree to bf16-level tolerance
        assert np.abs(p1 - p2).mean() < 0.05 * max(np.abs(p1).mean(), 1.0)


class TestHostServeParity:
    def _model(self, n_items=40):
        ratings, _, _ = make_synthetic(n_items=n_items, seed=5)
        params = ALSParams(rank=4, num_iterations=5, reg=0.05, seed=2)
        U, V = train_als(ratings, params)
        from predictionio_tpu.models.als import ALSModel
        return (ALSModel(user_factors=np.asarray(U),
                         item_factors=np.asarray(V), n_users=60,
                         n_items=n_items, user_ids=None, item_ids=None,
                         params=params),
                ALSModel(user_factors=U, item_factors=V, n_users=60,
                         n_items=n_items, user_ids=None, item_ids=None,
                         params=params))

    def test_host_matches_device(self):
        from predictionio_tpu.models.als import (
            recommend_batch,
            recommend_products,
        )

        host, dev = self._model()
        for u in (0, 13, 42):
            ih, sh = recommend_products(host, u, 7)
            idv, sv = recommend_products(dev, u, 7)
            assert list(np.asarray(ih)) == list(np.asarray(idv))
            np.testing.assert_allclose(np.asarray(sh), np.asarray(sv),
                                       rtol=1e-5)
        bh = recommend_batch(host, np.array([0, 13]), 5)
        bd = recommend_batch(dev, np.array([0, 13]), 5)
        np.testing.assert_array_equal(np.asarray(bh[0]),
                                      np.asarray(bd[0]))

    def test_tie_break_lowest_index(self):
        """Duplicate factor rows: host path must prefer the lowest item
        index, like lax.top_k."""
        from predictionio_tpu.models.als import _host_topk

        V = np.ones((6, 4), dtype=np.float32)  # all items tie
        u = np.ones((1, 4), dtype=np.float32)
        ids, scores = _host_topk(u, V, k=3, n_items=6)
        assert ids[0].tolist() == [0, 1, 2]

    def test_work_gate_scales_with_batch(self):
        from predictionio_tpu.models.als import (
            HOST_SERVE_WORK,
            _serve_on_host,
        )

        host, _ = self._model()
        size = host.item_factors.size
        assert _serve_on_host(host, batch=1)
        assert not _serve_on_host(host, batch=HOST_SERVE_WORK // size + 1)


class TestSplitHistories:
    """Split (drop-free) history mode — VERDICT r1 task 3."""

    def test_pack_split_covers_every_entry(self):
        from predictionio_tpu.ops.ragged import pack_histories_split

        rng = np.random.default_rng(0)
        rows = rng.integers(0, 10, 500).astype(np.int32)
        cols = rng.integers(0, 50, 500).astype(np.int32)
        vals = rng.random(500).astype(np.float32)
        h = pack_histories_split(rows, cols, vals, n_rows=10, max_len=8)
        # every entry present exactly once, attributed to the right row
        got = []
        for v in range(h.n_virtual):
            r = int(h.row_ids[v])
            if r >= 10:
                assert h.counts[v] == 0
                continue
            for k in range(int(h.counts[v])):
                got.append((r, int(h.indices[v, k]),
                            float(np.float32(h.values[v, k]))))
        want = sorted(zip(rows.tolist(), cols.tolist(),
                          [float(np.float32(v)) for v in vals]))
        assert sorted(got) == want
        assert h.real_counts[:10].tolist() == \
            np.bincount(rows, minlength=10).tolist()

    def test_device_pack_matches_host(self):
        from predictionio_tpu.ops.ragged import (
            pack_histories_split,
            pack_histories_split_device,
        )

        rng = np.random.default_rng(1)
        rows = rng.integers(0, 7, 200).astype(np.int32)
        cols = rng.integers(0, 20, 200).astype(np.int32)
        vals = rng.random(200).astype(np.float32)
        hh = pack_histories_split(rows, cols, vals, 7, 16, pad_rows_to=4)
        hd = pack_histories_split_device(rows, cols, vals, 7, 16,
                                         pad_rows_to=4)
        np.testing.assert_array_equal(hh.indices, np.asarray(hd.indices))
        np.testing.assert_array_equal(hh.values, np.asarray(hd.values))
        np.testing.assert_array_equal(hh.counts, np.asarray(hd.counts))
        np.testing.assert_array_equal(hh.row_ids, np.asarray(hd.row_ids))
        np.testing.assert_array_equal(hh.real_counts,
                                      np.asarray(hd.real_counts))

    def test_split_matches_pad_explicit(self):
        ratings, _, _ = make_synthetic(n_users=25, n_items=18, rank=3,
                                       seed=11)
        base = dict(rank=3, num_iterations=4, reg=0.05, seed=5)
        U_p, V_p = train_als(ratings, ALSParams(**base,
                                                history_mode="pad"))
        # max_history=4 in split mode splits rows, drops nothing
        U_s, V_s = train_als(ratings, ALSParams(**base, max_history=4,
                                                history_mode="split"))
        np.testing.assert_allclose(np.asarray(U_s)[:25],
                                   np.asarray(U_p)[:25], rtol=2e-3,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(V_s)[:18],
                                   np.asarray(V_p)[:18], rtol=2e-3,
                                   atol=2e-4)

    def test_split_matches_pad_implicit(self):
        ratings, _, _ = make_synthetic(n_users=22, n_items=16, rank=3,
                                       seed=12)
        ratings = RatingsCOO(ratings.users, ratings.items,
                             np.abs(ratings.ratings) + 0.1,
                             ratings.n_users, ratings.n_items)
        base = dict(rank=3, num_iterations=4, reg=0.05, seed=5,
                    implicit_prefs=True, alpha=2.0)
        U_p, V_p = train_als(ratings, ALSParams(**base,
                                                history_mode="pad"))
        U_s, V_s = train_als(ratings, ALSParams(**base, max_history=4,
                                                history_mode="split"))
        np.testing.assert_allclose(np.asarray(U_s)[:22],
                                   np.asarray(U_p)[:22], rtol=2e-3,
                                   atol=2e-4)

    def test_split_sharded_matches_single_device(self, mesh8):
        ratings, _, _ = make_synthetic(n_users=32, n_items=24, rank=3,
                                       seed=13)
        params = ALSParams(rank=3, num_iterations=3, reg=0.05, seed=5,
                           max_history=4, history_mode="split")
        U_1, V_1 = train_als(ratings, params)
        U_8, V_8 = train_als(ratings, params, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(U_8)[:32],
                                   np.asarray(U_1)[:32], rtol=2e-3,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(V_8)[:24],
                                   np.asarray(V_1)[:24], rtol=2e-3,
                                   atol=2e-4)

    def test_auto_mode_drops_nothing_under_skew(self, monkeypatch):
        import predictionio_tpu.ops.ragged as ragged
        from predictionio_tpu.models.als import _pack

        # shrink the auto-cap so the skewed side can't use a flat pad
        monkeypatch.setattr(ragged, "AUTO_CAP_ENTRIES", 2000)
        rng = np.random.default_rng(3)
        rows = np.concatenate([np.zeros(900, np.int32),
                               rng.integers(1, 100, 300).astype(np.int32)])
        cols = rng.integers(0, 50, 1200).astype(np.int32)
        vals = rng.random(1200).astype(np.float32)
        from predictionio_tpu.ops.ragged import BucketedHistories

        h = _pack(rows, cols, vals, 100, ALSParams(history_mode="auto"), 1)
        assert isinstance(h, BucketedHistories)
        # nothing dropped: bucket counts sum to nnz
        total = sum(int(np.asarray(b.counts).sum()) for b in h.buckets)
        assert total == 1200
        # pow2 padding bound: at most 2x + the min-length floor
        assert h.padded_entries <= 2 * 1200 + 8 * 100

    def test_auto_split_len_minimizes_padding(self):
        from predictionio_tpu.models.als import auto_split_len

        counts = np.array([1000000, 3, 3, 3])
        L = auto_split_len(counts)
        padded = (-(-counts // L) * L).sum()
        for cand in (32, 64, 128, 4096, 8192):
            assert padded <= (-(-counts // cand) * cand).sum()


class TestBucketedHistories:
    """Bucket mode: drop-free pow2 length buckets (the TPU-fast drop-free
    layout — unique-index scatters only, MXU-deep contractions)."""

    def test_pack_covers_every_entry_once(self):
        from predictionio_tpu.ops.ragged import (
            BucketedHistories,
            pack_histories_bucketed_device,
        )

        rng = np.random.default_rng(5)
        rows = np.concatenate([np.zeros(500, np.int32),
                               rng.integers(1, 40, 700).astype(np.int32)])
        cols = rng.integers(0, 64, 1200).astype(np.int32)
        vals = rng.random(1200).astype(np.float32)
        h = pack_histories_bucketed_device(rows, cols, vals, 40,
                                           pad_rows_to=4)
        assert isinstance(h, BucketedHistories)
        # every (row, col, val) triple appears exactly once across buckets
        seen = []
        for b in h.buckets:
            idx = np.asarray(b.indices)
            val = np.asarray(b.values)
            for j in range(idx.shape[0]):
                rid = int(b.row_ids[j])
                c = int(b.counts[j])
                if rid >= h.n_rows_padded or c == 0:
                    continue
                for k in range(c):
                    seen.append((rid, int(idx[j, k]), float(val[j, k])))
        assert len(seen) == 1200
        expect = sorted(zip(rows.tolist(), cols.tolist(),
                            [float(v) for v in vals]))
        assert sorted(seen) == expect
        # each real row appears in at most one bucket
        owners = [int(r) for b in h.buckets for r in b.row_ids
                  if int(r) < h.n_rows_padded]
        assert len(owners) == len(set(owners))

    def test_bucket_matches_pad_explicit(self):
        ratings, _, _ = make_synthetic(n_users=25, n_items=18, rank=3,
                                       seed=11)
        base = dict(rank=3, num_iterations=4, reg=0.05, seed=5)
        U_p, V_p = train_als(ratings, ALSParams(**base,
                                                history_mode="pad"))
        U_b, V_b = train_als(ratings, ALSParams(**base,
                                                history_mode="bucket"))
        np.testing.assert_allclose(np.asarray(U_b)[:25],
                                   np.asarray(U_p)[:25], rtol=2e-3,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(V_b)[:18],
                                   np.asarray(V_p)[:18], rtol=2e-3,
                                   atol=2e-4)

    def test_bucket_matches_pad_implicit(self):
        ratings, _, _ = make_synthetic(n_users=22, n_items=16, rank=3,
                                       seed=12)
        ratings = RatingsCOO(ratings.users, ratings.items,
                             np.abs(ratings.ratings) + 0.1,
                             ratings.n_users, ratings.n_items)
        base = dict(rank=3, num_iterations=4, reg=0.05, seed=5,
                    implicit_prefs=True, alpha=2.0)
        U_p, V_p = train_als(ratings, ALSParams(**base,
                                                history_mode="pad"))
        U_b, V_b = train_als(ratings, ALSParams(**base,
                                                history_mode="bucket"))
        np.testing.assert_allclose(np.asarray(U_b)[:22],
                                   np.asarray(U_p)[:22], rtol=2e-3,
                                   atol=2e-4)

    def test_bucket_matches_split_on_skew(self):
        # zipf-ish skew: one mega row + many small rows
        rng = np.random.default_rng(9)
        rows = np.concatenate([np.zeros(600, np.int32),
                               rng.integers(1, 60, 400).astype(np.int32)])
        cols = rng.integers(0, 40, 1000).astype(np.int32)
        vals = np.ones(1000, np.float32)
        ratings = RatingsCOO(rows, cols, vals, 60, 40)
        base = dict(rank=3, num_iterations=3, reg=0.05, seed=5,
                    implicit_prefs=True, alpha=5.0)
        U_s, V_s = train_als(ratings, ALSParams(**base, max_history=8,
                                                history_mode="split"))
        U_b, V_b = train_als(ratings, ALSParams(**base,
                                                history_mode="bucket"))
        np.testing.assert_allclose(np.asarray(U_b)[:60],
                                   np.asarray(U_s)[:60], rtol=2e-3,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(V_b)[:40],
                                   np.asarray(V_s)[:40], rtol=2e-3,
                                   atol=2e-4)

    def test_bucket_sharded_matches_single_device(self, mesh8):
        # includes a mega row (thinner than the mesh -> L-axis sharding)
        rng = np.random.default_rng(13)
        rows = np.concatenate([np.zeros(500, np.int32),
                               rng.integers(1, 32, 300).astype(np.int32)])
        cols = rng.integers(0, 24, 800).astype(np.int32)
        vals = np.ones(800, np.float32)
        ratings = RatingsCOO(rows, cols, vals, 32, 24)
        params = ALSParams(rank=3, num_iterations=3, reg=0.05, seed=5,
                           implicit_prefs=True, alpha=3.0,
                           history_mode="bucket")
        U_1, V_1 = train_als(ratings, params)
        U_8, V_8 = train_als(ratings, params, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(U_8)[:32],
                                   np.asarray(U_1)[:32], rtol=2e-3,
                                   atol=2e-4)
        np.testing.assert_allclose(np.asarray(V_8)[:24],
                                   np.asarray(V_1)[:24], rtol=2e-3,
                                   atol=2e-4)

    def test_flops_model_counts_buckets(self):
        from predictionio_tpu.models.als import als_flops_per_iter
        from predictionio_tpu.models.als import pack_ratings

        ratings, _, _ = make_synthetic(n_users=16, n_items=12, rank=3,
                                       seed=2)
        p = ALSParams(rank=4, history_mode="bucket")
        packed = pack_ratings(ratings, p)
        f = als_flops_per_iter(packed.user_h, packed.item_h, p)
        # lower bound: both sides' A-outer products over real entries
        nnz = len(ratings.users)
        assert f >= 2 * (2 * nnz * 16)

    def test_bucket_honors_max_history(self):
        # bucket + max_history truncates like pad (same factors)
        ratings, _, _ = make_synthetic(n_users=20, n_items=14, rank=3,
                                       seed=21)
        base = dict(rank=3, num_iterations=3, reg=0.05, seed=5)
        U_p, V_p = train_als(ratings, ALSParams(**base, max_history=4,
                                                history_mode="pad"))
        U_b, V_b = train_als(ratings, ALSParams(**base, max_history=4,
                                                history_mode="bucket"))
        np.testing.assert_allclose(np.asarray(U_b)[:20],
                                   np.asarray(U_p)[:20], rtol=2e-3,
                                   atol=2e-4)
        # and the packing itself kept no more than max_history per row
        from predictionio_tpu.ops.ragged import (
            pack_histories_bucketed_device,
        )

        h = pack_histories_bucketed_device(
            ratings.users, ratings.items, ratings.ratings,
            ratings.n_users, max_len=4)
        assert all(int(np.asarray(b.counts).max(initial=0)) <= 4
                   for b in h.buckets)

    def test_mega_row_bucket_shards_history_axis(self, mesh8):
        # a 1-real-row bucket on an 8-device mesh must shard L, not rows
        from predictionio_tpu.models.als import _blocked_bucket
        from predictionio_tpu.ops.ragged import (
            pack_histories_bucketed_device,
        )

        rows = np.zeros(512, np.int32)  # one mega row, L=512
        cols = np.arange(512, dtype=np.int32) % 40
        vals = np.ones(512, np.float32)
        h = pack_histories_bucketed_device(rows, cols, vals, 1,
                                           pad_rows_to=8)
        bk = _blocked_bucket(h, 8, mesh8)
        mega = [b for b in bk["buckets"] if b["idx"].shape[-1] >= 512]
        assert mega, [b["idx"].shape for b in bk["buckets"]]
        # L-sharded layout keeps the row axes unsharded: [1, n_bk, L]
        assert mega[0]["idx"].shape[0] == 1


class TestSplitModeWarning:
    """Round-3 (VERDICT r2 weak #8): opting into split mode warns about
    the measured TPU scatter-serialization hazard."""

    def test_split_mode_warns(self):
        import warnings

        from predictionio_tpu.models.als import (
            ALSParams, RatingsCOO, pack_ratings)

        rng = np.random.default_rng(0)
        coo = RatingsCOO(rng.integers(0, 20, 200).astype(np.int32),
                         rng.integers(0, 30, 200).astype(np.int32),
                         np.ones(200, np.float32), 20, 30)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            pack_ratings(coo, ALSParams(history_mode="split"))
        assert any("bucket" in str(x.message) for x in w)

    def test_bucket_mode_does_not_warn(self):
        import warnings

        from predictionio_tpu.models.als import (
            ALSParams, RatingsCOO, pack_ratings)

        rng = np.random.default_rng(0)
        coo = RatingsCOO(rng.integers(0, 20, 200).astype(np.int32),
                         rng.integers(0, 30, 200).astype(np.int32),
                         np.ones(200, np.float32), 20, 30)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            pack_ratings(coo, ALSParams(history_mode="bucket"))
        assert not [x for x in w if "serialize" in str(x.message)]


class TestColumnarRatingsSource:
    """Sharded partial reads off a ColumnarBatch (VERDICT r2 task 5)."""

    def _batch(self, nnz=700, n_users=40, n_items=25, seed=2):
        from predictionio_tpu.data.columnar import (
            ColumnarDicts,
            columnar_from_columns,
        )
        rng = np.random.default_rng(seed)
        u = rng.integers(0, n_users, nnz)
        i = rng.integers(0, n_items, nnz)
        r = rng.integers(1, 6, nnz).astype(np.float64)
        batch = columnar_from_columns(
            ColumnarDicts(), ["rate"] * nnz, ["user"] * nnz,
            [f"u{x}" for x in u], ["item"] * nnz,
            [f"i{x}" for x in i], np.arange(nnz, dtype=np.int64),
            [None] * nnz, float_props=())
        batch.float_props["rating"] = r
        return batch

    def test_shard_reads_cover_exactly_the_log(self):
        from predictionio_tpu.models.data import (
            ColumnarRatingsSource,
            ratings_from_columnar,
        )
        batch = self._batch()
        src = ColumnarRatingsSource(batch, chunk=64)
        ref, uids, iids = ratings_from_columnar(batch)
        assert src.n_users == ref.n_users
        assert src.n_items == ref.n_items
        # union of disjoint shards == the full log, no dup/loss
        got = []
        bounds = np.linspace(0, src.n_users, 4).astype(int)
        for a, b in zip(bounds[:-1], bounds[1:]):
            rows, cols, vals = src.read_rows("user", a, b)
            assert ((rows >= a) & (rows < b)).all()
            got.append((rows, cols, vals))
        rows = np.concatenate([g[0] for g in got])
        cols = np.concatenate([g[1] for g in got])
        vals = np.concatenate([g[2] for g in got])
        assert sorted(zip(rows, cols, vals)) == \
            sorted(zip(ref.users, ref.items, ref.ratings))
        # item side mirrors
        r2, c2, v2 = src.read_rows("item", 0, src.n_items)
        assert sorted(zip(r2, c2, v2)) == \
            sorted(zip(ref.items, ref.users, ref.ratings))
        # row_counts agree with a bincount of the reference COO
        np.testing.assert_array_equal(
            src.row_counts("user"),
            np.bincount(ref.users, minlength=ref.n_users))

    def test_sharded_source_single_process_identity(self):
        """ShardedColumnarRatingsSource (v3: storage shard + collective
        shuffle) under ONE process: shard (0, 1) is the whole log, the
        exchange is the identity, and every read must match the plain
        source — including global-storage-order restoration (order
        affects max_history truncation)."""
        from predictionio_tpu.models.data import (
            ColumnarRatingsSource,
            ShardedColumnarRatingsSource,
        )
        batch = self._batch()
        batch.shard_offset = 0
        plain = ColumnarRatingsSource(batch, chunk=64)
        sharded = ShardedColumnarRatingsSource(batch, chunk=64,
                                               exchange_chunk=97)
        assert sharded.n_users == plain.n_users
        assert sharded.n_items == plain.n_items
        np.testing.assert_array_equal(sharded.row_counts("user"),
                                      plain.row_counts("user"))
        for side, a, b in (("user", 7, 23), ("item", 0, plain.n_items)):
            r1, c1, v1 = plain.read_rows(side, a, b)
            r2, c2, v2 = sharded.read_rows(side, a, b)
            np.testing.assert_array_equal(r1, r2)  # exact order match
            np.testing.assert_array_equal(c1, c2)
            np.testing.assert_array_equal(v1, v2)
        mask = np.zeros(plain.n_users, dtype=bool)
        mask[::3] = True
        r1, c1, v1 = plain.read_row_mask("user", mask)
        r2, c2, v2 = sharded.read_row_mask("user", mask)
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(c1, c2)
        np.testing.assert_array_equal(v1, v2)

    def test_buy_weight_and_nan_rating_semantics(self):
        from predictionio_tpu.data.columnar import (
            ColumnarDicts,
            columnar_from_columns,
        )
        from predictionio_tpu.models.data import (
            ColumnarRatingsSource,
            ratings_from_columnar,
        )
        n = 6
        batch = columnar_from_columns(
            ColumnarDicts(),
            ["rate", "buy", "rate", "view", "buy", "rate"],
            ["user"] * n, [f"u{k}" for k in range(n)],
            ["item"] * n, [f"i{k % 2}" for k in range(n)],
            np.arange(n, dtype=np.int64), [None] * n, float_props=())
        batch.float_props["rating"] = np.array(
            [4.0, np.nan, np.nan, 2.0, np.nan, 1.0])
        src = ColumnarRatingsSource(batch)
        ref, _, _ = ratings_from_columnar(batch)
        coo = src.to_coo()
        assert sorted(zip(coo.users, coo.items, coo.ratings)) == \
            sorted(zip(ref.users, ref.items, ref.ratings))
        assert len(coo.users) == 4  # 2 rate + 2 buy; view + NaN-rate drop


class TestPadFusedTrainer:
    """The fused whole-run pad program must match the per-step path."""

    def _coo(self):
        rng = np.random.default_rng(6)
        return RatingsCOO(rng.integers(0, 40, 800).astype(np.int32),
                          rng.integers(0, 25, 800).astype(np.int32),
                          (rng.random(800) * 4 + 1).astype(np.float32),
                          40, 25)

    @pytest.mark.parametrize("implicit", [False, True])
    def test_fused_matches_stepwise(self, tmp_path, implicit):
        coo = self._coo()
        params = ALSParams(rank=6, num_iterations=3, seed=4,
                           history_mode="pad",
                           implicit_prefs=implicit, alpha=8.0)
        U1, V1 = train_als(coo, params)  # fused (no checkpointing)
        # checkpoint_dir forces the per-step path. Same math and order,
        # but the fused program inlines the Gramian into one XLA
        # computation whose fusion reassociates f32 reductions — a few
        # 1e-4-rel ulps of drift per iteration is expected, bitwise
        # equality is not.
        U2, V2 = train_als(coo, params,
                           checkpoint_dir=str(tmp_path / "ck"),
                           checkpoint_every=100)
        np.testing.assert_allclose(np.asarray(U1), np.asarray(U2),
                                   rtol=2e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(V1), np.asarray(V2),
                                   rtol=2e-3, atol=1e-5)

    def test_fused_on_mesh(self, mesh8):
        coo = self._coo()
        params = ALSParams(rank=6, num_iterations=3, seed=4,
                           history_mode="pad")
        U1, V1 = train_als(coo, params)
        U8, V8 = train_als(coo, params, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(U8), np.asarray(U1),
                                   rtol=2e-3, atol=2e-4)

    def test_mixed_pad_bucket_fused(self):
        """history_mode='auto' can resolve pad on one side and bucket on
        the other (per-side skew); the unified fused trainer must handle
        the mix and agree with the uniform layouts."""
        from predictionio_tpu.models.als import PackedRatings, pack_ratings
        from predictionio_tpu.ops.ragged import (
            pack_histories_bucketed_device,
            pack_histories_device,
        )

        coo = self._coo()
        params = ALSParams(rank=6, num_iterations=3, seed=4,
                           implicit_prefs=True, alpha=8.0)
        counts_u = np.bincount(coo.users, minlength=coo.n_users)
        user_h = pack_histories_device(
            coo.users, coo.items, coo.ratings, coo.n_users,
            max_len=int(counts_u.max()), pad_rows_to=1)
        item_h = pack_histories_bucketed_device(
            coo.items, coo.users, coo.ratings, coo.n_items,
            pad_rows_to=1)
        mixed = PackedRatings(user_h=user_h, item_h=item_h, mesh=None,
                              n_users=coo.n_users, n_items=coo.n_items)
        Um, Vm = train_als(coo, params, packed=mixed)
        import dataclasses
        Ub, Vb = train_als(coo, dataclasses.replace(
            params, history_mode="bucket"))
        np.testing.assert_allclose(np.asarray(Um)[:coo.n_users],
                                   np.asarray(Ub)[:coo.n_users],
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(Vm)[:coo.n_items],
                                   np.asarray(Vb)[:coo.n_items],
                                   rtol=2e-3, atol=2e-4)


class TestAutoLayoutWasteBound:
    def test_skewed_but_under_cap_picks_bucket(self):
        """auto layout must bound padding WASTE, not just absolute
        size: a 5%-sample eval fold padded 0.5M entries into 33M slots
        per side (30x waste) and exhausted device memory (round 4).
        Skewed counts under the absolute cap now go bucketed."""
        from predictionio_tpu.models.als import _pack
        from predictionio_tpu.ops.ragged import (
            BucketedHistories,
            PaddedHistories,
        )

        rng = np.random.default_rng(0)
        n_rows, nnz = 30_000, 400_000
        # one mega-row (L_full ~ 4k) over a light tail: slots ~ 123M
        # (< 200M cap) but waste ~ 300x
        rows = rng.integers(0, n_rows, nnz).astype(np.int32)
        rows[:4_000] = 7
        cols = rng.integers(0, 1000, nnz).astype(np.int32)
        vals = np.ones(nnz, np.float32)
        params = ALSParams(rank=4, history_mode="auto")
        h = _pack(rows, cols, vals, n_rows, params, n_dev=1)
        assert isinstance(h, BucketedHistories)

        # dense counts (waste <= 4x) still take the simpler pad path
        rows_d = np.repeat(np.arange(2000, dtype=np.int32), 50)
        cols_d = rng.integers(0, 100, len(rows_d)).astype(np.int32)
        h2 = _pack(rows_d, cols_d, np.ones(len(rows_d), np.float32),
                   2000, params, n_dev=1)
        assert isinstance(h2, PaddedHistories)

    def test_packs_are_host_resident(self):
        """Packed layouts live on HOST; only PackedRatings.blocked()
        ships mesh-shaped copies to the device (keeping both doubled
        HBM per pack — the round-4 eval OOM)."""
        from predictionio_tpu.models.als import _pack
        rng = np.random.default_rng(1)
        rows = rng.integers(0, 500, 20_000).astype(np.int32)
        cols = rng.integers(0, 300, 20_000).astype(np.int32)
        vals = np.ones(20_000, np.float32)
        for mode in ("pad", "bucket", "split"):
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                h = _pack(rows, cols, vals, 500,
                          ALSParams(rank=4, history_mode=mode), n_dev=1)
            arrs = []
            if hasattr(h, "buckets"):
                for b in h.buckets:
                    arrs += [b.indices, b.values]
            else:
                arrs += [h.indices, h.values]
            for a in arrs:
                assert isinstance(a, np.ndarray), (mode, type(a))


class TestGatherDtype:
    """gather_dtype='bfloat16' (round 4): factor rows are gathered from
    a bf16 shadow of the f32 table — master weights, gram accumulation
    and solves stay f32. Must stay CLOSE to the f32 run on every
    layout, and must not damage ranking quality."""

    def _coo(self, seed=0):
        coo, _, _ = make_synthetic(n_users=120, n_items=80, rank=4,
                                   density=0.3, seed=seed)
        return coo

    @pytest.mark.parametrize("mode", ["pad", "bucket", "split"])
    def test_close_to_f32_per_layout(self, mode):
        coo = self._coo()
        kw = dict(rank=6, num_iterations=3, seed=4, history_mode=mode,
                  implicit_prefs=True, alpha=8.0)
        U1, V1 = train_als(coo, ALSParams(**kw))
        U2, V2 = train_als(coo, ALSParams(**kw,
                                          gather_dtype="bfloat16"))
        # bf16 mantissa is 8 bits: inputs perturbed ~4e-3 relative;
        # after 3 alternating solves the factors drift accordingly
        np.testing.assert_allclose(np.asarray(U2), np.asarray(U1),
                                   rtol=0.1, atol=0.02)
        np.testing.assert_allclose(np.asarray(V2), np.asarray(V1),
                                   rtol=0.1, atol=0.02)

    def test_ranking_quality_preserved(self):
        # reconstruction quality of the completed matrix must match the
        # f32 run to noise level: rank the held-out positives
        coo, full, mask = make_synthetic(n_users=120, n_items=80,
                                         rank=4, density=0.3, seed=1)
        kw = dict(rank=4, num_iterations=8, seed=3, reg=0.05)

        def rmse(gd):
            U, V = train_als(coo, ALSParams(**kw, gather_dtype=gd))
            rec = np.asarray(U)[:coo.n_users] @ np.asarray(V)[:coo.n_items].T
            return float(np.sqrt(np.mean((rec[mask] - full[mask]) ** 2)))

        r32 = rmse("float32")
        r16 = rmse("bfloat16")
        assert r16 < r32 * 1.05 + 1e-3, (r32, r16)

    def test_checkpoint_fingerprint_distinct(self, tmp_path):
        coo = self._coo()
        kw = dict(rank=4, num_iterations=2, seed=3)
        d = str(tmp_path / "ck")
        train_als(coo, ALSParams(**kw), checkpoint_dir=d,
                  checkpoint_every=1)
        with pytest.raises(ValueError, match="different"):
            train_als(coo, ALSParams(**kw, gather_dtype="bfloat16"),
                      checkpoint_dir=d, checkpoint_every=1)
