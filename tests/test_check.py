"""`ptpu check` static-analysis tests: every rule's positive, negative,
and pragma-suppressed cases; the repo-wide clean gate; the CLI contract;
and the runtime complement (transfer guard + recompile sentinel)."""

import os
import textwrap
from dataclasses import dataclass

import pytest

from predictionio_tpu.analysis import (
    RULES,
    check_project,
    check_source,
    run_check,
)
from predictionio_tpu.cli import main

PKG = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "predictionio_tpu")

HOT = "predictionio_tpu/server/hot.py"    # host-sync rule applies
COLD = "predictionio_tpu/models/cold.py"  # ...and here it does not


def rules_of(findings):
    return [f.rule for f in findings]


def src(text):
    return textwrap.dedent(text)


class TestHostSyncInHotPath:
    def test_positive_all_sync_forms(self):
        code = src("""
            import numpy as np
            import jax
            import jax.numpy as jnp

            def handler(arr, dev):
                a = np.asarray(arr)
                b = np.ascontiguousarray(arr)
                c = jax.device_get(dev)
                d = dev.item()
                e = dev.tolist()
                dev.block_until_ready()
                f = float(jnp.sum(dev))
                return a, b, c, d, e, f
        """)
        findings = check_source(code, path=HOT)
        assert rules_of(findings) == ["host-sync-in-hot-path"] * 7

    def test_negative_outside_hot_packages(self):
        code = src("""
            import numpy as np

            def handler(arr):
                return np.asarray(arr)
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_module_level_is_not_hot(self):
        # import-time code runs once; only function bodies are hot
        code = src("""
            import numpy as np

            TABLE = np.asarray([1, 2, 3])
        """)
        assert check_source(code, path=HOT) == []

    def test_pragma_suppresses(self):
        code = src("""
            import numpy as np

            def handler(arr):
                # ptpu: allow[host-sync-in-hot-path] — test justification
                return np.asarray(arr)
        """)
        assert check_source(code, path=HOT) == []

    def test_pragma_in_comment_block_above(self):
        code = src("""
            import numpy as np

            def handler(arr):
                # a multi-line justification whose marker sits on the
                # first line: ptpu: allow[host-sync-in-hot-path]
                # and more prose after it
                return np.asarray(arr)
        """)
        assert check_source(code, path=HOT) == []


class TestRecompileHazard:
    def test_positive_unhashable_static_arg(self):
        code = src("""
            import jax

            def f(x, cfg):
                return x

            g = jax.jit(f, static_argnames=("cfg",))

            def call(x):
                return g(x, cfg=[1, 2])
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["recompile-hazard"]
        assert "unhashable" in findings[0].message

    def test_positive_closure_over_jnp_array(self):
        code = src("""
            import jax
            import jax.numpy as jnp

            def build(vals):
                w = jnp.asarray(vals)
                return jax.jit(lambda x: x + w)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["recompile-hazard"]
        assert "closes over" in findings[0].message

    def test_positive_python_if_on_traced_arg(self):
        code = src("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("flag",))
            def f(x, n, flag):
                if n > 0:
                    return x
                return -x
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["recompile-hazard"]
        assert "traced argument" in findings[0].message

    def test_negative_static_branch_and_hashable_call(self):
        code = src("""
            import functools
            import jax
            import jax.numpy as jnp

            @functools.partial(jax.jit, static_argnames=("flag", "n"))
            def f(x, n, flag):
                if flag:
                    return x * n
                return jnp.where(x > 0, x, -x)

            def call(x):
                return f(x, n=4, flag=True)
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            import jax
            import jax.numpy as jnp

            def build(vals):
                w = jnp.asarray(vals)
                # ptpu: allow[recompile-hazard] — built once, cached
                return jax.jit(lambda x: x + w)
        """)
        assert check_source(code, path=COLD) == []


class TestMissingDonation:
    def test_positive_rebound_without_donation(self):
        code = src("""
            import jax

            @jax.jit
            def step(w, g):
                return w - g

            def train(w, g):
                w = step(w, g)
                return w
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["missing-donation"]
        assert "`w`" in findings[0].message

    def test_positive_tuple_rebind(self):
        code = src("""
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(w, m, g):
                return w - g, m * g

            def train(w, m, g):
                w, m = step(w, m, g)
                return w, m
        """)
        findings = check_source(code, path=COLD)
        # w (argnum 0) is donated; m (argnum 1) is not
        assert rules_of(findings) == ["missing-donation"]
        assert "`m`" in findings[0].message

    def test_negative_donated(self):
        code = src("""
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def step(w, m, g):
                return w - g, m * g

            def train(w, m, g):
                w, m = step(w, m, g)
                return w, m
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_no_rebind(self):
        code = src("""
            import jax

            @jax.jit
            def score(w, x):
                return w @ x

            def run(w, x):
                s = score(w, x)
                return s
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            import jax

            @jax.jit
            def step(w, g):
                return w - g

            def train(w, g):
                # ptpu: allow[missing-donation] — tiny buffers, test only
                w = step(w, g)
                return w
        """)
        assert check_source(code, path=COLD) == []


class TestShardingMismatch:
    def test_positive_undeclared_axis(self):
        code = src("""
            from jax.sharding import PartitionSpec as P

            SPEC = P("bogus_axis", None)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]
        assert "bogus_axis" in findings[0].message

    def test_positive_undeclared_axis_in_tuple(self):
        code = src("""
            from jax.sharding import PartitionSpec

            SPEC = PartitionSpec(("data", "oops"))
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]
        assert "oops" in findings[0].message

    def test_negative_declared_axes(self):
        code = src("""
            from jax.sharding import PartitionSpec as P

            A = P("data", None)
            B = P(("data", "model"))
            C = P()
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            from jax.sharding import PartitionSpec as P

            # ptpu: allow[sharding-mismatch] — external mesh contract
            SPEC = P("expert")
        """)
        assert check_source(code, path=COLD) == []

    def test_positive_collective_axis(self):
        # ISSUE 6: a typo'd axis handed to a lax collective fails at
        # trace time on a real mesh exactly like a bad PartitionSpec
        code = src("""
            from jax import lax

            def half_step(g):
                return lax.psum(g, "modle")
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]
        assert "modle" in findings[0].message

    def test_positive_collective_axis_kwarg_and_index(self):
        code = src("""
            import jax

            def who(x):
                i = jax.lax.axis_index("bogus")
                return jax.lax.all_gather(x, axis_name="nope")
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"] * 2

    def test_negative_collectives_on_declared_axes(self):
        # "batch" is the serving-mesh axis declared by parallel/mesh.py
        # (BATCH_AXIS) — NamedSharding-annotated serving entry points
        # and their collectives land clean without pragmas
        code = src("""
            import jax
            from jax import lax
            from jax.sharding import NamedSharding, PartitionSpec as P

            def rank(scores, mesh):
                spec = NamedSharding(mesh, P(("batch", "model")))
                s = lax.all_gather(scores, ("batch", "model"), tiled=True)
                return s, spec, lax.axis_index("batch")
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_collective_variable_axis(self):
        # a variable axis name is resolved at run time — not lintable
        code = src("""
            from jax import lax

            def reduce_over(x, axis):
                return lax.psum(x, axis)
        """)
        assert check_source(code, path=COLD) == []


class TestConfigDrift:
    def test_positive_update_outside_platform(self):
        code = src("""
            import jax

            def setup():
                jax.config.update("jax_enable_x64", True)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["config-drift"]
        assert "jax_enable_x64" in findings[0].message

    def test_negative_platform_module_owns_config(self):
        code = src("""
            import jax

            def setup():
                jax.config.update("jax_enable_x64", True)
        """)
        path = "predictionio_tpu/utils/platform.py"
        assert check_source(code, path=path) == []

    def test_pragma_suppresses(self):
        code = src("""
            import jax

            def setup():
                # ptpu: allow[config-drift] — init-time, owns this flag
                jax.config.update("jax_enable_x64", True)
        """)
        assert check_source(code, path=COLD) == []


class TestUnboundedRetry:
    RETRY = "predictionio_tpu/streaming/loop.py"  # in-scope dir

    def test_positive_hot_spin_retry(self):
        code = src("""
            def tail(store):
                while True:
                    try:
                        return store.read()
                    except Exception:
                        continue
        """)
        findings = check_source(code, path=self.RETRY)
        assert rules_of(findings) == ["unbounded-retry"]
        assert "retry_call" in findings[0].message

    def test_positive_itertools_count(self):
        code = src("""
            import itertools

            def tail(store):
                for _ in itertools.count():
                    try:
                        return store.read()
                    except OSError:
                        pass
        """)
        findings = check_source(code, path=self.RETRY)
        assert rules_of(findings) == ["unbounded-retry"]

    def test_negative_backoff_sleep(self):
        code = src("""
            import time

            def tail(store):
                while True:
                    try:
                        return store.read()
                    except Exception:
                        time.sleep(0.5)
        """)
        assert check_source(code, path=self.RETRY) == []

    def test_negative_bounded_attempts(self):
        code = src("""
            def tail(store):
                for attempt in range(5):
                    try:
                        return store.read()
                    except Exception:
                        continue
                raise RuntimeError("gave up")
        """)
        assert check_source(code, path=self.RETRY) == []

    def test_negative_blocking_get_paces(self):
        code = src("""
            def drain(q, store):
                while True:
                    item = q.get()
                    try:
                        store.write(item)
                    except Exception:
                        continue
        """)
        assert check_source(code, path=self.RETRY) == []

    def test_negative_nowait_does_not_pace(self):
        code = src("""
            def drain(q, store):
                while True:
                    try:
                        store.write(q.get_nowait())
                    except Exception:
                        continue
        """)
        findings = check_source(code, path=self.RETRY)
        assert rules_of(findings) == ["unbounded-retry"]

    def test_negative_reraise_escapes(self):
        code = src("""
            def tail(store):
                while True:
                    try:
                        return store.read()
                    except Exception:
                        raise
        """)
        assert check_source(code, path=self.RETRY) == []

    def test_negative_retry_call_helper(self):
        code = src("""
            from predictionio_tpu.utils.retrying import retry_call

            def tail(store):
                while True:
                    try:
                        return retry_call(store.read)
                    except Exception:
                        continue
        """)
        assert check_source(code, path=self.RETRY) == []

    def test_negative_out_of_scope_dir(self):
        code = src("""
            def tail(store):
                while True:
                    try:
                        return store.read()
                    except Exception:
                        continue
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            def tail(store):
                while True:
                    try:
                        return store.read()
                    except Exception:  # ptpu: allow[unbounded-retry]
                        continue
        """)
        assert check_source(code, path=self.RETRY) == []


class TestPragmaGeneral:
    def test_wildcard_allows_every_rule(self):
        code = src("""
            import jax

            def setup():
                jax.config.update("jax_enable_x64", True)  # ptpu: allow[*]
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_for_other_rule_does_not_suppress(self):
        code = src("""
            import jax

            def setup():
                # ptpu: allow[missing-donation] — wrong rule on purpose
                jax.config.update("jax_enable_x64", True)
        """)
        assert rules_of(check_source(code, path=COLD)) == ["config-drift"]


class TestMaterializedGather:
    """`table[indices]` advanced-indexing gathers inside jitted
    train/serve hot-path functions (ISSUE 7): the [B, L, r]-shaped HBM
    temps behind the BENCH_r05 roofline bound."""

    def test_positive_jitted_gather(self):
        code = src("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k",))
            def half_step(table, indices, w, k):
                F = table[indices]
                return (F * w[..., None]).sum(-2)
        """)
        assert rules_of(check_source(code, path=COLD)) \
            == ["materialized-gather"]

    def test_positive_jit_of_lambda(self):
        code = src("""
            import jax

            def make(table):
                return jax.jit(lambda tab, idx: tab[idx])
        """)
        assert rules_of(check_source(code, path=COLD)) \
            == ["materialized-gather"]

    def test_negative_unjitted_host_helper(self):
        # host-side numpy gathers pay once, not per dispatch
        code = src("""
            import numpy as np

            def pack(table, indices):
                return np.asarray(table)[indices]
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_static_index_and_scatter_builder(self):
        code = src("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("sel",))
            def pick(table, acc, ids, sel):
                part = table[sel]                 # static: no temp
                return acc.at[ids].add(part)      # scatter, not gather
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_outside_hot_packages(self):
        code = src("""
            import jax

            @jax.jit
            def gather(table, indices):
                return table[indices]
        """)
        assert check_source(code,
                            path="predictionio_tpu/rollout/x.py") == []

    def test_pragma_suppresses(self):
        code = src("""
            import jax

            @jax.jit
            def serve(table, idx):
                # ptpu: allow[materialized-gather] — [B, r] row fetch
                return table[idx]
        """)
        assert check_source(code, path=COLD) == []


class TestRepoWide:
    def test_package_is_clean(self):
        findings = run_check([PKG])
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_unknown_rule_rejected(self):
        with pytest.raises(ValueError, match="unknown rule"):
            run_check([PKG], rule_names=["not-a-rule"])

    def test_rule_catalogue_complete(self):
        assert set(RULES) == {
            "host-sync-in-hot-path", "recompile-hazard",
            "missing-donation", "sharding-mismatch", "config-drift",
            "materialized-gather", "unbounded-retry",
            "unguarded-shared-state", "lock-order-inversion",
            "blocking-under-lock", "callback-under-lock",
            "vmem-overbudget", "dma-unwaited",
            "low-precision-accumulator", "missing-interpret-fallback",
            "implicit-reshard", "shard-map-spec-mismatch",
            "unsharded-capture", "missing-donation-sharded",
            "low-precision-reduction", "dequant-outside-funnel",
            "quantize-without-parity-gate", "unguarded-domain",
            "requant-torn-pair", "metric-catalog-drift",
            "leaked-thread", "missing-timeout", "non-atomic-persist",
            "unbounded-queue", "hot-spin-loop"}

    def test_kernel_files_clean_under_kernel_rules(self):
        # the acceptance bar: the real Pallas kernels pass the rules
        # that were written because of them
        findings = run_check(
            [os.path.join(PKG, "ops")],
            rule_names=["vmem-overbudget", "dma-unwaited",
                        "low-precision-accumulator",
                        "missing-interpret-fallback"])
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_benchmarks_and_examples_clean(self):
        root = os.path.dirname(PKG)
        findings = run_check([os.path.join(root, "benchmarks"),
                              os.path.join(root, "examples")])
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_full_run_wall_time_budget(self):
        # CI enforces 60 s over predictionio_tpu+benchmarks+examples;
        # guard the interprocedural pass from quadratic blowup with
        # headroom for slow runners
        import time

        t0 = time.time()
        run_check([PKG])
        assert time.time() - t0 < 30

    def test_parse_error_is_reported_not_raised(self):
        findings = check_source("def broken(:", path=COLD)
        assert rules_of(findings) == ["parse-error"]


class TestCheckCLI:
    def test_findings_exit_1(self, tmp_path, capsys):
        bad = tmp_path / "server" / "bad.py"
        bad.parent.mkdir()
        bad.write_text(src("""
            import numpy as np

            def handler(arr):
                return np.asarray(arr)
        """))
        # the hot-path rule keys off path parts, so check the parent dir
        assert main(["check", str(tmp_path)]) == 1
        out = capsys.readouterr()
        assert "host-sync-in-hot-path" in out.out
        assert "1 finding(s)" in out.err

    def test_clean_exit_0(self, tmp_path, capsys):
        good = tmp_path / "fine.py"
        good.write_text("X = 1\n")
        assert main(["check", str(good)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_rule_filter_and_list(self, tmp_path, capsys):
        bad = tmp_path / "drift.py"
        bad.write_text(src("""
            import jax

            def setup():
                jax.config.update("jax_enable_x64", True)
        """))
        assert main(["check", str(bad), "--rule", "missing-donation"]) == 0
        assert main(["check", str(bad), "--rule", "config-drift"]) == 1
        assert main(["check", "--list-rules"]) == 0
        assert "config-drift" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# concurrency rule family (ISSUE 5)
# ---------------------------------------------------------------------------

UNGUARDED = src("""
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def inc(self):
            with self._lock:
                self._n += 1

        def read(self):
            return self._n
""")


class TestUnguardedSharedState:
    def test_positive_read_outside_lock(self):
        findings = check_source(UNGUARDED, path=COLD)
        assert rules_of(findings) == ["unguarded-shared-state"]
        assert "`self._n`" in findings[0].message
        assert "_lock" in findings[0].message

    def test_positive_write_outside_lock(self):
        code = UNGUARDED.replace("return self._n", "self._n = 0")
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["unguarded-shared-state"]
        assert "written" in findings[0].message

    def test_negative_init_is_exempt_and_locked_access_clean(self):
        code = src("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def inc(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    with self._lock:
                        return self._n
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_unlocked_attrs_are_not_tracked(self):
        # attrs never written under a lock have no inferred guard
        code = src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.hits = 0

                def bump(self):
                    self.hits += 1
        """)
        assert check_source(code, path=COLD) == []

    def test_guarded_by_on_access_line_suppresses(self):
        code = UNGUARDED.replace(
            "return self._n",
            "# ptpu: guarded-by[_lock] — caller holds it\n"
            "            return self._n")
        assert check_source(code, path=COLD) == []

    def test_guarded_by_on_def_line_covers_whole_method(self):
        code = UNGUARDED.replace(
            "def read(self):",
            "def read(self):  # ptpu: guarded-by[_lock] — private "
            "helper, every caller locks")
        assert check_source(code, path=COLD) == []

    def test_guarded_by_declaration_in_init_tracks_attr(self):
        # _gen is NEVER written under a with-lock, but the declaration
        # annotation forces it into the guarded set
        code = src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._gen = 0  # ptpu: guarded-by[_lock]

                def read(self):
                    return self._gen
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["unguarded-shared-state"]
        assert "`self._gen`" in findings[0].message

    def test_guarded_by_wrong_lock_does_not_suppress(self):
        code = UNGUARDED.replace(
            "return self._n",
            "# ptpu: guarded-by[_other_lock] — wrong lock on purpose\n"
            "            return self._n")
        assert rules_of(check_source(code, path=COLD)) == [
            "unguarded-shared-state"]

    def test_nested_function_resets_lock_context(self):
        # a closure defined under the lock runs later, unlocked
        code = src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def set(self):
                    with self._lock:
                        self._n = 1

                def deferred(self):
                    with self._lock:
                        def later():
                            return self._n
                        return later
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["unguarded-shared-state"]
        assert "deferred" in findings[0].message

    def test_pragma_suppresses(self):
        code = UNGUARDED.replace(
            "return self._n",
            "# ptpu: allow[unguarded-shared-state] — test justification\n"
            "            return self._n")
        assert check_source(code, path=COLD) == []


LOCK_CYCLE = src("""
    import threading

    A_LOCK = threading.Lock()
    B_LOCK = threading.Lock()

    def f():
        with A_LOCK:
            with B_LOCK:
                pass

    def g():
        with B_LOCK:
            with A_LOCK:
                pass
""")


class TestLockOrderInversion:
    def test_positive_two_lock_cycle(self):
        findings = check_source(LOCK_CYCLE, path=COLD)
        assert rules_of(findings) == ["lock-order-inversion"]
        assert "A_LOCK" in findings[0].message
        assert "B_LOCK" in findings[0].message
        assert "deadlock" in findings[0].message

    def test_positive_cycle_across_classes(self):
        code = src("""
            import threading

            class P:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.q = None

                def a(self):
                    with self._lock:
                        with self.q.qlock:
                            pass

            class Q:
                def __init__(self):
                    self.qlock = threading.Lock()
                    self.p = None

                def b(self):
                    with self.qlock:
                        with self.p.plock:
                            pass
        """)
        # P._lock → mod:?.qlock and mod:self.qlock → mod:?.plock do
        # not close a cycle (naming is conservative); make a real one:
        code = src("""
            import threading

            class P:
                def __init__(self):
                    self._lock_a = threading.Lock()
                    self._lock_b = threading.Lock()

                def a(self):
                    with self._lock_a:
                        with self._lock_b:
                            pass

                def b(self):
                    with self._lock_b:
                        with self._lock_a:
                            pass
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["lock-order-inversion"]
        assert "P._lock_a" in findings[0].message

    def test_negative_consistent_order(self):
        code = src("""
            import threading

            A_LOCK = threading.Lock()
            B_LOCK = threading.Lock()

            def f():
                with A_LOCK:
                    with B_LOCK:
                        pass

            def g():
                with A_LOCK:
                    with B_LOCK:
                        pass
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_sequential_not_nested(self):
        code = src("""
            import threading

            A_LOCK = threading.Lock()
            B_LOCK = threading.Lock()

            def f():
                with A_LOCK:
                    pass
                with B_LOCK:
                    pass

            def g():
                with B_LOCK:
                    pass
                with A_LOCK:
                    pass
        """)
        assert check_source(code, path=COLD) == []

    def test_multi_item_with_is_ordered(self):
        code = src("""
            import threading

            A_LOCK = threading.Lock()
            B_LOCK = threading.Lock()

            def f():
                with A_LOCK, B_LOCK:
                    pass

            def g():
                with B_LOCK, A_LOCK:
                    pass
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["lock-order-inversion"]

    def test_pragma_suppresses_at_anchor_edge(self):
        # the finding anchors at the cycle's first edge site — the
        # inner `with B_LOCK` in f(); the pragma must cover that line
        code = LOCK_CYCLE.replace(
            "    with A_LOCK:\n        with B_LOCK:",
            "    with A_LOCK:\n"
            "        # ptpu: allow[lock-order-inversion] — test fixture\n"
            "        with B_LOCK:")
        assert check_source(code, path=COLD) == []


class TestBlockingUnderLock:
    HOT_SRV = "predictionio_tpu/server/hot.py"

    def _code(self, body):
        return src("""
            import threading
            import time
            import urllib.request

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def m(self, dev, t, fut):
                    with self._lock:
                        {body}
        """).replace("{body}", body)

    def test_positive_sleep(self):
        findings = check_source(self._code("time.sleep(1)"),
                                path=self.HOT_SRV)
        assert rules_of(findings) == ["blocking-under-lock"]
        assert "S._lock" in findings[0].message

    def test_positive_block_until_ready_and_join_and_http(self):
        for body in ("dev.block_until_ready()", "t.join()",
                     "urllib.request.urlopen('http://x')",
                     "fut.result()"):
            findings = check_source(self._code(body), path=self.HOT_SRV)
            # block_until_ready also trips host-sync-in-hot-path (both
            # rules are right: it is a sync AND it is under a lock)
            assert "blocking-under-lock" in rules_of(findings), body

    def test_positive_storage_io(self):
        code = src("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.storage = None

                def m(self, event, app_id):
                    with self._lock:
                        self.storage.events().insert(event, app_id)
        """)
        findings = check_source(code, path=self.HOT_SRV)
        assert rules_of(findings) == ["blocking-under-lock"]

    def test_negative_outside_lock_or_outside_serving_stack(self):
        code = src("""
            import threading
            import time

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def m(self):
                    with self._lock:
                        x = 1
                    time.sleep(0.01)
                    return x
        """)
        assert check_source(code, path=self.HOT_SRV) == []
        # same blocking code outside server/cache/rollout: not flagged
        assert check_source(self._code("time.sleep(1)"), path=COLD) == []

    def test_negative_str_join_with_args_not_flagged(self):
        findings = check_source(self._code("','.join(['a', 'b'])"),
                                path=self.HOT_SRV)
        assert findings == []

    def test_negative_deferred_closure_not_flagged(self):
        # defining a function under the lock is not calling it
        body = ("def later():\n"
                "                    time.sleep(1)")
        assert check_source(self._code(body), path=self.HOT_SRV) == []

    def test_pragma_suppresses(self):
        body = ("# ptpu: allow[blocking-under-lock] — test fixture\n"
                "            time.sleep(1)")
        assert check_source(self._code(body), path=self.HOT_SRV) == []


class TestCallbackUnderLock:
    BUS = src("""
        import threading

        class Bus:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = []

            def publish(self, x):
                with self._lock:
                    for fn in self._subs:
                        fn(x)
    """)

    def test_positive_loop_variable_callback(self):
        findings = check_source(self.BUS, path=COLD)
        assert rules_of(findings) == ["callback-under-lock"]
        assert "`fn(…)`" in findings[0].message

    def test_positive_param_callback(self):
        code = src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def run(self, hook):
                    with self._lock:
                        hook()
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["callback-under-lock"]

    def test_positive_publish_method_under_lock(self):
        code = src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.bus = None

                def ingest(self, ev):
                    with self._lock:
                        self.bus.publish(ev)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["callback-under-lock"]
        assert ".publish" in findings[0].message

    def test_negative_snapshot_then_call_outside(self):
        # the invalidation-bus pattern: copy under lock, call outside
        code = src("""
            import threading

            class Bus:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._subs = []

                def publish(self, x):
                    with self._lock:
                        subs = list(self._subs)
                    for fn in subs:
                        fn(x)
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_nested_def_called_under_lock(self):
        # a locally-defined function's body is statically known
        code = src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def run(self):
                    def helper():
                        return 1

                    with self._lock:
                        return helper()
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = self.BUS.replace(
            "fn(x)",
            "# ptpu: allow[callback-under-lock] — test fixture\n"
            "                    fn(x)")
        assert check_source(code, path=COLD) == []


class TestCheckFormatsAndBaseline:
    BAD = src("""
        import numpy as np

        def handler(arr):
            return np.asarray(arr)
    """)

    def _bad_dir(self, tmp_path):
        d = tmp_path / "server"
        d.mkdir()
        (d / "bad.py").write_text(self.BAD)
        return tmp_path

    def test_format_json(self, tmp_path, capsys):
        import json

        target = self._bad_dir(tmp_path)
        assert main(["check", str(target), "--format", "json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["count"] == 1
        f = doc["findings"][0]
        assert f["rule"] == "host-sync-in-hot-path"
        assert f["line"] == 5 and f["path"].endswith("bad.py")

    def test_format_sarif(self, tmp_path, capsys):
        import json

        target = self._bad_dir(tmp_path)
        assert main(["check", str(target), "--format", "sarif"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "ptpu-check"
        declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
        from predictionio_tpu.analysis import RULES as rules

        assert set(rules) <= declared
        result = run["results"][0]
        assert result["ruleId"] == "host-sync-in-hot-path"
        loc = result["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] == 5
        assert loc["artifactLocation"]["uri"].endswith("bad.py")

    def test_sarif_clean_run_is_valid(self, tmp_path, capsys):
        import json

        good = tmp_path / "fine.py"
        good.write_text("X = 1\n")
        assert main(["check", str(good), "--format", "sarif"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["runs"][0]["results"] == []

    def test_baseline_write_then_gate(self, tmp_path, capsys):
        target = self._bad_dir(tmp_path)
        bl = tmp_path / "baseline.json"
        assert main(["check", str(target),
                     "--baseline", str(bl), "--write-baseline"]) == 0
        assert bl.exists()
        # baselined finding no longer fails the gate
        assert main(["check", str(target), "--baseline", str(bl)]) == 0
        out = capsys.readouterr()
        assert "baselined" in out.out
        # a NEW finding still fails, and is the only one printed
        (target / "server" / "bad2.py").write_text(self.BAD)
        assert main(["check", str(target), "--baseline", str(bl)]) == 1
        out = capsys.readouterr()
        assert "bad2.py" in out.out
        assert "bad.py:" not in out.out.replace("bad2.py:", "")
        assert "new finding" in out.err

    def test_baseline_counts_per_key(self, tmp_path):
        # two identical findings in one file, baseline records both;
        # a third instance of the same (path, rule, message) fails
        d = tmp_path / "server"
        d.mkdir()
        two = ("import numpy as np\n\n"
               "def handler(arr):\n"
               "    a = np.asarray(arr)\n"
               "    b = np.asarray(arr)\n"
               "    return a, b\n")
        (d / "bad.py").write_text(two)
        bl = tmp_path / "bl.json"
        assert main(["check", str(tmp_path),
                     "--baseline", str(bl), "--write-baseline"]) == 0
        assert main(["check", str(tmp_path), "--baseline", str(bl)]) == 0
        three = two.replace("return a, b",
                            "c = np.asarray(arr)\n    return a, b, c")
        (d / "bad.py").write_text(three)
        assert main(["check", str(tmp_path), "--baseline", str(bl)]) == 1

    def test_missing_baseline_file_is_an_error(self, tmp_path, capsys):
        good = tmp_path / "fine.py"
        good.write_text("X = 1\n")
        assert main(["check", str(good),
                     "--baseline", str(tmp_path / "nope.json")]) == 2
        assert "cannot read baseline" in capsys.readouterr().err

    def test_write_baseline_requires_path(self, tmp_path, capsys):
        good = tmp_path / "fine.py"
        good.write_text("X = 1\n")
        assert main(["check", str(good), "--write-baseline"]) == 2


# ---------------------------------------------------------------------------
# interprocedural layer: call graph + effect summaries (ISSUE 8)
# ---------------------------------------------------------------------------

class TestInterprocedural:
    def test_two_hop_host_sync_reported_at_hot_site(self):
        findings = check_project({
            "pkg/utils/convert.py": src("""
                import numpy as np

                def land(x):
                    return np.asarray(x)
            """),
            "pkg/lib/middle.py": src("""
                from pkg.utils.convert import land

                def shuttle(x):
                    return land(x) + 1
            """),
            "pkg/server/handler.py": src("""
                from pkg.lib.middle import shuttle

                def handle(q):
                    return shuttle(q)
            """),
        })
        assert rules_of(findings) == ["host-sync-in-hot-path"]
        f = findings[0]
        # anchored at the HOT call site, not in the helpers
        assert f.path == "pkg/server/handler.py"
        # ...with the full chain in the message
        assert "shuttle" in f.message and "land" in f.message
        assert "np.asarray" in f.message
        # ...and the hop locations machine-readable for SARIF
        assert [p for p, _, _ in f.related] == [
            "pkg/lib/middle.py", "pkg/utils/convert.py"]

    def test_helper_in_hot_package_not_double_reported(self):
        # the helper's own body gets the direct finding; the call site
        # must not add a second one
        findings = check_project({
            "pkg/server/helper.py": src("""
                import numpy as np

                def land(x):
                    return np.asarray(x)
            """),
            "pkg/server/handler.py": src("""
                from pkg.server.helper import land

                def handle(q):
                    return land(q)
            """),
        })
        assert rules_of(findings) == ["host-sync-in-hot-path"]
        assert findings[0].path == "pkg/server/helper.py"

    def test_pragma_at_direct_site_stops_propagation(self):
        # blessing the one named D2H helper blesses its callers
        findings = check_project({
            "pkg/utils/convert.py": src("""
                import numpy as np

                def land(x):
                    # ptpu: allow[host-sync-in-hot-path] — blessed
                    return np.asarray(x)
            """),
            "pkg/server/handler.py": src("""
                from pkg.utils.convert import land

                def handle(q):
                    return land(q)
            """),
        })
        assert findings == []

    def test_pragma_at_call_site_suppresses(self):
        findings = check_project({
            "pkg/utils/convert.py": src("""
                import numpy as np

                def land(x):
                    return np.asarray(x)
            """),
            "pkg/server/handler.py": src("""
                from pkg.utils.convert import land

                def handle(q):
                    # ptpu: allow[host-sync-in-hot-path] — one-shot
                    return land(q)
            """),
        })
        assert findings == []

    def test_recursion_and_cycles_handled(self):
        # mutual recursion must neither crash nor lose the effect
        findings = check_project({
            "pkg/utils/recur.py": src("""
                import numpy as np

                def a(x):
                    return b(x)

                def b(x):
                    if x:
                        return a(x)
                    return np.asarray(x)
            """),
            "pkg/server/h.py": src("""
                from pkg.utils.recur import a

                def handle(q):
                    return a(q)
            """),
        })
        assert rules_of(findings) == ["host-sync-in-hot-path"]
        assert findings[0].path == "pkg/server/h.py"

    def test_self_recursion_no_crash(self):
        assert check_project({
            "pkg/lib/r.py": "def f(x):\n    return f(x - 1)\n",
        }) == []

    def test_method_vs_function_resolution(self):
        # a module FUNCTION named like a method of another class must
        # not satisfy a self.X() call — only the enclosing class's own
        # method does
        findings = check_project({
            "pkg/utils/sink.py": src("""
                import numpy as np

                def flush(x):
                    return np.asarray(x)
            """),
            "pkg/server/srv.py": src("""
                from pkg.utils.sink import flush

                class Handler:
                    def flush(self, x):
                        return x  # clean method, same name

                    def a(self, q):
                        return self.flush(q)   # clean: own method

                    def b(self, q):
                        return flush(q)        # dirty: module func
            """),
        })
        assert rules_of(findings) == ["host-sync-in-hot-path"]
        assert "in hot function `b`" in findings[0].message \
            or "`Handler.b`" in findings[0].message

    def test_relative_import_resolution(self):
        findings = check_project({
            "predictionio_tpu/utils/conv.py": src("""
                import numpy as np

                def land(x):
                    return np.asarray(x)
            """),
            "predictionio_tpu/server/web.py": src("""
                from ..utils.conv import land

                def handle(q):
                    return land(q)
            """),
        })
        assert rules_of(findings) == ["host-sync-in-hot-path"]
        assert findings[0].path == "predictionio_tpu/server/web.py"

    def test_ambiguous_suffix_resolves_to_nothing(self):
        # two modules define helper(); the call must not guess
        findings = check_project({
            "pkg/a/util.py": src("""
                import numpy as np

                def helper(x):
                    return np.asarray(x)
            """),
            "pkg/b/util.py": src("""
                def helper(x):
                    return x
            """),
            "pkg/server/h.py": src("""
                from util import helper

                def handle(q):
                    return helper(q)
            """),
        })
        assert findings == []

    def test_gather_sink_through_helper(self):
        findings = check_project({
            "pkg/ops/helper.py": src("""
                def fetch_rows(table, ids):
                    return table[ids]
            """),
            "pkg/models/train.py": src("""
                import jax
                from pkg.ops.helper import fetch_rows

                @jax.jit
                def step(table, idx):
                    return fetch_rows(table, idx)
            """),
        }, rule_names=["materialized-gather"])
        assert rules_of(findings) == ["materialized-gather"]
        assert findings[0].path == "pkg/models/train.py"
        assert "fetch_rows" in findings[0].message

    def test_gather_sink_two_hops_and_kwarg(self):
        findings = check_project({
            "pkg/ops/inner.py": src("""
                def raw(table, ids):
                    return table[ids]
            """),
            "pkg/ops/outer.py": src("""
                from pkg.ops.inner import raw

                def fetch(table, rows):
                    return raw(table, rows)
            """),
            "pkg/models/train.py": src("""
                import jax
                from pkg.ops.outer import fetch

                @jax.jit
                def step(table, idx):
                    return fetch(table, rows=idx)
            """),
        }, rule_names=["materialized-gather"])
        assert rules_of(findings) == ["materialized-gather"]
        assert findings[0].path == "pkg/models/train.py"

    def test_blocking_chain_under_lock(self):
        findings = check_project({
            "pkg/server/srv.py": src("""
                import threading

                class Server:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def _slow(self):
                        import time
                        time.sleep(1)

                    def tick(self):
                        with self._lock:
                            self._slow()
            """),
        }, rule_names=["blocking-under-lock"])
        assert rules_of(findings) == ["blocking-under-lock"]
        assert "_slow" in findings[0].message
        assert "time.sleep" in findings[0].message

    def test_callback_delivery_chain_under_lock(self):
        findings = check_project({
            "pkg/cache/bus.py": src("""
                import threading

                class Bus:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.subs = []

                    def _deliver(self, ev):
                        self.bus.publish(ev)

                    def ingest(self, ev):
                        with self._lock:
                            self._deliver(ev)
            """),
        }, rule_names=["callback-under-lock"])
        assert rules_of(findings) == ["callback-under-lock"]
        assert "_deliver" in findings[0].message

    def test_callable_passed_into_invoking_helper_under_lock(self):
        findings = check_project({
            "pkg/cache/run.py": src("""
                import threading

                def run_hook(fn, ev):
                    return fn(ev)

                class Cache:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def fire(self, hook, ev):
                        with self._lock:
                            run_hook(hook, ev)
            """),
        }, rule_names=["callback-under-lock"])
        assert rules_of(findings) == ["callback-under-lock"]
        assert "run_hook" in findings[0].message

    def test_lock_order_edge_through_call(self):
        # with a: self._refill() where _refill takes b, elsewhere
        # with b: takes a — a cycle with no lexical nesting of a and b
        findings = check_project({
            "pkg/cache/two.py": src("""
                import threading

                class Two:
                    def __init__(self):
                        self._a_lock = threading.Lock()
                        self._b_lock = threading.Lock()

                    def _refill(self):
                        with self._b_lock:
                            pass

                    def forward(self):
                        with self._a_lock:
                            self._refill()

                    def backward(self):
                        with self._b_lock:
                            with self._a_lock:
                                pass
            """),
        }, rule_names=["lock-order-inversion"])
        assert rules_of(findings) == ["lock-order-inversion"]
        assert "Two._a_lock" in findings[0].message
        assert "Two._b_lock" in findings[0].message

    def test_cli_reports_two_hop_sync(self, tmp_path, capsys):
        # the acceptance-criteria path: a seeded two-call-deep host
        # sync surfaces through the real `ptpu check` entry point
        (tmp_path / "utils").mkdir()
        (tmp_path / "lib").mkdir()
        (tmp_path / "server").mkdir()
        (tmp_path / "utils" / "conv.py").write_text(src("""
            import numpy as np

            def land(x):
                return np.asarray(x)
        """))
        (tmp_path / "lib" / "mid.py").write_text(src("""
            from utils.conv import land

            def shuttle(x):
                return land(x)
        """))
        (tmp_path / "server" / "web.py").write_text(src("""
            from lib.mid import shuttle

            def handle(q):
                return shuttle(q)
        """))
        assert main(["check", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "host-sync-in-hot-path" in out
        assert "shuttle" in out and "land" in out
        assert "web.py" in out


class TestTakeGather:
    def test_jnp_take_positive(self):
        code = src("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(table, idx):
                return jnp.take(table, idx)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["materialized-gather"]
        assert "jnp.take" in findings[0].message

    def test_jnp_take_along_axis_kwarg(self):
        code = src("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(table, idx):
                return jnp.take_along_axis(table, indices=idx, axis=0)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["materialized-gather"]

    def test_jnp_take_static_index_negative(self):
        code = src("""
            import functools
            import jax
            import jax.numpy as jnp

            @functools.partial(jax.jit, static_argnames=("idx",))
            def step(table, idx):
                return jnp.take(table, idx)
        """)
        assert check_source(code, path=COLD) == []

    def test_jnp_take_outside_hot_dirs_negative(self):
        code = src("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(table, idx):
                return jnp.take(table, idx)
        """)
        assert check_source(code,
                            path="predictionio_tpu/obs/x.py") == []

    def test_jnp_take_pragma(self):
        code = src("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def serve(table, idx):
                # ptpu: allow[materialized-gather] — [B, r] row fetch
                return jnp.take(table, idx)
        """)
        assert check_source(code, path=COLD) == []


# ---------------------------------------------------------------------------
# Pallas kernel-safety rules (ISSUE 8)
# ---------------------------------------------------------------------------

KERNEL_PRELUDE = src("""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
""")


def ksrc(text):
    """Kernel-test source: the pallas prelude + a dedented body (the
    two halves dedent separately — their literal indents differ)."""
    return KERNEL_PRELUDE + src(text)


class TestVmemOverbudget:
    def test_seeded_overbudget_kernel(self):
        code = ksrc("""
            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def big(x):
                return pl.pallas_call(
                    kern,
                    grid=(8,),
                    in_specs=[pl.BlockSpec((4096, 4096),
                                           lambda i: (i, 0),
                                           memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec((4096, 4096),
                                           lambda i: (i, 0),
                                           memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((4096, 4096),
                                                   jnp.float32),
                    interpret=True,
                )(x)
        """)
        findings = check_source(code, path="ops/k.py",
                                rule_names=["vmem-overbudget"])
        assert rules_of(findings) == ["vmem-overbudget"]
        assert "16 MiB" in findings[0].message

    def test_rank_scenario_from_autotune_grid(self):
        # r is free → bound to the autotune rank grid; 128·chunk·r·4B
        # double-buffered clears the budget only at r=128
        code = ksrc("""
            def kern(x_ref, o_ref, acc):
                o_ref[:] = x_ref[:]

            def run(x, r):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((128, 512, r),
                                           lambda i: (i, 0, 0),
                                           memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec((128, 512, r),
                                           lambda i: (i, 0, 0),
                                           memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((512, 512, r),
                                                   jnp.float32),
                    scratch_shapes=[pltpu.VMEM((r, r), jnp.float32)],
                    interpret=True,
                )(x)
        """)
        findings = check_source(code, path="ops/k.py",
                                rule_names=["vmem-overbudget"])
        assert rules_of(findings) == ["vmem-overbudget"]
        assert "rank 128" in findings[0].message

    def test_constraint_makes_scenario_infeasible(self):
        # the block clears the budget at rank 64 and would blow it at
        # rank 128 — but an enclosing bound excludes r=128 (the
        # solve.py scratch-variant pattern), so the call is clean
        code = ksrc("""
            _RP_MAX = 64

            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x, r):
                if r <= _RP_MAX:
                    return pl.pallas_call(
                        kern,
                        grid=(4,),
                        in_specs=[pl.BlockSpec((48, 512, r),
                                               lambda i: (i, 0, 0),
                                               memory_space=pltpu.VMEM)],
                        out_specs=pl.BlockSpec((8, 128),
                                               lambda i: (i, 0),
                                               memory_space=pltpu.VMEM),
                        out_shape=jax.ShapeDtypeStruct((32, 128),
                                                       jnp.float32),
                        interpret=True,
                    )(x)
        """)
        assert check_source(code, path="ops/k.py",
                            rule_names=["vmem-overbudget"]) == []

    def test_same_shapes_without_constraint_flagged_at_128(self):
        # the twin of the test above minus the bound: rank 128 is now
        # feasible and 25 MiB of double-buffered block exceeds budget
        code = ksrc("""
            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x, r):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((48, 512, r),
                                           lambda i: (i, 0, 0),
                                           memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec((8, 128),
                                           lambda i: (i, 0),
                                           memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((32, 128),
                                                   jnp.float32),
                    interpret=True,
                )(x)
        """)
        findings = check_source(code, path="ops/k.py",
                                rule_names=["vmem-overbudget"])
        assert rules_of(findings) == ["vmem-overbudget"]
        assert "rank 128" in findings[0].message

    def test_any_memory_space_not_counted(self):
        # the fused_gram idiom: the big table stays in HBM (ANY) and
        # rows stream via DMA — only VMEM residents count
        code = ksrc("""
            def kern(t_ref, o_ref):
                o_ref[:] = o_ref[:]

            def run(table):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                    out_specs=pl.BlockSpec((8, 128),
                                           lambda i: (i, 0),
                                           memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((32, 128),
                                                   jnp.float32),
                    interpret=True,
                )(table)
        """)
        assert check_source(code, path="ops/k.py",
                            rule_names=["vmem-overbudget"]) == []

    def test_pragma_suppresses(self):
        code = ksrc("""
            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def big(x):
                # ptpu: allow[vmem-overbudget] — measured: fits
                return pl.pallas_call(
                    kern,
                    grid=(8,),
                    in_specs=[pl.BlockSpec((4096, 4096),
                                           lambda i: (i, 0),
                                           memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec((4096, 4096),
                                           lambda i: (i, 0),
                                           memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((4096, 4096),
                                                   jnp.float32),
                    interpret=True,
                )(x)
        """)
        assert check_source(code, path="ops/k.py",
                            rule_names=["vmem-overbudget"]) == []


class TestDmaUnwaited:
    def test_start_without_wait(self):
        code = ksrc("""
            def kern(h_ref, o_ref, buf, sem):
                pltpu.make_async_copy(h_ref.at[0], buf.at[0],
                                      sem.at[0]).start()
                o_ref[:] = buf[0]
        """)
        findings = check_source(code, path="ops/k.py",
                                rule_names=["dma-unwaited"])
        assert rules_of(findings) == ["dma-unwaited"]
        assert "no matching .wait()" in findings[0].message

    def test_var_start_wait_pair_clean(self):
        code = ksrc("""
            def kern(h_ref, o_ref, buf, sem):
                c = pltpu.make_async_copy(h_ref.at[0], buf.at[0],
                                          sem.at[0])
                c.start()
                c.wait()
                o_ref[:] = buf[0]
        """)
        assert check_source(code, path="ops/k.py",
                            rule_names=["dma-unwaited"]) == []

    def test_split_start_and_wait_matched_by_semaphore(self):
        # the fused_gram pipeline idiom: issue in one nested helper,
        # drain in a sibling — matched through the semaphore slot
        code = ksrc("""
            def kern(h_ref, o_ref, buf, sems):
                def issue(slot):
                    pltpu.make_async_copy(h_ref.at[slot],
                                          buf.at[slot],
                                          sems.at[slot]).start()

                def drain(slot):
                    pltpu.make_async_copy(h_ref.at[slot],
                                          buf.at[slot],
                                          sems.at[slot]).wait()

                issue(0)
                drain(0)
                o_ref[:] = buf[0]
        """)
        assert check_source(code, path="ops/k.py",
                            rule_names=["dma-unwaited"]) == []

    def test_slot_restarted_before_wait(self):
        code = ksrc("""
            def kern(h_ref, o_ref, buf, sem):
                pltpu.make_async_copy(h_ref.at[0], buf.at[0],
                                      sem.at[0]).start()
                pltpu.make_async_copy(h_ref.at[1], buf.at[1],
                                      sem.at[0]).start()
                pltpu.make_async_copy(h_ref.at[0], buf.at[0],
                                      sem.at[0]).wait()
                o_ref[:] = buf[0]
        """)
        findings = check_source(code, path="ops/k.py",
                                rule_names=["dma-unwaited"])
        assert rules_of(findings) == ["dma-unwaited"]
        assert "restarted before its wait" in findings[0].message


class TestLowPrecisionAccumulator:
    BF16 = ksrc("""
        def kern(x_ref, o_ref, acc):
            acc[:] = acc[:] + x_ref[:]
            o_ref[:] = acc[:]

        def run(x):
            return pl.pallas_call(
                kern,
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                scratch_shapes=[pltpu.VMEM((8, 128), jnp.bfloat16)],
                interpret=True,
            )(x)
    """)

    def test_bf16_accumulation_flagged(self):
        findings = check_source(
            self.BF16, path="ops/k.py",
            rule_names=["low-precision-accumulator"])
        assert rules_of(findings) == ["low-precision-accumulator"]
        assert "bfloat16" in findings[0].message

    def test_f32_accumulator_clean(self):
        code = self.BF16.replace("jnp.bfloat16)],", "jnp.float32)],")
        assert check_source(
            code, path="ops/k.py",
            rule_names=["low-precision-accumulator"]) == []

    def test_augassign_and_dot_into_bf16(self):
        code = ksrc("""
            def kern(x_ref, o_ref, acc):
                acc[:] += x_ref[:]
                acc[:] = jax.lax.dot_general(
                    x_ref[:], x_ref[:], (((0,), (0,)), ((), ())))
                o_ref[:] = acc[:]

            def run(x):
                return pl.pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((8, 128),
                                                   jnp.float32),
                    scratch_shapes=[pltpu.VMEM((128, 128),
                                               jnp.float16)],
                    interpret=True,
                )(x)
        """)
        findings = check_source(
            code, path="ops/k.py",
            rule_names=["low-precision-accumulator"])
        assert rules_of(findings) == ["low-precision-accumulator"] * 2

    def test_partial_bound_kernel_mapping(self):
        # functools.partial-bound leading args shift the ref mapping —
        # the fused_gram wiring shape
        code = ksrc("""
            def kern(n, x_ref, o_ref, acc):
                acc[:] = acc[:] + x_ref[:]
                o_ref[:] = acc[:]

            def run(x):
                k = functools.partial(kern, 4)
                return pl.pallas_call(
                    k,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((8, 128),
                                                   jnp.float32),
                    scratch_shapes=[pltpu.VMEM((8, 128),
                                               jnp.bfloat16)],
                    interpret=True,
                )(x)
        """)
        findings = check_source(
            code, path="ops/k.py",
            rule_names=["low-precision-accumulator"])
        assert rules_of(findings) == ["low-precision-accumulator"]


class TestMissingInterpretFallback:
    def test_no_interpret_kwarg_flagged(self):
        code = ksrc("""
            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((8, 128),
                                                   jnp.float32),
                )(x)
        """)
        findings = check_source(
            code, path="ops/k.py",
            rule_names=["missing-interpret-fallback"])
        assert rules_of(findings) == ["missing-interpret-fallback"]
        assert "fused_gram_dispatch" in findings[0].message

    def test_interpret_param_clean(self):
        code = ksrc("""
            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x, interpret=False):
                return pl.pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((8, 128),
                                                   jnp.float32),
                    interpret=interpret,
                )(x)
        """)
        assert check_source(
            code, path="ops/k.py",
            rule_names=["missing-interpret-fallback"]) == []

    def test_interpret_false_literal_flagged(self):
        code = ksrc("""
            def kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct((8, 128),
                                                   jnp.float32),
                    interpret=False,
                )(x)
        """)
        findings = check_source(
            code, path="ops/k.py",
            rule_names=["missing-interpret-fallback"])
        assert rules_of(findings) == ["missing-interpret-fallback"]

    def test_non_pallas_module_ignored(self):
        assert check_source(
            "def pallas_call(x):\n    return x\n",
            path="ops/k.py",
            rule_names=["missing-interpret-fallback"]) == []


# ---------------------------------------------------------------------------
# checker robustness: broken files become findings, never crashes
# ---------------------------------------------------------------------------

class TestCheckerRobustness:
    def test_syntax_error_file_is_per_file_finding(self, tmp_path):
        d = tmp_path / "server"
        d.mkdir()
        (d / "broken.py").write_text("def broken(:\n")
        (d / "bad.py").write_text(src("""
            import numpy as np

            def handler(arr):
                return np.asarray(arr)
        """))
        findings = run_check([str(tmp_path)])
        rules = rules_of(findings)
        # the broken file reports, AND the rest of the tree still runs
        assert "parse-error" in rules
        assert "host-sync-in-hot-path" in rules

    def test_undecodable_file_is_per_file_finding(self, tmp_path):
        d = tmp_path / "server"
        d.mkdir()
        (d / "binary.py").write_bytes(b"\xff\xfe\x00\x00garbage")
        (d / "fine.py").write_text("X = 1\n")
        findings = run_check([str(tmp_path)])
        assert rules_of(findings) == ["parse-error"]
        assert "binary.py" in findings[0].path

    def test_cli_exit_code_on_broken_fixture(self, tmp_path, capsys):
        (tmp_path / "broken.py").write_text("def broken(:\n")
        assert main(["check", str(tmp_path)]) == 1
        assert "parse-error" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# baseline ratchet (ISSUE 8 satellite)
# ---------------------------------------------------------------------------

class TestBaselineRatchet:
    TWO = src("""
        import numpy as np

        def handler(arr):
            a = np.asarray(arr)
            b = np.asarray(arr)
            return a, b
    """)
    ONE = src("""
        import numpy as np

        def handler(arr):
            return np.asarray(arr)
    """)

    def _write(self, tmp_path, text):
        d = tmp_path / "server"
        d.mkdir(exist_ok=True)
        (d / "bad.py").write_text(text)

    def test_gate_prints_shrinkable_entries(self, tmp_path, capsys):
        self._write(tmp_path, self.TWO)
        bl = tmp_path / "bl.json"
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        self._write(tmp_path, self.ONE)
        assert main(["check", str(tmp_path),
                     "--baseline", str(bl)]) == 0
        err = capsys.readouterr().err
        assert "ratchet down" in err
        assert "recorded 2, found 1" in err

    def test_write_baseline_auto_tightens(self, tmp_path, capsys):
        from predictionio_tpu.analysis import load_baseline

        self._write(tmp_path, self.TWO)
        bl = tmp_path / "bl.json"
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        self._write(tmp_path, self.ONE)
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        recorded = load_baseline(str(bl))
        assert sum(recorded.values()) == 1  # 2 → 1: ratcheted

    def test_write_baseline_refuses_new_debt(self, tmp_path, capsys):
        self._write(tmp_path, self.ONE)
        bl = tmp_path / "bl.json"
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        # a NEW kind of finding appears; the ratchet must not absorb it
        (tmp_path / "server" / "drift.py").write_text(src("""
            import jax

            def setup():
                jax.config.update("jax_enable_x64", True)
        """))
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline"]) == 1
        err = capsys.readouterr().err
        assert "NOT absorbed" in err
        # the baseline still gates: the new finding fails the gate
        assert main(["check", str(tmp_path),
                     "--baseline", str(bl)]) == 1

    def test_baseline_grow_records_new_debt(self, tmp_path, capsys):
        self._write(tmp_path, self.ONE)
        bl = tmp_path / "bl.json"
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline"]) == 0
        (tmp_path / "server" / "drift.py").write_text(src("""
            import jax

            def setup():
                jax.config.update("jax_enable_x64", True)
        """))
        assert main(["check", str(tmp_path), "--baseline", str(bl),
                     "--write-baseline", "--baseline-grow"]) == 0
        assert main(["check", str(tmp_path),
                     "--baseline", str(bl)]) == 0

    def test_shrinkable_entries_api(self):
        from predictionio_tpu.analysis import shrinkable_entries

        findings = check_source(self.ONE,
                                path="predictionio_tpu/server/s.py")
        assert len(findings) == 1
        key = (findings[0].path, findings[0].rule, findings[0].message)
        shrink = shrinkable_entries(findings, {key: 3})
        assert shrink == [(key, 3, 1)]
        assert shrinkable_entries(findings, {key: 1}) == []


# ---------------------------------------------------------------------------
# runtime complement: recompile sentinel + transfer guard wiring
# ---------------------------------------------------------------------------

@dataclass
class _EchoQuery:
    v: int = 0


class _EchoAlgo:
    query_class = _EchoQuery

    def bind_serving(self, ctx):
        pass

    def prepare_serving_model(self, model, max_batch):
        return model

    def predict(self, model, query):
        return {"doubled": query.v * 2}


class _EchoServing:
    def supplement(self, query):
        return query

    def serve(self, query, predictions):
        return predictions[0]


class _EchoEngine:
    def make_algorithms(self, engine_params):
        return [_EchoAlgo()]

    def make_serving(self, engine_params):
        return _EchoServing()


def _make_query_server(**config_kwargs):
    from predictionio_tpu.data.storage.base import EngineInstance
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
    )

    class _Ctx:
        storage = None

    from predictionio_tpu.data.event import utcnow

    now = utcnow()
    instance = EngineInstance(id="i1", status="COMPLETED",
                              start_time=now, end_time=now,
                              engine_id="echo", engine_version="1",
                              engine_variant="engine.json",
                              engine_factory="tests:echo")
    cfg = ServerConfig(warm_start=False, **config_kwargs)
    return QueryServer(_Ctx(), _EchoEngine(), engine_params=None,
                       models=[None], instance=instance, config=cfg)


class TestRecompileSentinel:
    def test_counts_fresh_compiles_after_arm(self):
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.server.stats import RecompileSentinel

        sentinel = RecompileSentinel()
        assert not sentinel.armed
        assert sentinel.since_armed == 0
        sentinel.arm()
        # a never-before-seen shape forces a fresh XLA compile
        jax.jit(lambda x: x * 3 + 1)(jnp.ones(11))
        snap = sentinel.snapshot()
        assert snap["available"] and snap["armed"]
        assert snap["compilesSinceWarm"] >= 1
        assert snap["compilesTotal"] >= snap["compilesSinceWarm"]

    def test_rearm_resets_baseline(self):
        from predictionio_tpu.server.stats import RecompileSentinel

        sentinel = RecompileSentinel()
        sentinel.arm()
        sentinel.arm()
        assert sentinel.since_armed == 0


class TestServingRuntimeWiring:
    def test_sentinel_armed_and_query_guarded(self):
        import contextlib

        server = _make_query_server(transfer_guard="log")
        assert server.warm_done.is_set()
        assert server.recompile_sentinel.armed
        # post-warmup with a level set: a real jax guard context
        guard = server._transfer_guard()
        assert not isinstance(guard, contextlib.nullcontext)
        result = server.query({"v": 21})
        assert result == {"doubled": 42}

    def test_guard_off_is_noop_context(self):
        import contextlib

        server = _make_query_server(transfer_guard="off")
        assert isinstance(server._transfer_guard(),
                          contextlib.nullcontext)
        server2 = _make_query_server(transfer_guard=None)
        assert isinstance(server2._transfer_guard(),
                          contextlib.nullcontext)

    def test_guard_waits_for_warmup(self):
        import contextlib

        server = _make_query_server(transfer_guard="log")
        server.warm_done.clear()
        assert isinstance(server._transfer_guard(),
                          contextlib.nullcontext)

    def test_status_json_exposes_sentinel_and_guard(self):
        from predictionio_tpu.server.engineserver import build_app

        server = _make_query_server(transfer_guard="log")
        app = build_app(server)
        route = next(h for m, _, _, h in app._routes
                     if getattr(h, "__name__", "") == "status")
        doc = route(None).body
        assert doc["transferGuard"] == "log"
        assert doc["recompile"]["armed"] is True
        assert "compilesSinceWarm" in doc["recompile"]

    def test_disallowed_transfer_rejected_under_guard(self):
        import jax.numpy as jnp
        import numpy as np

        server = _make_query_server(transfer_guard="disallow")
        with pytest.raises(Exception):
            with server._transfer_guard():
                np.asarray(jnp.ones(13) + 1)  # implicit D2H


# ---------------------------------------------------------------------------
# SPMD sharding-flow rule family (ISSUE 14)
# ---------------------------------------------------------------------------

class TestShardingMismatchGeneralized:
    """ISSUE 14 satellite: bare P() literals the alias table cannot
    resolve, and shard_map in_specs=/out_specs= keyword forms."""

    def test_positive_bare_jax_p(self):
        code = src("""
            import jax

            SPEC = jax.P("bogus")
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]
        assert "bogus" in findings[0].message

    def test_positive_star_import_p(self):
        code = src("""
            from jax.sharding import *

            SPEC = P("nope")
        """)
        findings = check_source(code, path=COLD)
        assert "sharding-mismatch" in rules_of(findings)

    def test_positive_shard_map_kwarg_specs(self):
        code = src("""
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, body):
                return shard_map_compat(body, mesh,
                                        in_specs=(P("typo_axis"),),
                                        out_specs=P())
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]
        assert "typo_axis" in findings[0].message

    def test_positive_shard_map_bare_string_specs(self):
        # a compat wrapper accepting bare axis strings in the spec
        # kwarg — no P() call anywhere, still checked
        code = src("""
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, body):
                return shard_map_compat(body, mesh,
                                        in_specs=("wrong",),
                                        out_specs=("model",))
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]
        assert "wrong" in findings[0].message

    def test_negative_declared_axes_every_form(self):
        code = src("""
            import jax
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            A = jax.P("batch")

            def build(mesh, body):
                return shard_map_compat(body, mesh,
                                        in_specs=(jax.P("model"),),
                                        out_specs=jax.P())
        """)
        assert check_source(code, path=COLD) == []

    def test_no_double_report_p_inside_shard_map_kwarg(self):
        # one bad axis inside a resolvable P inside in_specs= must
        # yield exactly ONE finding, not one per covering branch
        code = src("""
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, body):
                return shard_map_compat(body, mesh,
                                        in_specs=(P("oops"),),
                                        out_specs=(P(),))
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["sharding-mismatch"]


class TestShardMapSpecMismatch:
    def test_positive_in_specs_arity(self):
        code = src("""
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, x):
                def body(a, b):
                    return a + b
                fn = shard_map_compat(body, mesh,
                                      in_specs=(P("model"),),
                                      out_specs=P())
                return fn(x)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["shard-map-spec-mismatch"]
        assert "in_specs carries 1" in findings[0].message

    def test_positive_out_specs_arity(self):
        code = src("""
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, x):
                def body(a):
                    return a, a
                return shard_map_compat(body, mesh,
                                        in_specs=(P("model"),),
                                        out_specs=P())(x)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["shard-map-spec-mismatch"]
        assert "2-tuple" in findings[0].message

    def test_positive_axis_group_mixing(self):
        # "data" (training mesh) with "batch" (serving mesh): both
        # declared, but no single mesh carries both
        code = src("""
            import jax
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, x):
                def body(a):
                    return jax.lax.psum(a, "data")
                return shard_map_compat(body, mesh,
                                        in_specs=(P("batch"),),
                                        out_specs=(P("batch"),))(x)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["shard-map-spec-mismatch"]
        assert "different declared meshes" in findings[0].message

    def test_negative_coherent_site(self):
        code = src("""
            import jax
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, x, y):
                def body(a, b):
                    return jax.lax.psum(a + b, "model"), a
                return shard_map_compat(body, mesh,
                                        in_specs=(P("model"), P()),
                                        out_specs=(P(), P("model")))(x, y)
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_rows_spec_symbolic(self):
        # rows_spec(mesh) is mesh-agnostic — no static arity/axis claim
        # beyond the spec count itself
        code = src("""
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def build(mesh, x, y):
                spec = rows_spec(mesh)
                def body(a, b):
                    return a + b
                return shard_map_compat(body, mesh,
                                        in_specs=(P(), spec),
                                        out_specs=spec)(x, y)
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            from jax.sharding import PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def build(mesh, x):
                def body(a, b):
                    return a + b
                # ptpu: allow[shard-map-spec-mismatch] — b is bound by
                # functools.partial upstream of this wrapper
                fn = shard_map_compat(body, mesh,
                                      in_specs=(P("model"),),
                                      out_specs=P())
                return fn(x)
        """)
        assert check_source(code, path=COLD) == []


class TestImplicitReshard:
    def test_positive_direct(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def run(mesh, host):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                def body(t):
                    return t.sum()
                fn = shard_map_compat(body, mesh, in_specs=(P(),),
                                      out_specs=P())
                return fn(table)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["implicit-reshard"]
        assert "rows(*)" in findings[0].message
        assert "P()" in findings[0].message

    def test_positive_interprocedural_with_chain(self):
        files = {
            "predictionio_tpu/models/helper.py": src("""
                from jax.sharding import PartitionSpec as P
                from predictionio_tpu.parallel.collectives import \\
                    shard_map_compat

                def consume(table, mesh):
                    def body(t):
                        return t.sum()
                    fn = shard_map_compat(body, mesh, in_specs=(P(),),
                                          out_specs=P())
                    return fn(table)
            """),
            "predictionio_tpu/models/train.py": src("""
                import jax
                from jax.sharding import NamedSharding
                from predictionio_tpu.parallel.mesh import rows_spec
                from predictionio_tpu.models.helper import consume

                def step(mesh, host):
                    U = jax.device_put(
                        host, NamedSharding(mesh, rows_spec(mesh)))
                    return consume(U, mesh)
            """),
        }
        findings = check_project(files)
        assert rules_of(findings) == ["implicit-reshard"]
        f = findings[0]
        assert f.path == "predictionio_tpu/models/train.py"
        assert "consume" in f.message and "rows(*)" in f.message
        # the chain walks down to the shard_map boundary
        assert f.related and \
            f.related[-1][0] == "predictionio_tpu/models/helper.py"

    def test_negative_matching_specs(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def run(mesh, host):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                spec = rows_spec(mesh)
                def body(t):
                    return t.sum()
                fn = shard_map_compat(body, mesh, in_specs=(spec,),
                                      out_specs=P())
                return fn(table)
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_full_group_literal_equals_rows(self):
        # P(("data","model")) IS rows_spec on the training mesh — the
        # two spellings must not count as a reshard
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def run(mesh, host):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                def body(t):
                    return t.sum()
                fn = shard_map_compat(
                    body, mesh, in_specs=(P(("data", "model")),),
                    out_specs=P())
                return fn(table)
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_at_boundary_blesses_callers(self):
        files = {
            "predictionio_tpu/models/helper.py": src("""
                from jax.sharding import PartitionSpec as P
                from predictionio_tpu.parallel.collectives import \\
                    shard_map_compat

                def consume(table, mesh):
                    def body(t):
                        return t.sum()
                    fn = shard_map_compat(body, mesh, in_specs=(P(),),
                                          out_specs=P())
                    # ptpu: allow[implicit-reshard] — the table enters
                    # replicated by design (same all-gather the GSPMD
                    # gather pays); documented boundary
                    return fn(table)
            """),
            "predictionio_tpu/models/train.py": src("""
                import jax
                from jax.sharding import NamedSharding
                from predictionio_tpu.parallel.mesh import rows_spec
                from predictionio_tpu.models.helper import consume

                def step(mesh, host):
                    U = jax.device_put(
                        host, NamedSharding(mesh, rows_spec(mesh)))
                    return consume(U, mesh)
            """),
        }
        assert check_project(files) == []


class TestUnshardedCapture:
    def test_positive_shard_map_closure(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def run(mesh, host, idx):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                def body(i):
                    return table[i]
                fn = shard_map_compat(body, mesh,
                                      in_specs=(P("model"),),
                                      out_specs=P("model"))
                return fn(idx)
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["unsharded-capture"]
        assert "table" in findings[0].message

    def test_positive_jit_closure(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding
            from predictionio_tpu.parallel.mesh import rows_spec

            def build(mesh, host):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                @jax.jit
                def score(v):
                    return v @ table.T
                return score
        """)
        findings = check_source(code, path=COLD)
        assert "unsharded-capture" in rules_of(findings)

    def test_negative_passed_as_argument(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def run(mesh, host, idx):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                spec = rows_spec(mesh)
                def body(t, i):
                    return t[i]
                fn = shard_map_compat(body, mesh,
                                      in_specs=(spec, P("model")),
                                      out_specs=P("model"))
                return fn(table, idx)
        """)
        assert check_source(code, path=COLD) == []

    def test_negative_replicated_capture_fine(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat

            def run(mesh, host, idx):
                g = jax.device_put(host, NamedSharding(mesh, P()))
                def body(i):
                    return g[i]
                fn = shard_map_compat(body, mesh,
                                      in_specs=(P("model"),),
                                      out_specs=P("model"))
                return fn(idx)
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from predictionio_tpu.parallel.collectives import \\
                shard_map_compat
            from predictionio_tpu.parallel.mesh import rows_spec

            def run(mesh, host, idx):
                table = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                def body(i):
                    return table[i]
                # ptpu: allow[unsharded-capture] — [k, r] pinned tile,
                # deliberately replicated per device
                fn = shard_map_compat(body, mesh,
                                      in_specs=(P("model"),),
                                      out_specs=P("model"))
                return fn(idx)
        """)
        assert check_source(code, path=COLD) == []


class TestMissingDonationSharded:
    FILES = {
        "predictionio_tpu/models/stepmod.py": src("""
            import jax

            @jax.jit
            def half_step(U, hist):
                return U * 2
        """),
        "predictionio_tpu/models/train2.py": src("""
            import jax
            from jax.sharding import NamedSharding
            from predictionio_tpu.parallel.mesh import rows_spec
            from predictionio_tpu.models.stepmod import half_step

            def train(mesh, host, hist):
                U = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                for _ in range(4):
                    U = half_step(U, hist)
                return U
        """),
    }

    def test_positive_cross_module_rebind(self):
        findings = check_project(self.FILES)
        assert rules_of(findings) == ["missing-donation-sharded"]
        f = findings[0]
        assert f.path == "predictionio_tpu/models/train2.py"
        assert "half_step" in f.message and "rows(*)" in f.message
        # related points at the jit site missing the donation
        assert f.related and \
            f.related[0][0] == "predictionio_tpu/models/stepmod.py"

    def test_negative_donated(self):
        files = dict(self.FILES)
        files["predictionio_tpu/models/stepmod.py"] = src("""
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def half_step(U, hist):
                return U * 2
        """)
        assert check_project(files) == []

    def test_negative_same_module_is_plain_rules_job(self):
        # same-module rebinds are missing-donation's (which fires);
        # the sharded rule must not double-report
        code = src("""
            import jax
            from jax.sharding import NamedSharding
            from predictionio_tpu.parallel.mesh import rows_spec

            @jax.jit
            def half_step(U, hist):
                return U * 2

            def train(mesh, host, hist):
                U = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                U = half_step(U, hist)
                return U
        """)
        findings = check_source(code, path=COLD)
        assert rules_of(findings) == ["missing-donation"]

    def test_pragma_suppresses(self):
        files = dict(self.FILES)
        files["predictionio_tpu/models/train2.py"] = src("""
            import jax
            from jax.sharding import NamedSharding
            from predictionio_tpu.parallel.mesh import rows_spec
            from predictionio_tpu.models.stepmod import half_step

            def train(mesh, host, hist):
                U = jax.device_put(
                    host, NamedSharding(mesh, rows_spec(mesh)))
                for _ in range(4):
                    # ptpu: allow[missing-donation-sharded] — U is
                    # checkpoint-retained across steps by design
                    U = half_step(U, hist)
                return U
        """)
        assert check_project(files) == []


class TestShardingPragmaCensus:
    def test_counts_per_rule(self, tmp_path):
        from predictionio_tpu.analysis import count_sharding_pragmas

        (tmp_path / "a.py").write_text(src("""
            # ptpu: allow[implicit-reshard] — documented boundary
            x = 1
            # ptpu: allow[unsharded-capture,sharding-mismatch] — tile
            y = 2
            # ptpu: allow[host-sync-in-hot-path] — not sharding
            z = 3
        """))
        counts = count_sharding_pragmas(str(tmp_path))
        assert counts == {"implicit-reshard": 1,
                          "unsharded-capture": 1,
                          "sharding-mismatch": 1}

    def test_repo_census_matches_gauge_source(self):
        # whatever the tree carries, the census is non-negative ints
        # keyed by family rules only
        from predictionio_tpu.analysis import (
            SHARDING_RULES,
            count_sharding_pragmas,
        )

        counts = count_sharding_pragmas()
        assert all(rule in SHARDING_RULES for rule in counts)
        assert all(isinstance(n, int) and n > 0
                   for n in counts.values())


# ---------------------------------------------------------------------------
# the resource-lifecycle family (ISSUE 20)
# ---------------------------------------------------------------------------

SRV = "predictionio_tpu/server/svc.py"     # thread/queue/spin scopes
FLEET = "predictionio_tpu/fleet/scrape.py"  # net-timeout scope
SLO = "predictionio_tpu/slo/persist.py"     # durable-state scope


class TestLeakedThread:
    def test_positive_looping_daemon_never_joined(self):
        code = src("""
            import threading
            import time

            class Poller:
                def start(self):
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()

                def stop(self):
                    pass

                def _run(self):
                    while True:
                        time.sleep(0.1)
        """)
        findings = check_source(code, path=SRV)
        assert rules_of(findings) == ["leaked-thread"]
        assert "_run" in findings[0].message
        assert "join" in findings[0].message

    def test_positive_stop_event_loop_without_join(self):
        # signalling the event without joining still abandons the
        # thread mid-iteration — the join half is required too
        code = src("""
            import threading

            class Poller:
                def __init__(self):
                    self._stop = threading.Event()

                def start(self):
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()

                def close(self):
                    self._stop.set()

                def _run(self):
                    while not self._stop.is_set():
                        self._stop.wait(0.1)
        """)
        findings = check_source(code, path=SRV)
        assert rules_of(findings) == ["leaked-thread"]

    def test_negative_joined_in_close(self):
        code = src("""
            import threading

            class Poller:
                def __init__(self):
                    self._stop = threading.Event()

                def start(self):
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()

                def close(self):
                    self._stop.set()
                    self._t.join()

                def _run(self):
                    while not self._stop.is_set():
                        self._stop.wait(0.1)
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_one_shot_target(self):
        # a warmup thread ends on its own: no loop, no finding
        code = src("""
            import threading

            class Server:
                def start(self):
                    threading.Thread(
                        target=self._warm, daemon=True).start()

                def _warm(self):
                    self.model.warm()
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_appended_to_roster_joined_elsewhere(self):
        # handles stored via self._workers.append and joined through
        # `for t in self._workers` in another method
        code = src("""
            import threading
            import time

            class Pool:
                def __init__(self):
                    self._workers = []

                def start(self):
                    for _ in range(2):
                        self._workers.append(threading.Thread(
                            target=self._run, daemon=True))
                    for t in self._workers:
                        t.start()

                def close(self):
                    for t in self._workers:
                        t.join()

                def _run(self):
                    while True:
                        time.sleep(1)
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_handle_returned_to_caller(self):
        code = src("""
            import threading
            import time

            class Spawner:
                def spawn(self):
                    t = threading.Thread(
                        target=self._run, daemon=True)
                    t.start()
                    return t

                def _run(self):
                    while True:
                        time.sleep(1)
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_joiner_helper_via_call_graph(self):
        # a helper that joins its parameter blesses the spawner that
        # hands it the handle
        findings = check_project({
            "pkg/server/stop.py": src("""
                def reap(t, timeout):
                    t.join(timeout=timeout)
            """),
            "pkg/server/spawn.py": src("""
                import threading
                import time

                from pkg.server.stop import reap

                class Box:
                    def run_once(self):
                        t = threading.Thread(
                            target=self._run, daemon=True)
                        t.start()
                        reap(t, 5.0)

                    def _run(self):
                        while True:
                            time.sleep(1)
            """),
        })
        assert findings == []

    def test_negative_outside_scope(self):
        code = src("""
            import threading
            import time

            class Poller:
                def start(self):
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()

                def _run(self):
                    while True:
                        time.sleep(0.1)
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            import threading
            import time

            class Poller:
                def start(self):
                    # ptpu: allow[leaked-thread] — process-lifetime
                    # metrics pump by design
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()

                def _run(self):
                    while True:
                        time.sleep(0.1)
        """)
        assert check_source(code, path=SRV) == []


class TestMissingTimeout:
    def test_positive_urlopen_no_timeout(self):
        code = src("""
            import urllib.request

            def scrape(url):
                with urllib.request.urlopen(url) as resp:
                    return resp.read()
        """)
        findings = check_source(code, path=FLEET)
        assert rules_of(findings) == ["missing-timeout"]
        assert "urlopen" in findings[0].message

    def test_positive_create_connection_no_timeout(self):
        code = src("""
            import socket

            def probe(addr):
                return socket.create_connection(addr)
        """)
        findings = check_source(code, path=FLEET)
        assert rules_of(findings) == ["missing-timeout"]

    def test_positive_http_connection_ctor(self):
        code = src("""
            import http.client

            def connect(host):
                return http.client.HTTPConnection(host)
        """)
        findings = check_source(code, path=FLEET)
        assert rules_of(findings) == ["missing-timeout"]

    def test_negative_timeout_keyword(self):
        code = src("""
            import urllib.request

            def scrape(url):
                with urllib.request.urlopen(url, timeout=5.0) as r:
                    return r.read()
        """)
        assert check_source(code, path=FLEET) == []

    def test_negative_timeout_positional(self):
        code = src("""
            import socket

            def probe(addr):
                return socket.create_connection(addr, 3.0)
        """)
        assert check_source(code, path=FLEET) == []

    def test_negative_outside_scope(self):
        code = src("""
            import urllib.request

            def fetch(url):
                return urllib.request.urlopen(url)
        """)
        assert check_source(code, path=COLD) == []

    def test_two_hop_chain_reported_at_fleet_site(self):
        # the hang sits two helpers away; the finding lands at the
        # in-scope call site with the chain down to the direct call
        findings = check_project({
            "pkg/net/raw.py": src("""
                import urllib.request

                def fetch(url):
                    return urllib.request.urlopen(url)
            """),
            "pkg/lib/client.py": src("""
                from pkg.net.raw import fetch

                def pull(url):
                    return fetch(url)
            """),
            "pkg/fleet/scrape.py": src("""
                from pkg.lib.client import pull

                def scrape(url):
                    return pull(url)
            """),
        })
        assert rules_of(findings) == ["missing-timeout"]
        f = findings[0]
        assert f.path == "pkg/fleet/scrape.py"
        assert "pull" in f.message and "fetch" in f.message
        assert [p for p, _, _ in f.related] == [
            "pkg/lib/client.py", "pkg/net/raw.py"]

    def test_pragma_at_direct_site_stops_propagation(self):
        # blessing the helper blesses its callers: the net_wait
        # effect dies at the pragma'd direct site
        findings = check_project({
            "pkg/net/raw.py": src("""
                import urllib.request

                def fetch(url):
                    # ptpu: allow[missing-timeout] — caller sets
                    # socket.setdefaulttimeout at boot
                    return urllib.request.urlopen(url)
            """),
            "pkg/fleet/scrape.py": src("""
                from pkg.net.raw import fetch

                def scrape(url):
                    return fetch(url)
            """),
        })
        assert findings == []

    def test_pragma_suppresses_direct(self):
        code = src("""
            import urllib.request

            def scrape(url):
                # ptpu: allow[missing-timeout] — bounded by the
                # caller's deadline wrapper
                return urllib.request.urlopen(url)
        """)
        assert check_source(code, path=FLEET) == []


class TestNonAtomicPersist:
    def test_positive_plain_rewrite(self):
        code = src("""
            import json

            def save(path, state):
                with open(path, "w") as fh:
                    json.dump(state, fh)
        """)
        findings = check_source(code, path=SLO)
        assert rules_of(findings) == ["non-atomic-persist"]
        assert "os.replace" in findings[0].message

    def test_negative_tmp_plus_replace_funnel(self):
        code = src("""
            import json
            import os

            def save(path, state):
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump(state, fh)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
        """)
        assert check_source(code, path=SLO) == []

    def test_negative_append_only_log(self):
        # append-only tears at most the trailing record; replay
        # truncates it — a legitimate durable pattern
        code = src("""
            def log_event(path, line):
                with open(path, "a") as fh:
                    fh.write(line)
        """)
        assert check_source(code, path=SLO) == []

    def test_negative_read_mode(self):
        code = src("""
            import json

            def load(path):
                with open(path, "r") as fh:
                    return json.load(fh)
        """)
        assert check_source(code, path=SLO) == []

    def test_negative_outside_scope(self):
        code = src("""
            def save(path, text):
                with open(path, "w") as fh:
                    fh.write(text)
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            def save(path, text):
                # ptpu: allow[non-atomic-persist] — scratch file on
                # tmpfs, rebuilt from scratch on boot
                with open(path, "w") as fh:
                    fh.write(text)
        """)
        assert check_source(code, path=SLO) == []


class TestUnboundedQueue:
    def test_positive_queue_and_deque(self):
        code = src("""
            import collections
            import queue

            class Batcher:
                def __init__(self):
                    self.q = queue.Queue()
                    self.window = collections.deque()
        """)
        findings = check_source(code, path=SRV)
        assert rules_of(findings) == ["unbounded-queue"] * 2
        assert "maxsize" in findings[0].message
        assert "maxlen" in findings[1].message

    def test_positive_explicit_zero_bound(self):
        # maxsize=0 means infinite — same finding
        code = src("""
            import queue

            class Batcher:
                def __init__(self):
                    self.q = queue.Queue(maxsize=0)
        """)
        findings = check_source(code, path=SRV)
        assert rules_of(findings) == ["unbounded-queue"]

    def test_negative_bounded(self):
        code = src("""
            import collections
            import queue

            class Batcher:
                def __init__(self):
                    self.q = queue.Queue(maxsize=128)
                    self.window = collections.deque(maxlen=32)
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_outside_scope(self):
        code = src("""
            import queue

            class Batcher:
                def __init__(self):
                    self.q = queue.Queue()
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            import queue

            class Batcher:
                def __init__(self):
                    # ptpu: allow[unbounded-queue] — depth bounded by
                    # the HTTP worker pool blocked on done-Events
                    self.q = queue.Queue()
        """)
        assert check_source(code, path=SRV) == []


class TestHotSpinLoop:
    def test_positive_busy_poll(self):
        code = src("""
            def pump(q):
                while True:
                    if q.empty():
                        continue
                    handle(q.get_nowait())
        """)
        findings = check_source(code, path=SRV)
        assert rules_of(findings) == ["hot-spin-loop"]
        assert "stop-event" in findings[0].message

    def test_positive_itertools_count(self):
        code = src("""
            import itertools

            def spin(work):
                for i in itertools.count():
                    work(i)
        """)
        findings = check_source(code, path=SRV)
        assert rules_of(findings) == ["hot-spin-loop"]

    def test_negative_blocking_get_paces(self):
        code = src("""
            def pump(q):
                while True:
                    handle(q.get())
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_sleep_paces(self):
        code = src("""
            import time

            def tick(step):
                while True:
                    step()
                    time.sleep(1.0)
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_stop_event_checked(self):
        code = src("""
            def run(stop, step):
                while True:
                    if stop.is_set():
                        return
                    step()
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_generator_is_consumer_paced(self):
        code = src("""
            def feed(it):
                while True:
                    yield next(it)
        """)
        assert check_source(code, path=SRV) == []

    def test_negative_outside_scope(self):
        code = src("""
            def pump(q):
                while True:
                    if q.empty():
                        continue
                    handle(q.get_nowait())
        """)
        assert check_source(code, path=COLD) == []

    def test_pragma_suppresses(self):
        code = src("""
            def pump(q):
                # ptpu: allow[hot-spin-loop] — benchmark harness
                # measuring poll latency on purpose
                while True:
                    if q.empty():
                        continue
                    handle(q.get_nowait())
        """)
        assert check_source(code, path=SRV) == []
