"""Fault-injection registry (ISSUE 11): spec grammar, deterministic
seeded schedules, point/label matching, modes, and the global fire()
fast path."""

import time

import pytest

from predictionio_tpu import faults
from predictionio_tpu.faults import FaultError, FaultSpec, parse_specs
from predictionio_tpu.faults.registry import FaultRegistry


@pytest.fixture(autouse=True)
def _clean_global_registry():
    yield
    faults.clear()


class TestSpecGrammar:
    def test_basic(self):
        (s,) = parse_specs("storage.io=error")
        assert s.point == "storage.io" and s.mode == "error"
        assert s.rate == 1.0 and s.times == -1 and s.after == 0

    def test_options_and_labels(self):
        (s,) = parse_specs(
            "serving.lane=error,rate=0.5,times=3,after=2,seed=7,lane=1")
        assert s.rate == 0.5 and s.times == 3 and s.after == 2
        assert s.seed == 7 and s.match == {"lane": "1"}

    def test_multiple_specs(self):
        specs = parse_specs(
            "checkpoint.commit=crash,after=2; storage.io=latency,"
            "delay_ms=5")
        assert [s.mode for s in specs] == ["crash", "latency"]
        assert specs[1].delay_ms == 5.0

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError):
            parse_specs("nonsense")
        with pytest.raises(ValueError):
            parse_specs("p=error,rate=")
        with pytest.raises(ValueError, match="mode"):
            parse_specs("p=explode")
        with pytest.raises(ValueError, match="rate"):
            FaultSpec(point="p", rate=1.5)


class TestSchedules:
    def test_error_mode_raises_with_point(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="storage.io"))
        with pytest.raises(FaultError) as ei:
            r.fire("storage.io")
        assert ei.value.point == "storage.io"

    def test_times_bounds_injections(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="p", times=2))
        for _ in range(2):
            with pytest.raises(FaultError):
                r.fire("p")
        r.fire("p")  # budget spent: passes through
        assert r.status()["injections"] == {"p|error": 2}

    def test_after_skips_first_n(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="p", after=3, times=1))
        for _ in range(3):
            r.fire("p")
        with pytest.raises(FaultError):
            r.fire("p")
        r.fire("p")

    def test_rate_is_seed_deterministic(self):
        def run():
            r = FaultRegistry()
            r.inject(FaultSpec(point="p", rate=0.4, seed=11))
            hits = []
            for i in range(50):
                try:
                    r.fire("p")
                    hits.append(0)
                except FaultError:
                    hits.append(1)
            return hits

        a, b = run(), run()
        assert a == b
        assert 5 < sum(a) < 45  # actually probabilistic, not 0/1

    def test_label_match(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="serving.lane", match={"lane": "1"}))
        r.fire("serving.lane", lane=0)
        with pytest.raises(FaultError):
            r.fire("serving.lane", lane=1)

    def test_glob_point(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="checkpoint.*", times=2))
        with pytest.raises(FaultError):
            r.fire("checkpoint.save")
        with pytest.raises(FaultError):
            r.fire("checkpoint.commit")
        r.fire("storage.io")

    def test_latency_mode_sleeps_then_proceeds(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="p", mode="latency", delay_ms=30))
        t0 = time.monotonic()
        r.fire("p")  # no raise
        assert time.monotonic() - t0 >= 0.025

    def test_clear(self):
        r = FaultRegistry()
        r.inject(FaultSpec(point="a"))
        r.inject(FaultSpec(point="b"))
        assert r.enabled()
        assert r.clear("a") == 1
        assert r.clear() == 1
        assert not r.enabled()
        r.fire("a")
        r.fire("b")

    def test_listener_observes_injections(self):
        r = FaultRegistry()
        seen = []
        r.add_listener(lambda point, mode: seen.append((point, mode)))
        r.inject(FaultSpec(point="p", times=1))
        with pytest.raises(FaultError):
            r.fire("p")
        r.fire("p")
        assert seen == [("p", "error")]


class TestGlobalFire:
    def test_noop_when_disarmed(self):
        # must never raise or require the registry lock on the fast path
        faults.fire("storage.io", op="insert")

    def test_inject_spec_and_status(self):
        faults.inject_spec("storage.io=error,times=1")
        assert faults.enabled()
        with pytest.raises(FaultError):
            faults.fire("storage.io")
        st = faults.status()
        # >=: the process-wide registry accumulates counts across tests
        assert st["fired"]["storage.io"] >= 1
        assert st["injections"]["storage.io|error"] >= 1

    def test_env_loading(self, monkeypatch):
        monkeypatch.setenv("PTPU_FAULTS", "a.b=error,times=1")
        r = FaultRegistry()
        r.load_env()
        r.load_env()  # idempotent: loads once
        assert len(r.status()["armed"]) == 1

    def test_points_catalog_populated(self):
        # the instrumented subsystems declare their points at import
        import predictionio_tpu.server.engineserver  # noqa: F401
        import predictionio_tpu.streaming.trainer  # noqa: F401
        import predictionio_tpu.workflow.checkpoint  # noqa: F401

        for point in ("storage.io", "storage.remote", "serving.lane",
                      "serving.lane_restart", "serving.dispatch",
                      "stream.pass", "checkpoint.save",
                      "checkpoint.commit", "checkpoint.restore",
                      "multihost.collective"):
            assert point in faults.POINTS, point
