"""Multi-host (multi-controller) execution: a REAL 2-process run on CPU.

The reference scaled by placing Spark executors across hosts
(``tools/.../Runner.scala:185``); here two OS processes join one JAX
system over a localhost coordinator (gloo CPU collectives), each feeds
the history rows its own devices own (``pack_ratings_multihost`` →
``jax.make_array_from_process_local_data``), and the trained factors
must equal the single-process result bit-for-tolerance.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from predictionio_tpu.models.als import ALSParams, RatingsCOO, train_als

WORKER = textwrap.dedent("""
    import json, os, sys
    import numpy as np

    pid = int(sys.argv[1])
    port = sys.argv[2]
    outdir = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()

    from jax.sharding import NamedSharding, PartitionSpec as P
    from predictionio_tpu.models.als import (
        ALSParams, RatingsCOO, pack_ratings, train_als)
    from predictionio_tpu.parallel.multihost import global_mesh, host_shard

    # identical global COO on every process (v1 feeding contract)
    rng = np.random.default_rng(7)
    nnz, n_users, n_items = 900, 64, 40
    ratings = RatingsCOO(rng.integers(0, n_users, nnz).astype(np.int32),
                         rng.integers(0, n_items, nnz).astype(np.int32),
                         rng.random(nnz).astype(np.float32) * 4 + 1,
                         n_users, n_items)
    mesh = global_mesh(data=8)
    params = ALSParams(rank=4, num_iterations=3, reg=0.05, seed=5)
    packed = pack_ratings(ratings, params, mesh)  # routes to multihost
    U, V = train_als(ratings, params, mesh=mesh, packed=packed)

    # exercise host_shard too: each process's slice of a global array
    hs = host_shard(np.arange(10))
    assert len(hs) == 5, hs

    # v2 contract (partial reads): the same problem fed through a
    # sharded source — each process must MATERIALIZE only ~its half of
    # the log (VERDICT r2 task 5), and the factors must match v1's.
    from predictionio_tpu.data.columnar import (
        ColumnarDicts, columnar_from_columns)
    from predictionio_tpu.models.als import pack_ratings_multihost
    from predictionio_tpu.models.data import ColumnarRatingsSource

    batch = columnar_from_columns(
        ColumnarDicts(),
        ["rate"] * nnz, ["user"] * nnz,
        [f"u{u:05d}" for u in ratings.users],
        ["item"] * nnz,
        [f"i{i:05d}" for i in ratings.items],
        np.arange(nnz, dtype=np.int64),
        [None] * nnz, float_props=())
    batch.float_props["rating"] = ratings.ratings.astype(np.float64)
    src = ColumnarRatingsSource(batch, chunk=257)
    touched = {"n": 0}
    orig_read = src.read_rows
    def counting_read(side, start, stop):
        r, c, v = orig_read(side, start, stop)
        touched["n"] += len(r)
        return r, c, v
    src.read_rows = counting_read
    packed2 = pack_ratings_multihost(src, params, mesh)
    # each side reads ~nnz/2 per process -> ~nnz total, not 2*nnz
    assert touched["n"] <= 1.25 * nnz, touched
    U2, V2 = train_als(None, params, mesh=mesh, packed=packed2)

    # replicate through the compiled program, then read locally
    rep = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))
    U_full = np.asarray(rep(U).addressable_data(0))
    V_full = np.asarray(rep(V).addressable_data(0))
    U2_full = np.asarray(rep(U2).addressable_data(0))
    V2_full = np.asarray(rep(V2).addressable_data(0))
    # v2 equivalence check: SAME problem and indexation, fed the v1 way
    # (global COO on every host) — only the feeding path differs, so the
    # factors must agree tightly
    coo_v2 = src.to_coo()
    packed_v1 = pack_ratings_multihost(coo_v2, params, mesh)
    U3, V3 = train_als(coo_v2, params, mesh=mesh, packed=packed_v1)
    U3_full = np.asarray(rep(U3).addressable_data(0))
    V3_full = np.asarray(rep(V3).addressable_data(0))
    np.testing.assert_allclose(U2_full, U3_full, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(V2_full, V3_full, rtol=1e-4, atol=1e-5)
    # v3: DROP-FREE bucketed multihost on skewed data (the layout the
    # pad path would truncate) — each process packs only its own bucket
    # rows; factors must match the single-process bucket run (parent).
    rng2 = np.random.default_rng(21)
    nnz2 = 1200
    r2 = RatingsCOO(rng2.integers(0, 48, nnz2).astype(np.int32),
                    ((rng2.zipf(1.2, nnz2) - 1) % 24).astype(np.int32),
                    np.ones(nnz2, np.float32), 48, 24)
    params2 = ALSParams(rank=4, num_iterations=2, seed=9,
                        implicit_prefs=True, alpha=10.0,
                        history_mode="bucket")
    packed_b = pack_ratings_multihost(r2, params2, mesh)
    Ub, Vb = train_als(None, params2, mesh=mesh, packed=packed_b)
    Ub_full = np.asarray(rep(Ub).addressable_data(0))
    Vb_full = np.asarray(rep(Vb).addressable_data(0))

    # v4: FULL shard pushdown — each process holds ONLY its storage
    # shard (1/2 of the log's rows), agrees on indexation via the
    # count-allreduce, and re-assembles factor-row triples through the
    # chunked gloo shuffle (exchange_filtered). Factors must match the
    # v1 run (same problem, same indexation) tightly — the shuffle
    # restores global storage order, so packing is identical.
    from predictionio_tpu.models.data import ShardedColumnarRatingsSource

    my_shard = batch.shard(pid, 2, with_props=False)
    assert my_shard.n < nnz, (my_shard.n, nnz)
    src4 = ShardedColumnarRatingsSource(my_shard, chunk=113,
                                        exchange_chunk=151)
    assert src4.n_users == src.n_users and src4.n_items == src.n_items
    packed4 = pack_ratings_multihost(src4, params, mesh)
    U4, V4 = train_als(None, params, mesh=mesh, packed=packed4)
    U4_full = np.asarray(rep(U4).addressable_data(0))
    V4_full = np.asarray(rep(V4).addressable_data(0))
    np.testing.assert_allclose(U4_full, U3_full, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(V4_full, V3_full, rtol=1e-4, atol=1e-5)

    # v4 bucketed: the drop-free layout's arbitrary row masks through
    # the shuffle
    batch_b = columnar_from_columns(
        ColumnarDicts(),
        ["rate"] * nnz2, ["user"] * nnz2,
        [f"u{u:05d}" for u in r2.users],
        ["item"] * nnz2,
        [f"i{i:05d}" for i in r2.items],
        np.arange(nnz2, dtype=np.int64),
        [None] * nnz2, float_props=())
    batch_b.float_props["rating"] = r2.ratings.astype(np.float64)
    src4b = ShardedColumnarRatingsSource(batch_b.shard(pid, 2),
                                         exchange_chunk=173)
    packed4b = pack_ratings_multihost(src4b, params2, mesh)
    U4b, V4b = train_als(None, params2, mesh=mesh, packed=packed4b)
    U4b_full = np.asarray(rep(U4b).addressable_data(0))
    V4b_full = np.asarray(rep(V4b).addressable_data(0))
    # baseline with the SAME (code-order) indexation: the full batch's
    # COO fed the v1 way (plain source — no collective, every process
    # derives it locally)
    coo_b = ColumnarRatingsSource(batch_b).to_coo()
    packed5b = pack_ratings_multihost(coo_b, params2, mesh)
    U5b, V5b = train_als(None, params2, mesh=mesh, packed=packed5b)
    U5b_full = np.asarray(rep(U5b).addressable_data(0))
    V5b_full = np.asarray(rep(V5b).addressable_data(0))
    np.testing.assert_allclose(U4b_full, U5b_full, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(V4b_full, V5b_full, rtol=1e-4, atol=1e-5)

    if pid == 0:
        np.save(os.path.join(outdir, "U.npy"), U_full)
        np.save(os.path.join(outdir, "V.npy"), V_full)
        np.save(os.path.join(outdir, "Ub.npy"), Ub_full)
        np.save(os.path.join(outdir, "Vb.npy"), Vb_full)
        json.dump({"ok": True, "touched": touched["n"], "nnz": nnz},
                  open(os.path.join(outdir, "ok.json"), "w"))
""")


def test_two_process_training_matches_single_process(tmp_path):
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    portno = port.getsockname()[1]
    port.close()

    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), str(portno), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    assert (tmp_path / "ok.json").exists()

    # single-process reference on the same seeded problem (8 virtual
    # devices in THIS process, via the conftest mesh)
    rng = np.random.default_rng(7)
    nnz, n_users, n_items = 900, 64, 40
    ratings = RatingsCOO(rng.integers(0, n_users, nnz).astype(np.int32),
                         rng.integers(0, n_items, nnz).astype(np.int32),
                         rng.random(nnz).astype(np.float32) * 4 + 1,
                         n_users, n_items)
    params = ALSParams(rank=4, num_iterations=3, reg=0.05, seed=5)
    U1, V1 = train_als(ratings, params)

    U2 = np.load(tmp_path / "U.npy")
    V2 = np.load(tmp_path / "V.npy")
    np.testing.assert_allclose(U2[:n_users], np.asarray(U1)[:n_users],
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(V2[:n_items], np.asarray(V1)[:n_items],
                               rtol=2e-3, atol=2e-4)

    # drop-free bucketed multihost vs the single-process bucket run
    rng2 = np.random.default_rng(21)
    nnz2 = 1200
    r2 = RatingsCOO(rng2.integers(0, 48, nnz2).astype(np.int32),
                    ((rng2.zipf(1.2, nnz2) - 1) % 24).astype(np.int32),
                    np.ones(nnz2, np.float32), 48, 24)
    params2 = ALSParams(rank=4, num_iterations=2, seed=9,
                        implicit_prefs=True, alpha=10.0,
                        history_mode="bucket")
    U1b, V1b = train_als(r2, params2)
    Ub = np.load(tmp_path / "Ub.npy")
    Vb = np.load(tmp_path / "Vb.npy")
    np.testing.assert_allclose(Ub[:48], np.asarray(U1b)[:48],
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(Vb[:24], np.asarray(V1b)[:24],
                               rtol=2e-3, atol=2e-4)
