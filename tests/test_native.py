"""Native (C++) columnar codec: correctness against the Python path.

The codec is an ACCELERATOR — every test here must also pass with
``PTPU_NO_NATIVE=1`` (the suite covers both by construction: the
fallback-equivalence test runs the two paths against each other).
"""

import json

import numpy as np
import pytest

from predictionio_tpu.native import codec


@pytest.fixture(scope="module")
def mod():
    m = codec()
    if m is None:
        pytest.skip("native codec unavailable (no compiler)")
    return m


class TestCodecParse:
    def test_roundtrip_tricky_content(self, mod):
        recs = [
            {"op": "put", "event": {
                "event": "rate", "entityType": "user",
                "entityId": "uñ→\"x\\",
                "targetEntityType": "item",
                "targetEntityId": "i\U0001F600", "eventId": "e1",
                "properties": {"rating": 4.5, "note": "a\nb",
                               "nested": {"k": [1, {"r": 2}]},
                               "flag": True},
                "eventTime": "2026-07-30T12:00:00.123Z",
                "creationTime": "2026-07-30T12:00:00.123Z",
                "tags": ["a", "b"]}},
            {"op": "put", "event": {
                "event": "$set", "entityType": "item", "entityId": "i1",
                "eventId": "e2",
                "eventTime": "2026-07-30T12:00:01.000Z",
                "creationTime": "2026-07-30T12:00:01.000Z"}},
        ]
        data = ("".join(json.dumps(r) + "\n" for r in recs)).encode()
        ev, et, ei, tt, ti, times, ids, praw, fps = mod.parse_segment(
            data, ("rating",))
        assert ev == ["rate", "$set"]
        assert ei[0] == 'uñ→"x\\'
        assert ti[0] == "i\U0001F600" and tt[1] is None
        assert ids == ["e1", "e2"]
        assert json.loads(praw[0]) == recs[0]["event"]["properties"]
        assert praw[1] is None
        assert fps[0][0] == 4.5 and np.isnan(fps[0][1])

    def test_string_number_and_bool_props_stay_nan(self, mod):
        recs = [{"op": "put", "event": {
            "event": "rate", "entityType": "user", "entityId": "u",
            "targetEntityType": "item", "targetEntityId": "i",
            "eventId": f"e{k}", "properties": {"rating": v},
            "eventTime": "2026-01-01T00:00:00.000Z",
            "creationTime": "2026-01-01T00:00:00.000Z"}}
            for k, v in enumerate(["4.5", True, None, 3])]
        data = ("".join(json.dumps(r) + "\n" for r in recs)).encode()
        *_, fps = mod.parse_segment(data, ("rating",))
        r = fps[0]
        assert np.isnan(r[0]) and np.isnan(r[1]) and np.isnan(r[2])
        assert r[3] == 3.0

    def test_del_record_returns_none(self, mod):
        data = (json.dumps({"op": "del", "id": "x"}) + "\n").encode()
        assert mod.parse_segment(data, ()) is None

    def test_malformed_raises(self, mod):
        with pytest.raises(ValueError):
            mod.parse_segment(b'{"op": "put", "event": {oops\n', ())


class TestNativeVsPythonEncode:
    def test_segmentfs_encode_identical(self, tmp_path, monkeypatch,
                                        mod):
        """The sidecar built through the codec must be value-identical
        to the pure-Python build of the same log."""
        import predictionio_tpu.native as native
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )

        assert codec() is not None  # not vacuous: native side is real

        def build(td):
            es = SegmentFSEventStore(SegmentFSClient(str(td)))
            es.init(1)
            rng = np.random.default_rng(7)
            es.insert_batch(
                [Event(event="rate", entity_type="user",
                       entity_id=f"u{int(u)}",
                       target_entity_type="item",
                       target_entity_id=f"ié{int(i)}",
                       properties=DataMap({"rating": float(r),
                                           "extra": "x,\"y\""}))
                 for u, i, r in zip(rng.integers(0, 20, 400),
                                    rng.integers(0, 9, 400),
                                    rng.integers(1, 6, 400))], 1)
            return es.find_columnar(1, ordered=True)

        b1 = build(tmp_path / "native")
        native._state.clear()
        monkeypatch.setenv("PTPU_NO_NATIVE", "1")
        try:
            b2 = build(tmp_path / "python")
        finally:
            native._state.clear()
        assert b1.n == b2.n == 400
        np.testing.assert_array_equal(b1.float_prop("rating"),
                                      b2.float_prop("rating"))
        e1 = [(e.event, e.entity_id, e.target_entity_id,
               e.properties.to_dict()) for e in b1.to_events()]
        e2 = [(e.event, e.entity_id, e.target_entity_id,
               e.properties.to_dict()) for e in b2.to_events()]
        assert e1 == e2
