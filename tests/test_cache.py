"""Serving cache hierarchy tests (ISSUE 4): tier units, singleflight
dedup, invalidation-bus correctness (concurrent ingest + query stress —
no stale result past the staleness bound), flush on promote/rollback/
reload, the hot-entity tier, metrics exposition, and the operator
surface (/cache.json, /cache/flush, ``ptpu cache``)."""

import json
import threading
import time
import urllib.error
import urllib.request
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.cache import (
    InvalidationBus,
    ServingCache,
    ShardedTTLCache,
    SingleFlight,
    canonical_key,
)
from predictionio_tpu.controller import Context
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.base import (
    STATUS_COMPLETED,
    EngineInstance,
    Model,
)
from predictionio_tpu.server.engineserver import (
    QueryServer,
    ServerConfig,
    create_engine_server,
)
from predictionio_tpu.templates.recommendation import (
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import persistence

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return resp.status, (json.loads(raw) if "json" in ctype
                                 else raw.decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


# ---------------------------------------------------------------------------
# unit: sharded LRU + TTL + tags
# ---------------------------------------------------------------------------

class TestShardedTTLCache:
    def test_hit_miss_ttl(self):
        t = [0.0]
        c = ShardedTTLCache(max_entries=16, ttl_sec=10.0,
                            clock=lambda: t[0])
        assert c.lookup("k") == (False, None)
        c.put("k", {"v": 1})
        assert c.lookup("k") == (True, {"v": 1})
        t[0] = 10.1  # past the TTL: the staleness BOUND holds
        assert c.lookup("k") == (False, None)
        s = c.stats()
        assert s["hits"] == 1 and s["misses"] == 2
        assert s["expirations"] == 1 and s["entries"] == 0

    def test_lru_eviction_bounded(self):
        c = ShardedTTLCache(max_entries=8, ttl_sec=100.0, shards=2)
        for i in range(100):
            c.put(("ns", i), i)
        assert len(c) <= 8
        assert c.stats()["evictions"] >= 92
        # most-recent entries survive within their shard
        assert any(c.lookup(("ns", i))[0] for i in range(96, 100))

    def test_tag_invalidation_is_surgical(self):
        c = ShardedTTLCache(max_entries=64, ttl_sec=100.0)
        c.put(("ns", "a"), 1, tags=("user:u1",))
        c.put(("ns", "b"), 2, tags=("user:u1", "user:u2"))
        c.put(("ns", "c"), 3, tags=("user:u3",))
        assert c.invalidate_tag("user:u1") == 2
        assert c.lookup(("ns", "a"))[0] is False
        assert c.lookup(("ns", "b"))[0] is False
        assert c.lookup(("ns", "c")) == (True, 3)
        assert c.stats()["invalidations"] == 2
        # re-putting after invalidation works and tag index is clean
        c.put(("ns", "a"), 9, tags=("user:u1",))
        assert c.invalidate_tag("user:u1") == 1

    def test_namespace_flush(self):
        c = ShardedTTLCache(max_entries=64, ttl_sec=100.0)
        c.put(("armA", "q1"), 1)
        c.put(("armA", "q2"), 2)
        c.put(("armB", "q1"), 3)
        assert c.flush("armA") == 2
        assert c.lookup(("armB", "q1")) == (True, 3)
        assert c.flush() == 1  # full flush takes the rest
        assert len(c) == 0

    def test_bytes_accounting(self):
        c = ShardedTTLCache(max_entries=8, ttl_sec=100.0)
        c.put("k", {"itemScores": [{"item": "i1", "score": 0.5}]})
        assert c.bytes > 0
        c.flush()
        assert c.bytes == 0

    def test_canonical_key_order_insensitive(self):
        assert canonical_key({"user": "u1", "num": 3}) \
            == canonical_key({"num": 3, "user": "u1"})
        assert canonical_key({"user": "u1", "num": 3}) \
            != canonical_key({"user": "u1", "num": 4})


class TestSingleFlight:
    def test_concurrent_misses_compute_once(self):
        sf = SingleFlight()
        calls = []
        gate = threading.Event()

        def compute():
            calls.append(1)
            gate.wait(5)
            return "value"

        results = []

        def run():
            results.append(sf.do("k", compute))

        threads = [threading.Thread(target=run) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.2)  # let followers pile onto the flight
        gate.set()
        for t in threads:
            t.join()
        assert len(calls) == 1
        assert all(v == "value" for v, _ in results)
        assert sum(1 for _, leader in results if leader) == 1
        assert sf.coalesced == 7
        # the flight is gone: a later miss recomputes
        sf.do("k", lambda: calls.append(1) or "again")
        assert len(calls) == 2

    def test_exception_reaches_all_waiters_then_clears(self):
        sf = SingleFlight()
        with pytest.raises(RuntimeError):
            sf.do("k", lambda: (_ for _ in ()).throw(RuntimeError("x")))
        assert sf.do("k", lambda: 7) == (7, True)


class TestInvalidationBus:
    def test_publish_reaches_subscriber_and_weakref_cleans_up(self):
        bus = InvalidationBus()

        class Sub:
            def __init__(self):
                self.seen = []

            def on_event(self, app_id, etype, eid, name):
                self.seen.append((app_id, etype, eid, name))

        sub = Sub()
        bus.subscribe(sub)
        assert bus.publish(1, "user", "u1", "view") == 1
        assert sub.seen == [(1, "user", "u1", "view")]
        del sub
        import gc
        gc.collect()
        assert bus.publish(1, "user", "u2", "view") == 0
        assert bus.subscriber_count() == 0

    def test_failing_subscriber_never_breaks_publish(self):
        bus = InvalidationBus()

        class Bad:
            def on_event(self, *a):
                raise RuntimeError("boom")

        class Good:
            def __init__(self):
                self.n = 0

            def on_event(self, *a):
                self.n += 1

        bad, good = Bad(), Good()
        bus.subscribe(bad)
        bus.subscribe(good)
        bus.publish(1, "user", "u1", "view")
        assert good.n == 1


class TestServingCacheUnit:
    def test_on_event_invalidates_tagged_and_constraint_flushes(self):
        bus = InvalidationBus()
        sc = ServingCache(bus=bus)
        sc.query.put(("ns", "q-u1"), 1, tags=("user:u1",))
        sc.query.put(("ns", "q-u2"), 2, tags=("user:u2",))
        sc.features.put(("seen", "u1"), {"i1"}, tags=("user:u1",))
        bus.publish(0, "user", "u1", "view")
        assert sc.query.lookup(("ns", "q-u1"))[0] is False
        assert sc.query.lookup(("ns", "q-u2"))[0] is True
        assert sc.features.lookup(("seen", "u1"))[0] is False
        # a constraint $set reshapes every result: whole query tier dies
        bus.publish(0, "constraint", "unavailableItems", "$set")
        assert sc.query.lookup(("ns", "q-u2"))[0] is False

    def test_metrics_registered(self):
        from predictionio_tpu.obs import MetricsRegistry

        sc = ServingCache(bus=InvalidationBus())
        reg = MetricsRegistry()
        sc.register_metrics(reg)
        sc.query.put(("ns", "a"), 1)
        sc.query.lookup(("ns", "a"))
        text = reg.render()
        for name in ("pio_cache_hits", "pio_cache_misses",
                     "pio_cache_evictions", "pio_cache_invalidations",
                     "pio_cache_entries", "pio_cache_bytes",
                     "pio_cache_hit_ratio"):
            assert name in text, name
        assert 'tier="query"' in text and 'tier="feature"' in text
        snap = reg.snapshot()
        assert snap["pio_cache_hits"]['tier=query'] == 1.0


# ---------------------------------------------------------------------------
# integration: the engine server's cached serving path
# ---------------------------------------------------------------------------

def _synth_als_model(seed: int, n_users: int = 24, n_items: int = 24,
                     rank: int = 4):
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.als import ALSModel, ALSParams

    rng = np.random.default_rng(seed)
    return ALSModel(
        user_factors=rng.standard_normal(
            (n_users, rank)).astype(np.float32),
        item_factors=rng.standard_normal(
            (n_items, rank)).astype(np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))


@pytest.fixture()
def two_releases():
    """Two COMPLETED instances with persisted blobs (the
    promote/rollback/reload substrate), plus a per-test bus."""
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "cacheapp"))
    ctx = Context(app_name="cacheapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("cacheapp", rank=4)
    for i, (iid, seed) in enumerate((("ca1", 1), ("ca2", 2))):
        start = T0 + timedelta(minutes=i)
        storage.engine_instances().insert(EngineInstance(
            id=iid, status=STATUS_COMPLETED, start_time=start,
            end_time=start, engine_id="cache", engine_version="1",
            engine_variant="engine.json", engine_factory="synthetic"))
        storage.models().insert(Model(
            id=iid,
            models=persistence.dumps_models([_synth_als_model(seed)])))
    return ctx, engine, ep


def _cache_server(two_releases, iid="ca1", bus=None, **cfg_kw):
    from predictionio_tpu.workflow.core import load_models_for_deploy

    ctx, engine, ep = two_releases
    inst = ctx.storage.engine_instances().get(iid)
    models = load_models_for_deploy(ctx, engine, inst, ep)
    cfg = ServerConfig(warm_start=False, serving_cache=True, **cfg_kw)
    qs = QueryServer(ctx, engine, ep, models, inst, cfg)
    if bus is not None:
        # rewire onto the per-test bus (the default is process-global)
        qs.cache.bus = bus
        bus.subscribe(qs.cache)
    return qs


class TestCachedServing:
    def test_hit_skips_pipeline_and_matches(self, two_releases):
        qs = _cache_server(two_releases)
        r1 = qs.serve({"user": "u1", "num": 3})
        count_after_miss = qs.request_count
        r2 = qs.serve({"user": "u1", "num": 3})
        assert r1 == r2
        st = qs.cache.stats()["tiers"]["query"]
        assert st["hits"] == 1 and st["misses"] == 1
        # the hit still counts as a served request (bookkeeping parity)
        assert qs.request_count == count_after_miss + 1
        # key-order-insensitive exact match
        qs.serve({"num": 3, "user": "u1"})
        assert qs.cache.stats()["tiers"]["query"]["hits"] == 2

    def test_errors_are_never_cached(self, two_releases):
        from predictionio_tpu.server.engineserver import HTTPError

        qs = _cache_server(two_releases)
        for _ in range(2):
            with pytest.raises(HTTPError):
                qs.serve({"bogus": 1})
        assert len(qs.cache.query) == 0

    def test_singleflight_dedups_concurrent_identical_misses(
            self, two_releases):
        qs = _cache_server(two_releases)
        algo = qs.algorithms[0]
        calls = []
        orig = algo.predict

        def slow_predict(model, query):
            calls.append(1)
            time.sleep(0.3)
            return orig(model, query)

        algo.predict = slow_predict
        results = []

        def run():
            results.append(qs.serve({"user": "u4", "num": 3}))

        threads = [threading.Thread(target=run) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(calls) == 1, "identical misses must compute once"
        assert all(r == results[0] for r in results)
        assert qs.cache.flight.coalesced >= 5

    def test_bus_invalidation_no_stale_serve(self, two_releases):
        bus = InvalidationBus()
        qs = _cache_server(two_releases, bus=bus)
        versions = {}
        algo = qs.algorithms[0]
        orig = algo.predict

        def versioned_predict(model, query):
            r = orig(model, query)
            versions.setdefault(query.user, 0)
            return type(r)(r.item_scores[:versions[query.user] + 1])

        algo.predict = versioned_predict
        r = qs.serve({"user": "u1", "num": 3})
        assert len(r["itemScores"]) == 1
        versions["u1"] = 1  # the world changed...
        assert qs.serve({"user": "u1", "num": 3}) == r, \
            "sanity: without an event the cached result serves"
        bus.publish(0, "user", "u1", "view")  # ...and the event landed
        r2 = qs.serve({"user": "u1", "num": 3})
        assert len(r2["itemScores"]) == 2, \
            "post-ingest query served the pre-ingest cached result"

    def test_concurrent_ingest_query_stress_staleness_bound(
            self, two_releases):
        """The acceptance stress: writers bump an entity's version and
        publish; a version older than the publish floor must NEVER be
        served FROM THE CACHE (bus delivery is synchronous and fills
        racing an invalidation are epoch-dropped). A reader may
        transiently share an in-flight compute that began pre-publish
        — that result is not cached, so serving must converge to the
        floor as soon as that flight drains."""
        bus = InvalidationBus()
        qs = _cache_server(two_releases, bus=bus)
        committed = {f"u{i}": 0 for i in range(8)}
        published = {f"u{i}": 0 for i in range(8)}
        lock = threading.Lock()
        algo = qs.algorithms[0]
        orig = algo.predict

        def versioned_predict(model, query):
            r = orig(model, query)
            with lock:
                v = committed[query.user]
            d = r.to_json()
            d["version"] = v
            return d

        algo.predict = versioned_predict
        stop = threading.Event()
        violations = []

        def writer(user):
            while not stop.is_set():
                with lock:
                    committed[user] += 1
                bus.publish(0, "user", user, "view")
                with lock:
                    published[user] = committed[user]
                time.sleep(0.002)

        def reader(user):
            while not stop.is_set():
                with lock:
                    floor = published[user]
                obs = {}
                out = qs.serve({"user": user, "num": 2}, obs=obs)
                if out["version"] >= floor:
                    continue
                if obs.get("cache") == "hit":
                    violations.append(
                        ("stale-from-cache", user, out["version"],
                         floor))
                    continue
                # shared in-flight compute: must converge once the
                # pre-publish flight drains (its fill was dropped)
                for _ in range(50):
                    out = qs.serve({"user": user, "num": 2})
                    if out["version"] >= floor:
                        break
                    time.sleep(0.005)
                else:
                    violations.append(
                        ("never-converged", user, out["version"],
                         floor))

        writers = [threading.Thread(target=writer, args=(f"u{i}",))
                   for i in range(4)]
        readers = [threading.Thread(target=reader, args=(f"u{i}",))
                   for i in range(4)]
        for t in writers + readers:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in writers + readers:
            t.join()
        assert not violations, violations[:5]
        st = qs.cache.stats()["tiers"]["query"]
        assert st["invalidations"] > 0, "stress never invalidated"
        assert st["hits"] + st["misses"] > 100


class TestFlushOnRebind:
    def test_reload_flushes_all_tiers(self, two_releases):
        qs = _cache_server(two_releases)
        qs.serve({"user": "u1", "num": 3})
        assert len(qs.cache.query) == 1
        qs.cache.features.put(("seen", "u1"), {"i1"})
        qs.reload()
        assert len(qs.cache.query) == 0
        assert len(qs.cache.features) == 0

    def test_promote_flushes_and_namespaces_differ(self, two_releases):
        ctx, engine, ep = two_releases
        qs = _cache_server(two_releases, iid="ca1")
        stable_r = qs.serve({"user": "u1", "num": 3})
        qs.bind_candidate(ctx.storage.engine_instances().get("ca2"))
        cand_r = qs.serve_candidate({"user": "u1", "num": 3})
        # per-arm namespaces: same query cached once per arm
        keys = {k for shard in qs.cache.query._shards
                for k in shard.entries}
        namespaces = {k[0] for k in keys}
        assert namespaces == {"ca1", "ca2"}
        assert stable_r != cand_r  # different models, different answers
        # candidate hit comes from the candidate namespace
        assert qs.serve_candidate({"user": "u1", "num": 3}) == cand_r
        qs.promote_candidate()
        assert len(qs.cache.query) == 0, \
            "promote must flush — the new stable must recompute"
        post = qs.serve({"user": "u1", "num": 3})
        assert post == cand_r  # ca2 now serves stable, fresh compute
        keys = {k[0] for shard in qs.cache.query._shards
                for k in shard.entries}
        assert keys == {"ca2"}

    def test_rollback_flushes_candidate_namespace_only(
            self, two_releases):
        ctx, engine, ep = two_releases
        qs = _cache_server(two_releases, iid="ca1")
        qs.serve({"user": "u1", "num": 3})
        qs.bind_candidate(ctx.storage.engine_instances().get("ca2"))
        qs.serve_candidate({"user": "u1", "num": 3})
        qs.drop_candidate()  # the rollback path
        keys = {k[0] for shard in qs.cache.query._shards
                for k in shard.entries}
        assert keys == {"ca1"}, \
            "rollback must flush the dead arm and keep stable's"


class TestHotEntityTier:
    def test_pin_refresh_lookup_and_flush(self, two_releases,
                                          monkeypatch):
        from predictionio_tpu.models import als as als_mod

        monkeypatch.setattr(als_mod, "HOST_SERVE_WORK", 16)
        qs = _cache_server(two_releases, hot_entities=4,
                           hot_refresh_every=4)
        for _ in range(6):
            qs.serve({"user": "u2", "num": 3})
        qs.cache.hot.refresh(wait=True)
        st = qs.cache.hot.stats()
        assert st["entries"] >= 1 and st["refreshes"] >= 1
        handle = qs.cache.hot.lookup("u2")
        assert handle is not None
        # pinned fast path answers EXACTLY like the normal path
        from predictionio_tpu.utils.jsonutil import from_jsonable

        algo = qs.algorithms[0]
        q = from_jsonable(algo.query_class, {"user": "u2", "num": 3})
        assert algo.predict_pinned(qs.models[0], q, handle) \
            == algo.predict(qs.models[0], q)
        # serve() consults the pin once the query cache is cold
        qs.cache.query.flush()
        before = qs.cache.hot.stats()["hits"]
        r = qs.serve({"user": "u2", "num": 3})
        assert r["itemScores"]
        assert qs.cache.hot.stats()["hits"] > before
        # rebind flushes pins AND hit stats
        qs.reload()
        assert qs.cache.hot.stats()["entries"] == 0
        assert qs.cache.hot.lookup("u2") is None

    def test_host_served_models_skip_pinning(self, two_releases):
        qs = _cache_server(two_releases, hot_entities=4,
                           hot_refresh_every=2)
        for _ in range(4):
            qs.serve({"user": "u3", "num": 2})
        qs.cache.hot.refresh(wait=True)
        # tiny host-served model: nothing to pin, nothing breaks
        assert qs.cache.hot.stats()["entries"] == 0
        assert qs.serve({"user": "u3", "num": 2})["itemScores"]


# ---------------------------------------------------------------------------
# E2E over HTTP: ingest through the REAL event server invalidates the
# REAL engine server's cache; /cache.json + /cache/flush; ptpu cache
# ---------------------------------------------------------------------------

class TestHTTPEndToEnd:
    def test_ingest_invalidates_and_routes_work(self, two_releases):
        from predictionio_tpu.data.storage.base import AccessKey
        from predictionio_tpu.server.eventserver import (
            build_app as build_event_app,
        )
        from predictionio_tpu.server.http import AppServer

        ctx, engine, ep = two_releases
        bus = InvalidationBus()
        qs = _cache_server(two_releases, bus=bus)
        srv = create_engine_server(qs, "127.0.0.1", 0).start_background()
        ctx.storage.access_keys().insert(
            AccessKey(key="CK", app_id=0, events=()))
        ev_srv = AppServer(build_event_app(ctx.storage, bus=bus),
                           "127.0.0.1", 0).start_background()
        try:
            status, r1 = call(srv.port, "POST", "/queries.json",
                              {"user": "u1", "num": 3})
            assert status == 200
            call(srv.port, "POST", "/queries.json",
                 {"user": "u1", "num": 3})
            status, cj = call(srv.port, "GET", "/cache.json")
            assert status == 200 and cj["enabled"]
            assert cj["tiers"]["query"]["hits"] >= 1

            # ingest an event for u1 through the REAL event server
            status, _ = call(
                ev_srv.port, "POST", "/events.json?accessKey=CK",
                {"event": "view", "entityType": "user",
                 "entityId": "u1", "targetEntityType": "item",
                 "targetEntityId": "i5"})
            assert status == 201
            status, cj = call(srv.port, "GET", "/cache.json")
            assert cj["tiers"]["query"]["invalidations"] >= 1, \
                "ingest did not invalidate the engine server's cache"

            # operator flush
            call(srv.port, "POST", "/queries.json",
                 {"user": "u2", "num": 3})
            status, fl = call(srv.port, "POST", "/cache/flush")
            assert status == 200 and "query" in fl["removed"]
            status, cj = call(srv.port, "GET", "/cache.json")
            assert cj["tiers"]["query"]["entries"] == 0

            # /status.json and /metrics carry the cache series
            status, sj = call(srv.port, "GET", "/status.json")
            assert sj["cache"]["enabled"]
            status, text = call(srv.port, "GET", "/metrics")
            assert "pio_cache_hits" in text
        finally:
            srv.shutdown()
            ev_srv.shutdown()

    def test_cache_json_when_disabled(self, two_releases):
        from predictionio_tpu.workflow.core import (
            load_models_for_deploy,
        )

        ctx, engine, ep = two_releases
        inst = ctx.storage.engine_instances().get("ca1")
        models = load_models_for_deploy(ctx, engine, inst, ep)
        qs = QueryServer(ctx, engine, ep, models, inst,
                         ServerConfig(warm_start=False))
        srv = create_engine_server(qs, "127.0.0.1",
                                   0).start_background()
        try:
            status, body = call(srv.port, "GET", "/cache.json")
            assert status == 200 and body["enabled"] is False
            status, _ = call(srv.port, "POST", "/cache/flush")
            assert status == 409
        finally:
            srv.shutdown()

    def test_ptpu_cache_cli(self, two_releases, capsys):
        from predictionio_tpu.cli import main as cli_main

        qs = _cache_server(two_releases)
        srv = create_engine_server(qs, "127.0.0.1",
                                   0).start_background()
        try:
            qs.serve({"user": "u1", "num": 3})
            qs.serve({"user": "u1", "num": 3})
            rc = cli_main(["cache", "stats", "--ip", "127.0.0.1",
                           "--port", str(srv.port)],
                          storage=qs.ctx.storage)
            assert rc == 0
            out = capsys.readouterr().out
            assert "hit ratio" in out and "query" in out
            rc = cli_main(["cache", "flush", "--ip", "127.0.0.1",
                           "--port", str(srv.port)],
                          storage=qs.ctx.storage)
            assert rc == 0
            assert "Flushed" in capsys.readouterr().out
            assert len(qs.cache.query) == 0
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# satellites: ecommerce feature cache + weights memo, batched supplement
# ---------------------------------------------------------------------------

class TestEcommerceFeatureCache:
    def _algo_with_counting_store(self):
        from predictionio_tpu.templates.ecommerce import (
            ECommAlgorithm,
            ECommAlgorithmParams,
        )

        calls = []

        class CountingStore:
            def find_by_entity(self, app_name, etype, eid, **kw):
                calls.append((etype, eid))
                return []

        algo = ECommAlgorithm(ECommAlgorithmParams(
            app_name="shop", unseen_only=True))
        algo._serving_store = CountingStore()
        return algo, calls

    def test_reads_cached_and_invalidated(self):
        from predictionio_tpu.templates.ecommerce import Query

        algo, calls = self._algo_with_counting_store()
        cache = ShardedTTLCache(max_entries=64, ttl_sec=100.0)
        algo.bind_feature_cache(cache)
        q = Query(user="u1", num=3)
        algo.gen_black_list(q, "shop")
        n_first = len(calls)
        assert n_first == 2  # seen + unavailable
        algo.gen_black_list(q, "shop")
        assert len(calls) == n_first, "second query must hit the cache"
        # an event for u1 invalidates the seen read only
        cache.invalidate_tag("user:u1")
        algo.gen_black_list(q, "shop")
        assert len(calls) == n_first + 1  # seen re-read, constraint hit
        # constraint invalidation forces the unavailable re-read
        cache.invalidate_tag("constraint:unavailableItems")
        algo.gen_black_list(q, "shop")
        assert len(calls) == n_first + 2

    def test_recent_and_weighted_cached(self):
        algo, calls = self._algo_with_counting_store()
        cache = ShardedTTLCache(max_entries=64, ttl_sec=100.0)
        algo.bind_feature_cache(cache)
        from predictionio_tpu.templates.ecommerce import Query

        q = Query(user="u2", num=3)
        algo.get_recent_items(q, "shop")
        algo.get_recent_items(q, "shop")
        algo.weighted_items("shop")
        algo.weighted_items("shop")
        assert len(calls) == 2  # one recent read + one weighted read

    def test_works_without_cache(self):
        from predictionio_tpu.templates.ecommerce import Query

        algo, calls = self._algo_with_counting_store()
        q = Query(user="u1", num=3)
        algo.gen_black_list(q, "shop")
        algo.gen_black_list(q, "shop")
        assert len(calls) == 4  # uncached: every query re-reads


class TestWeightsVectorMemo:
    def test_computed_once_per_generation(self):
        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.templates.ecommerce import (
            ECommAlgorithm,
            ECommAlgorithmParams,
            ECommModel,
        )

        algo = ECommAlgorithm(ECommAlgorithmParams(app_name="shop"))
        groups = [[({"i1", "i2"}, 2.0)]]

        algo.weighted_items = lambda app_name: groups[0]

        def model(n=6):
            ids = BiMap({f"i{i}": i for i in range(n)})
            return ECommModel(
                app_name="shop", rank=2,
                user_factors=np.zeros((2, 2), np.float32),
                has_user=np.ones(2, bool),
                item_factors=np.zeros((n, 2), np.float32),
                has_item=np.ones(n, bool),
                popular_count=np.zeros(n, np.int64),
                user_ids=BiMap({"u0": 0, "u1": 1}),
                item_ids=ids, items={})

        m = model()
        w1 = algo._weights_vector(m, "shop")
        assert w1[1] == 2.0 and w1[0] == 1.0
        # same (model, app, weights) generation: the SAME vector object
        assert algo._weights_vector(m, "shop") is w1
        # the weights constraint changed → recompute
        groups[0] = [({"i3"}, 0.5)]
        w2 = algo._weights_vector(m, "shop")
        assert w2 is not w1 and w2[3] == 0.5 and w2[1] == 1.0
        # a NEW model (new item index space) → recompute
        m2 = model()
        assert algo._weights_vector(m2, "shop") is not w2


class TestParallelSupplement:
    def test_order_and_error_slots_preserved(self):
        from predictionio_tpu.workflow.batch_predict import (
            predict_serve_batch,
        )

        class Query:
            def __init__(self, user):
                self.user = user

        class Serving:
            def supplement(self, q):
                if q.user == "bad":
                    raise ValueError("poison supplement")
                time.sleep(0.01)
                return q

            def serve(self, q, preds):
                return preds[0]

        class Algo:
            def batch_predict(self, model, queries):
                return [f"pred-{q.user}" for q in queries]

        queries = [Query(f"u{i}") for i in range(16)]
        queries[5] = Query("bad")
        out = predict_serve_batch([Algo()], [None], Serving(), queries)
        assert isinstance(out[5], ValueError)
        for i, r in enumerate(out):
            if i != 5:
                assert r == f"pred-u{i}", (i, r)

    def test_single_query_stays_pool_free(self):
        from predictionio_tpu.workflow.batch_predict import (
            predict_serve_batch,
        )

        main_thread = threading.current_thread().name
        seen = []

        class Serving:
            def supplement(self, q):
                seen.append(threading.current_thread().name)
                return q

            def serve(self, q, preds):
                return preds[0]

        class Algo:
            def batch_predict(self, model, queries):
                return list(queries)

        predict_serve_batch([Algo()], [None], Serving(), ["q"])
        assert seen == [main_thread]
