"""Collectives + multihost helpers on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel import (
    all_gather,
    all_reduce_sum,
    host_shard,
    make_mesh,
    reduce_scatter,
    ring_permute,
    sharded,
    sharded_top_k,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    return make_mesh(data=1, model=8)


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        x = np.arange(8, dtype=np.float32)

        @sharded(mesh8, in_specs=P("model"), out_specs=P())
        def total(shard):
            return all_reduce_sum(shard.sum())

        assert float(total(x)) == x.sum()

    def test_all_gather_identity(self, mesh8):
        x = np.arange(16, dtype=np.float32)

        @sharded(mesh8, in_specs=P("model"), out_specs=P("model"))
        def gather_then_slice(shard):
            full = all_gather(shard)
            # every shard sees the full vector; return own slice to check
            i = jax.lax.axis_index("model")
            return jax.lax.dynamic_slice(full, (i * 2,), (2,))

        np.testing.assert_array_equal(np.asarray(gather_then_slice(x)), x)

    def test_reduce_scatter_matches_psum(self, mesh8):
        x = np.ones((16,), dtype=np.float32)

        @sharded(mesh8, in_specs=P("model"), out_specs=P("model"))
        def rs(shard):
            return reduce_scatter(jnp.tile(shard, 8))

        # each shard contributes its 2 elems tiled 8x; reduce_scatter sums
        # over shards then scatters — every output element is 8.0
        np.testing.assert_array_equal(np.asarray(rs(x)),
                                      np.full(16, 8.0, np.float32))

    def test_ring_permute(self, mesh8):
        x = np.arange(8, dtype=np.float32)

        @sharded(mesh8, in_specs=P("model"), out_specs=P("model"))
        def shift(shard):
            return ring_permute(shard, shift=1)

        out = np.asarray(shift(x))
        np.testing.assert_array_equal(out, np.roll(x, 1))

    def test_sharded_top_k(self, mesh8):
        rng = np.random.default_rng(0)
        scores = rng.normal(size=64).astype(np.float32)
        s = jax.device_put(scores, NamedSharding(mesh8, P("model")))
        idx, vals = sharded_top_k(s, k=5, mesh=mesh8)
        want = np.argsort(-scores)[:5]
        np.testing.assert_array_equal(np.sort(np.asarray(idx)),
                                      np.sort(want))
        np.testing.assert_allclose(np.asarray(vals), scores[want],
                                   rtol=1e-6)


class TestMultihost:
    def test_host_shard_single_process(self):
        x = np.arange(10)
        # single process: the shard is the whole array
        np.testing.assert_array_equal(host_shard(x), x)


class TestShardedServing:
    """recommend_batch_sharded vs the single-device serving dispatch
    (the multi-chip serving moment, ``CreateServer.scala:508-510``)."""

    def test_matches_single_device(self):
        import numpy as np

        from predictionio_tpu.models.als import (
            _serve_topk,
            recommend_batch_sharded,
        )

        mesh = make_mesh(data=4, model=2)
        rng = np.random.default_rng(0)
        n_items, n_pad, r = 101, 104, 16
        V = rng.standard_normal((n_pad, r)).astype(np.float32)
        U = rng.standard_normal((40, r)).astype(np.float32)
        idx = rng.integers(0, 40, 7)
        ids, scores = recommend_batch_sharded(U, V, idx, 10, mesh,
                                              n_items)
        s1, i1 = _serve_topk(jnp.asarray(U), jnp.asarray(V),
                             jnp.asarray(idx), k=10, n_items=n_items)
        np.testing.assert_array_equal(ids, np.asarray(i1))
        np.testing.assert_allclose(scores, np.asarray(s1), rtol=1e-5)

    def test_k_exceeding_local_shard(self):
        import numpy as np

        from predictionio_tpu.models.als import (
            _serve_topk,
            recommend_batch_sharded,
        )

        mesh = make_mesh(data=8, model=1)
        rng = np.random.default_rng(1)
        n_pad, r = 16, 8  # 2 items per shard, k=6 > local 2
        V = rng.standard_normal((n_pad, r)).astype(np.float32)
        U = rng.standard_normal((5, r)).astype(np.float32)
        idx = np.arange(5)
        ids, scores = recommend_batch_sharded(U, V, idx, 6, mesh, 13)
        s1, i1 = _serve_topk(jnp.asarray(U), jnp.asarray(V),
                             jnp.asarray(idx), k=6, n_items=13)
        np.testing.assert_array_equal(ids, np.asarray(i1))
