"""Deprecated batch views (C22), SSL (C26), pypio bridge (C27), and the
`run` CLI command."""

import json
import warnings
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)

MEM_ENV = {
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
}


@pytest.fixture()
def seeded():
    storage = Storage(env=MEM_ENV)
    app_id = storage.apps().insert(App(0, "viewapp"))
    storage.events().init(app_id)
    storage.events().insert_batch([
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"a": 1, "b": 2}), event_time=T0),
        Event(event="$unset", entity_type="user", entity_id="u1",
              properties=DataMap({"b": None}),
              event_time=T0 + timedelta(hours=1)),
        Event(event="$set", entity_type="user", entity_id="u2",
              properties=DataMap({"a": 5}), event_time=T0),
        Event(event="$delete", entity_type="user", entity_id="u2",
              event_time=T0 + timedelta(hours=2)),
        Event(event="view", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              event_time=T0 + timedelta(hours=3)),
    ], app_id)
    return Context(app_name="viewapp", _storage=storage)


class TestBatchViews:
    def test_batch_view_aggregate(self, seeded):
        from predictionio_tpu.data.view import BatchView

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            view = BatchView(seeded, "viewapp")
        props = view.aggregate_properties("user")
        assert set(props) == {"u1"}  # u2 deleted
        assert props["u1"].to_dict() == {"a": 1}

    def test_event_seq_filter_and_fold(self, seeded):
        from predictionio_tpu.data.view import EventSeq

        events = EventSeq(seeded.event_store.find("viewapp"))
        assert len(events.filter(event="view")) == 1
        assert len(events.filter(entity_type="user")) == 5
        assert len(events.filter(
            start_time=T0 + timedelta(hours=1))) == 3
        counts = events.aggregate_by_entity_ordered(
            0, lambda acc, e: acc + 1)
        assert counts == {"u1": 3, "u2": 2}

    def test_deprecation_warning(self, seeded):
        from predictionio_tpu.data.view import BatchView

        with pytest.warns(DeprecationWarning):
            BatchView(seeded, "viewapp")


class TestSSL:
    def test_https_server(self, tmp_path):
        import ssl
        import subprocess
        import urllib.request

        cert = tmp_path / "cert.pem"
        key = tmp_path / "key.pem"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-nodes", "-subj", "/CN=localhost"],
            check=True, capture_output=True)

        from predictionio_tpu.server.adminserver import build_app
        from predictionio_tpu.server.http import (
            AppServer,
            ssl_context_from,
        )

        ctx = ssl_context_from(str(cert), str(key))
        assert ctx is not None
        srv = AppServer(build_app(Storage(env=MEM_ENV)),
                        host="127.0.0.1", port=0, ssl_context=ctx)
        srv.start_background()
        try:
            client_ctx = ssl.create_default_context()
            client_ctx.check_hostname = False
            client_ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                    f"https://127.0.0.1:{srv.port}/",
                    context=client_ctx, timeout=5) as resp:
                assert json.loads(resp.read())["status"] == "alive"
        finally:
            srv.shutdown()

    def test_unconfigured_returns_none(self, monkeypatch):
        from predictionio_tpu.server.http import ssl_context_from

        monkeypatch.delenv("PIO_SSL_CERT", raising=False)
        assert ssl_context_from() is None


class TestPypio:
    def test_find_and_columns(self, seeded):
        from predictionio_tpu.data.store import EventStoreFacade
        from predictionio_tpu.pypio import PEventStore, events_to_columns

        store = PEventStore(EventStoreFacade(seeded.storage))
        rows = store.find("viewapp", event_names=["view"])
        assert len(rows) == 1
        props = store.aggregate_properties("viewapp", "user")
        assert set(props) == {"u1"}
        cols = events_to_columns(rows)
        assert cols["entityId"].tolist() == ["u1"]
        assert cols["eventTime"].dtype == np.int64


def _run_target(storage_marker):
    return f"ran:{storage_marker}"


class TestRunCommand:
    def test_run_invokes_callable(self, capsys):
        from predictionio_tpu.cli import main

        storage = Storage(env=MEM_ENV)
        rc = main(["run", "tests.test_compat_layers:_run_target", "xyz"],
                  storage=storage)
        assert rc == 0
        assert "ran:xyz" in capsys.readouterr().out
