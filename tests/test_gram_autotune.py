"""Shape-keyed persistent gram-mode selection (VERDICT r3 task 2)."""

import json

import numpy as np
import pytest

from predictionio_tpu.ops import gram_autotune as ga

_REAL_DEFAULTS = ga._DEFAULTS_PATH


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE",
                       str(tmp_path / "tune.json"))
    # isolate from the PACKAGED defaults too — these tests check the
    # resolution machinery, not the shipped measurements
    monkeypatch.setattr(ga, "_DEFAULTS_PATH",
                        str(tmp_path / "no_defaults.json"))
    ga.reset_for_tests()
    yield
    ga.reset_for_tests()


def test_packaged_defaults_ship_measured_r64_winner(monkeypatch,
                                                    tmp_path):
    """The committed defaults carry the on-chip r64 measurement."""
    monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE",
                       str(tmp_path / "empty.json"))
    monkeypatch.setattr(ga, "_DEFAULTS_PATH", _REAL_DEFAULTS)
    ga.reset_for_tests()
    assert ga.best_mode(64, device_kind="TPU v5 lite0") == "einsum"
    ga.reset_for_tests()


def test_heuristic_fallback_tpu_vs_cpu():
    # untuned TPU: pair below rank 128 (two systems per MXU tile)
    assert ga.best_mode(64, device_kind="TPU v5 lite0") == "pair"
    assert ga.best_mode(32, device_kind="TPU v4") == "pair"
    assert ga.best_mode(128, device_kind="TPU v5 lite0") == "einsum"
    # CPU gains nothing from pair's 2x multiplies
    assert ga.best_mode(64, device_kind="cpu") == "einsum"


def test_recorded_winner_overrides_heuristic(tmp_path):
    ga.record(64, "einsum", device_kind="TPU v5 lite0",
              measured={"source": "test"})
    assert ga.best_mode(64, device_kind="TPU v5 lite0") == "einsum"
    # rank bucketing: 48 shares the r64 bucket
    assert ga.best_mode(48, device_kind="TPU v5 lite0") == "einsum"
    # other buckets / dtypes untouched
    assert ga.best_mode(32, device_kind="TPU v5 lite0") == "pair"
    assert ga.best_mode(64, bf16=True,
                        device_kind="TPU v5 lite0") == "pair"
    # the cache file is merge-written valid JSON
    data = json.loads((tmp_path / "tune.json").read_text())
    assert data["TPU v5 lite|r64|f32"]["mode"] == "einsum"
    assert data["TPU v5 lite|r64|f32"]["source"] == "test"


def test_cpu_measurements_not_persisted(tmp_path):
    ga.record(64, "pair", device_kind="cpu")
    assert not (tmp_path / "tune.json").exists()


def test_device_family_normalizes_kind_strings():
    assert ga.device_family("TPU v5 lite0") == "TPU v5 lite"
    assert ga.device_family("TPU v5 lite") == "TPU v5 lite"
    assert ga.device_family("TPU v4") == "TPU v4"
    assert ga.device_family("cpu") == "cpu"


def test_corrupt_cache_falls_back(tmp_path):
    (tmp_path / "tune.json").write_text("{not json")
    assert ga.best_mode(64, device_kind="TPU v5 lite0") == "pair"


def test_auto_dispatch_matches_concrete_modes():
    """gram_dispatch("auto") must produce the same numbers as whichever
    concrete mode the table picks (CPU here: einsum)."""
    import jax.numpy as jnp

    from predictionio_tpu.ops.gram import gram_dispatch

    rng = np.random.default_rng(0)
    F = jnp.asarray(rng.standard_normal((6, 9, 8)).astype(np.float32))
    w = jnp.asarray(rng.random((6, 9)).astype(np.float32))
    out_auto = np.asarray(gram_dispatch(F, w, "auto"))
    out_ein = np.asarray(gram_dispatch(F, w, "einsum"))
    out_pair = np.asarray(gram_dispatch(F, w, "pair"))
    np.testing.assert_allclose(out_auto, out_ein, rtol=1e-6)
    np.testing.assert_allclose(out_pair, out_ein, rtol=1e-5,
                               atol=1e-5)
