"""Row-quantized serving factor tables (ISSUE 13): quantize/dequant
accuracy, the ≥~4x users-per-HBM sizing claim, the NDCG@10 parity gate
(the tier-1 half of the CI quality gate — a trained fixture model must
rank within tolerance of f32 under int8/bf16, and a pathological model
must trip the auto-off fallback), streaming hot-swap re-quantization,
the hot tier's quantized pinned table, server-side bind wiring +
``pio_serving_kernel`` gauge, and the conditional hot-tier refresh
fix."""

from datetime import datetime, timezone

import numpy as np
import pytest

import jax

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.models import als
from predictionio_tpu.models.als import (
    ALSModel,
    ALSParams,
    QuantizedFactors,
    RatingsCOO,
    SERVING_QUANT_NDCG_FLOOR,
    apply_row_updates,
    extend_factor_rows,
    quantize_serving_model,
    recommend_batch,
    serving_quant_ndcg,
    serving_quant_of,
    table_host_f32,
    train_als,
)


def synth_model(nu=200, ni=160, r=16, seed=0, device=False):
    rng = np.random.default_rng(seed)
    U = rng.standard_normal((nu, r)).astype(np.float32)
    V = rng.standard_normal((ni, r)).astype(np.float32)
    if device:
        U, V = jax.device_put(U), jax.device_put(V)
    return ALSModel(
        user_factors=U, item_factors=V, n_users=nu, n_items=ni,
        user_ids=BiMap({f"u{i}": i for i in range(nu)}),
        item_ids=BiMap({f"i{i}": i for i in range(ni)}),
        params=ALSParams(rank=r))


def trained_fixture(rank=8, seed=3):
    """A small TRAINED model (structured factors, not noise) — the
    fixture the NDCG parity gate runs on."""
    rng = np.random.default_rng(seed)
    nu, ni, nnz = 80, 60, 1200
    coo = RatingsCOO(rng.integers(0, nu, nnz).astype(np.int32),
                     rng.integers(0, ni, nnz).astype(np.int32),
                     (rng.random(nnz).astype(np.float32) * 4 + 1),
                     nu, ni)
    U, V = train_als(coo, ALSParams(rank=rank, num_iterations=4,
                                    seed=seed))
    return ALSModel(
        user_factors=np.asarray(U), item_factors=np.asarray(V),
        n_users=nu, n_items=ni,
        user_ids=BiMap({f"u{i}": i for i in range(nu)}),
        item_ids=BiMap({f"i{i}": i for i in range(ni)}),
        params=ALSParams(rank=rank))


class TestQuantizeRoundtrip:
    def test_int8_per_row_error_bound(self):
        rng = np.random.default_rng(0)
        # rows at wildly different magnitudes: per-ROW scales must
        # hold relative error on every row, which one global scale
        # cannot
        rows = rng.standard_normal((32, 24)).astype(np.float32)
        rows *= (10.0 ** rng.integers(-3, 3, (32, 1)))
        data, scale = als._quantize_rows(rows, "int8")
        back = data.astype(np.float32) * scale
        rel = np.abs(back - rows).max(axis=1) \
            / np.abs(rows).max(axis=1)
        assert rel.max() < 1 / 127 + 1e-6

    def test_bf16_has_no_scale(self):
        rows = np.random.default_rng(1).standard_normal(
            (8, 16)).astype(np.float32)
        data, scale = als._quantize_rows(rows, "bf16")
        assert scale is None
        np.testing.assert_allclose(
            np.asarray(data, dtype=np.float32), rows, rtol=1e-2)

    def test_capacity_claim(self):
        """The HBM sizing math (docs/sharded-serving.md): int8 shrinks
        the factor bytes 4x; with the per-row f32 scale the per-user
        bytes are r+4 vs 4r — ≥3.7x more users per HBM at rank 64 and
        asymptotically 4x."""
        m = synth_model(nu=1000, ni=100, r=64)
        q = quantize_serving_model(m, "int8", parity_sample=0)
        f32_user_bytes = m.user_factors.nbytes
        q_user_bytes = q.user_factors.nbytes
        ratio = f32_user_bytes / q_user_bytes
        assert ratio == pytest.approx(4 * 64 / (64 + 4), rel=1e-6)
        assert ratio > 3.7
        b = quantize_serving_model(m, "bf16", parity_sample=0)
        assert m.user_factors.nbytes / b.user_factors.nbytes == 2.0

    def test_off_and_idempotent(self):
        m = synth_model()
        assert quantize_serving_model(m, "off") is m
        q = quantize_serving_model(m, "int8")
        assert quantize_serving_model(q, "int8") is q
        with pytest.raises(ValueError, match="quant"):
            quantize_serving_model(m, "fp4")


class TestNDCGParityGate:
    """The CI quality gate: quantized ranking vs f32 ranking on a
    TRAINED fixture must clear the same floor the deploy-time auto-off
    probe enforces — `--serving-quant` can never silently degrade
    ranking past it."""

    @pytest.mark.parametrize("quant,floor", [("int8", 0.97),
                                             ("bf16", 0.99)])
    def test_trained_fixture_parity(self, quant, floor):
        m = trained_fixture()
        q = quantize_serving_model(m, quant, parity_sample=0)
        ndcg = serving_quant_ndcg(
            table_host_f32(m.user_factors),
            table_host_f32(m.item_factors),
            q.user_factors, q.item_factors, m.n_items, k=10,
            sample=64)
        assert ndcg >= floor, \
            f"{quant} NDCG@10 {ndcg:.4f} below the {floor} gate"

    def test_auto_off_on_pathological_model(self):
        """Items nearly identical within int8 resolution: quantization
        destroys the ranking, the probe must refuse and keep f32."""
        rng = np.random.default_rng(5)
        nu, ni, r = 60, 50, 8
        U = rng.standard_normal((nu, r)).astype(np.float32)
        v0 = rng.standard_normal(r).astype(np.float32)
        V = (v0[None, :]
             + 1e-5 * rng.standard_normal((ni, r))).astype(np.float32)
        m = ALSModel(
            user_factors=U, item_factors=V, n_users=nu, n_items=ni,
            user_ids=BiMap({f"u{i}": i for i in range(nu)}),
            item_ids=BiMap({f"i{i}": i for i in range(ni)}),
            params=ALSParams(rank=r))
        q = quantize_serving_model(m, "int8")
        assert not isinstance(q.user_factors, QuantizedFactors)
        assert serving_quant_of(q) == "off"
        # the healthy fixture passes the same probe
        ok = quantize_serving_model(trained_fixture(), "int8")
        assert serving_quant_of(ok) == "int8"

    def test_floor_constant_sane(self):
        assert 0.9 <= SERVING_QUANT_NDCG_FLOOR < 1.0


class TestServingParity:
    def test_int8_ranking_close_to_f32(self):
        m = trained_fixture()
        ids_f, _ = recommend_batch(
            als.ensure_device_resident(m), np.arange(30), 10)
        q = als.ensure_device_resident(
            quantize_serving_model(m, "int8", parity_sample=0))
        ids_q, _ = recommend_batch(q, np.arange(30), 10)
        overlap = np.mean([len(set(a) & set(b)) / 10
                           for a, b in zip(ids_f, ids_q)])
        assert overlap >= 0.9

    def test_host_fast_path_untouched(self):
        """A small f32 model keeps the host numpy fast path; the quant
        knob moves serving to the device only when asked."""
        m = synth_model()
        assert als._serve_on_host(m, 1)
        q = quantize_serving_model(m, "int8", parity_sample=0)
        assert not als._serve_on_host(q, 1)


class TestStreamingHotSwap:
    def test_apply_row_updates_requantizes(self):
        m = quantize_serving_model(synth_model(device=True), "int8",
                                   parity_sample=0)
        rng = np.random.default_rng(2)
        rows = rng.standard_normal((4, 16)).astype(np.float32)
        idx = np.array([0, 3, 9, 11])
        m2 = apply_row_updates(m, "user", idx, rows)
        assert isinstance(m2.user_factors, QuantizedFactors)
        got = table_host_f32(m2.user_factors)[idx]
        rel = np.abs(got - rows).max() / np.abs(rows).max()
        assert rel < 0.02  # int8 quantization error, nothing more
        # untouched rows bit-identical (functional update)
        before = table_host_f32(m.user_factors)
        after = table_host_f32(m2.user_factors)
        keep = np.setdiff1d(np.arange(m.n_users), idx)
        np.testing.assert_array_equal(after[keep], before[keep])

    def test_extend_factor_rows_quantized(self):
        m = quantize_serving_model(synth_model(device=True), "int8",
                                   parity_sample=0)
        rows = np.random.default_rng(3).standard_normal(
            (2, 16)).astype(np.float32)
        m2 = extend_factor_rows(m, "user", ["new-a", "new-b"], rows)
        assert m2.n_users == m.n_users + 2
        assert isinstance(m2.user_factors, QuantizedFactors)
        got = table_host_f32(m2.user_factors)[m.n_users:m.n_users + 2]
        assert np.abs(got - rows).max() / np.abs(rows).max() < 0.02

    def test_fold_in_rows_against_quant_table(self):
        """fold_in_rows dequantizes the fixed side: solving against a
        quantized serving table lands near the f32 solve."""
        m = trained_fixture()
        q = quantize_serving_model(m, "int8", parity_sample=0)
        idx = np.array([[1, 2, 3, 0]], dtype=np.int32)
        val = np.array([[4.0, 3.0, 5.0, 0.0]], dtype=np.float32)
        cnt = np.array([3], dtype=np.int32)
        r_f = als.fold_in_rows(m.item_factors, idx, val, cnt, m.params)
        r_q = als.fold_in_rows(q.item_factors, idx, val, cnt, m.params)
        np.testing.assert_allclose(r_q, r_f, rtol=0.1, atol=0.05)


def _boot_server(cfg, model=None, rank=16):
    from predictionio_tpu.controller import Context
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
    )
    from predictionio_tpu.server.engineserver import QueryServer
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )

    model = model or synth_model(r=rank)
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "sq"))
    ctx = Context(app_name="sq", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="sq", status=STATUS_COMPLETED, start_time=now, end_time=now,
        engine_id="sq", engine_version="1", engine_variant="e.json",
        engine_factory="s")
    return QueryServer(ctx, recommendation_engine(),
                       default_engine_params("sq", rank=rank),
                       [model], inst, cfg)


class TestServerWiring:
    def test_bind_quantizes_and_records_gauge(self):
        from predictionio_tpu.models.als import set_serving_topk_mode
        from predictionio_tpu.server.engineserver import ServerConfig

        try:
            qs = _boot_server(ServerConfig(warm_start=False,
                                           serving_quant="int8"))
            assert isinstance(qs.models[0].user_factors,
                              QuantizedFactors)
            st = qs.serving_kernel_status()
            assert st["quant"] == "int8"
            assert st["configuredQuant"] == "int8"
            fam = qs.metrics.gauge("pio_serving_kernel")
            active = {tuple(sorted(dict(items).items())): c.value
                      for items, c in fam.children()}
            assert any(v == 1.0 for v in active.values())
            # queries still answer on the quantized binding
            out = qs.query({"user": "u3", "num": 5})
            assert len(out["itemScores"]) == 5
        finally:
            set_serving_topk_mode(None)

    def test_bad_config_fails_deploy(self):
        from predictionio_tpu.server.engineserver import ServerConfig

        with pytest.raises(ValueError, match="serving_quant"):
            _boot_server(ServerConfig(warm_start=False,
                                      serving_quant="fp8"))
        from predictionio_tpu.models.als import set_serving_topk_mode

        try:
            with pytest.raises(ValueError, match="serving topk"):
                _boot_server(ServerConfig(warm_start=False,
                                          serving_topk="fastest"))
        finally:
            set_serving_topk_mode(None)

    def test_off_default_serves_f32(self):
        from predictionio_tpu.server.engineserver import ServerConfig

        qs = _boot_server(ServerConfig(warm_start=False))
        assert not isinstance(qs.models[0].user_factors,
                              QuantizedFactors)
        assert qs.serving_kernel_status()["quant"] == "off"


class TestConditionalHotRefresh:
    """Satellite fix: a stream hot-swap that touches NO pinned entity
    must not re-warm the pinned table (the unconditional refresh paid
    a full re-pin + k-ladder warm per fold-in)."""

    def _server_with_hot(self):
        from predictionio_tpu.server.engineserver import ServerConfig

        model = synth_model(nu=2000, ni=2000, r=32, device=True)
        qs = _boot_server(
            ServerConfig(warm_start=False, serving_cache=True,
                         hot_entities=8, hot_refresh_every=4),
            model=model, rank=32)
        return qs

    def test_untouched_swap_skips_refresh(self):
        qs = self._server_with_hot()
        hot = qs.cache.hot
        # pin u1 by hand (deterministic, no background thread timing)
        for _ in range(3):
            hot.record("u1")
        hot.refresh(wait=True)
        assert hot.lookup("u1") is not None
        refreshes_before = hot.stats()["refreshes"]
        with qs._lock:
            base_id = qs.instance.id
        m2 = apply_row_updates(
            qs.models[0], "user", np.array([500]),
            np.random.default_rng(0).standard_normal(
                (1, 32)).astype(np.float32))
        assert qs.apply_stream_delta(0, m2, ["u500"], base_id,
                                     rows_updated=1)
        # u500 was never pinned: no refresh scheduled
        assert hot.stats()["refreshes"] == refreshes_before
        assert hot.lookup("u1") is not None  # pin survives

    def test_touched_swap_refreshes(self):
        import time

        qs = self._server_with_hot()
        hot = qs.cache.hot
        for _ in range(3):
            hot.record("u1")
        hot.refresh(wait=True)
        refreshes_before = hot.stats()["refreshes"]
        with qs._lock:
            base_id = qs.instance.id
        m2 = apply_row_updates(
            qs.models[0], "user", np.array([1]),
            np.random.default_rng(1).standard_normal(
                (1, 32)).astype(np.float32))
        assert qs.apply_stream_delta(0, m2, ["u1"], base_id,
                                     rows_updated=1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if hot.stats()["refreshes"] > refreshes_before:
                break
            time.sleep(0.05)
        assert hot.stats()["refreshes"] > refreshes_before
