"""Staged serving-pipeline tests (ISSUE 9): burst-load slot integrity,
deadline shedding, promote/reload mid-flight binding consistency, the
overlap/phase telemetry, and the OverlapTracker itself."""

import threading
import time
from datetime import datetime, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.base import (
    STATUS_COMPLETED,
    EngineInstance,
)
from predictionio_tpu.models.als import ALSModel, ALSParams
from predictionio_tpu.obs import OverlapTracker
from predictionio_tpu.server.engineserver import (
    HTTPError,
    MicroBatcher,
    QueryServer,
    ServerConfig,
    StagedPipeline,
)
from predictionio_tpu.templates.recommendation import (
    default_engine_params,
    recommendation_engine,
)


def _model(nu=64, ni=40, rank=8, seed=0):
    rng = np.random.default_rng(seed)
    return ALSModel(
        user_factors=rng.standard_normal((nu, rank)).astype(np.float32),
        item_factors=rng.standard_normal((ni, rank)).astype(np.float32),
        n_users=nu, n_items=ni,
        user_ids=BiMap({f"u{i}": i for i in range(nu)}),
        item_ids=BiMap({f"i{i}": i for i in range(ni)}),
        params=ALSParams(rank=rank))


def _mk_server(cfg, model=None, persist=False):
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "pipe"))
    ctx = Context(app_name="pipe", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="p0", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="pipe", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    storage.engine_instances().insert(inst)
    model = model or _model()
    if persist:
        # make the instance reload()-able: persist the model blob the
        # way run_train does
        from predictionio_tpu.data.storage.base import Model
        from predictionio_tpu.workflow import persistence

        engine = recommendation_engine()
        ep = default_engine_params("pipe", rank=8)
        algo = engine.make_algorithms(ep)[0]
        stored = [algo.make_persistent_model(model, inst.id, 0)]
        storage.models().insert(Model(
            id=inst.id, models=persistence.dumps_models(stored)))
    qs = QueryServer(ctx, recommendation_engine(),
                     default_engine_params("pipe", rank=8),
                     [model], inst, cfg)
    return qs


def _items(result) -> list:
    return [s["item"] for s in result["itemScores"]]


def _assert_same_answer(got, want):
    """Same ranking; scores to float tolerance — different batch
    shapes legitimately differ by an ulp in reduction order."""
    assert _items(got) == _items(want)
    for g, w in zip(got["itemScores"], want["itemScores"]):
        assert g["score"] == pytest.approx(w["score"], rel=1e-5)


class TestBurstIntegrity:
    def test_flood_4x_max_batch_no_lost_or_swapped_slots(self):
        """4× max_batch concurrent submits: every caller gets exactly
        ITS user's result (slot swaps would cross users), nothing is
        lost, and nothing is duplicated into the wrong slot."""
        qs = _mk_server(ServerConfig(batching=True, max_batch=8,
                                     batch_window_ms=5.0,
                                     warm_start=False))
        assert isinstance(qs.batcher, StagedPipeline)
        want = {u: qs.query({"user": f"u{u}", "num": 3})
                for u in range(8)}
        n = 4 * 8
        users = [i % 8 for i in range(n)]
        results = [None] * n

        def fire(i):
            results[i] = qs.batcher.submit(
                {"user": f"u{users[i]}", "num": 3})

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, r in enumerate(results):
            assert not isinstance(r, HTTPError), f"slot {i}: {r}"
            _assert_same_answer(r, want[users[i]])
        # every query was counted exactly once
        assert qs.request_count >= n

    def test_burst_batches_actually_coalesce(self):
        """The occupancy histogram must show real coalescing under
        burst (the staged path must not shred into batch-1 slivers)."""
        qs = _mk_server(ServerConfig(batching=True, max_batch=16,
                                     batch_window_ms=20.0,
                                     warm_start=False))
        n = 48
        threads = [threading.Thread(
            target=lambda i=i: qs.batcher.submit(
                {"user": f"u{i % 8}", "num": 3})) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        occ = qs.metrics.snapshot()["pio_batch_occupancy"]
        assert occ["sum"] == n
        assert occ["max"] > 1  # at least one real coalesced batch

    def test_parse_errors_complete_without_device_round_trip(self):
        qs = _mk_server(ServerConfig(batching=True, max_batch=8,
                                     warm_start=False))
        r = qs.batcher.submit({"bogus": 1})
        assert isinstance(r, HTTPError) and r.status == 400
        r2 = qs.batcher.submit({"user": "u1", "num": 2})
        assert len(r2["itemScores"]) == 2


class TestDeadline:
    def _wedge(self, qs, seconds):
        """Wedge the pipeline: supplement blocks (assemble stage)."""
        class Wedged:
            def __init__(self, inner):
                self.inner = inner

            def supplement(self, q):
                time.sleep(seconds)
                return self.inner.supplement(q)

            def serve(self, q, ps):
                return self.inner.serve(q, ps)

        qs.serving = Wedged(qs.serving)

    @pytest.mark.parametrize("pipeline", ["staged", "serial"])
    def test_wedged_dispatch_sheds_503(self, pipeline):
        qs = _mk_server(ServerConfig(batching=True, max_batch=4,
                                     serving_pipeline=pipeline,
                                     queue_deadline_ms=150.0,
                                     warm_start=False))
        self._wedge(qs, 2.0)
        t0 = time.monotonic()
        r = qs.batcher.submit({"user": "u1", "num": 2})
        waited = time.monotonic() - t0
        assert isinstance(r, HTTPError) and r.status == 503
        assert waited < 1.5  # returned at the deadline, not after the
        # wedge cleared
        assert qs._deadline_exceeded.labels().value >= 1
        # the shed is visible as a 503 in the error series too
        assert qs._query_errors.labels(status="503").value >= 1

    def test_expired_queue_entries_never_dispatch(self):
        """Entries whose submitter already gave up are completed as
        corpses at pickup — the batch they would have joined must not
        contain them (no device work for dead callers)."""
        qs = _mk_server(ServerConfig(batching=True, max_batch=8,
                                     queue_deadline_ms=100.0,
                                     warm_start=False))
        self._wedge(qs, 0.8)
        n = 12
        results = [None] * n
        threads = [threading.Thread(
            target=lambda i=i: results.__setitem__(
                i, qs.batcher.submit({"user": "u1", "num": 2})))
            for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(isinstance(r, HTTPError) and r.status == 503
                   for r in results)
        assert qs._deadline_exceeded.labels().value == n
        # the wedge clears; the pipeline is healthy again
        time.sleep(1.0)
        qs.serving = qs.serving.inner
        assert len(qs.batcher.submit(
            {"user": "u2", "num": 2})["itemScores"]) == 2

    def test_deadline_zero_disables(self):
        qs = _mk_server(ServerConfig(batching=True,
                                     queue_deadline_ms=0.0,
                                     warm_start=False))
        r = qs.batcher.submit({"user": "u1", "num": 2})
        assert len(r["itemScores"]) == 2
        assert qs._deadline_exceeded.labels().value == 0

    def test_microbatcher_deadline_signature_default(self):
        import inspect

        sig = inspect.signature(MicroBatcher.__init__)
        assert sig.parameters["deadline_ms"].default == 0.0


class TestMidFlightRebind:
    def test_promote_reload_storm_never_serves_torn_binding(self):
        """Queries flood the staged pipeline while reload() rebinds in
        a loop. Every response must be a complete, well-formed result
        from SOME binding — never a 500 from a half-swapped one
        (extends the PR 3 warm-race stress to the staged path)."""
        qs = _mk_server(ServerConfig(batching=True, max_batch=8,
                                     batch_window_ms=2.0,
                                     warm_start=False), persist=True)
        want = qs.query({"user": "u3", "num": 4})
        stop = threading.Event()
        rebind_errors = []

        def rebinder():
            while not stop.is_set():
                try:
                    qs.reload()
                except Exception as e:  # noqa: BLE001 — surface
                    rebind_errors.append(e)

        errors = []
        results = []
        lock = threading.Lock()

        def fire():
            for _ in range(20):
                r = qs.batcher.submit({"user": "u3", "num": 4})
                with lock:
                    if isinstance(r, HTTPError):
                        errors.append(r)
                    else:
                        results.append(r)

        rb = threading.Thread(target=rebinder)
        workers = [threading.Thread(target=fire) for _ in range(6)]
        rb.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        stop.set()
        rb.join()
        assert not rebind_errors
        assert not errors, f"mid-rebind queries failed: {errors[:3]}"
        # same instance re-loaded → identical answers throughout
        for r in results:
            _assert_same_answer(r, want)

    def test_batch_binding_snapshot_is_consistent(self):
        """The assemble-time snapshot must ride the whole batch: a
        rebind between assemble and dispatch must not mix models."""
        qs = _mk_server(ServerConfig(batching=True, max_batch=4,
                                     warm_start=False), persist=True)
        ab = qs.batcher._assemble([
            type("E", (), {"query_json": {"user": "u1", "num": 2},
                           "t_enq": time.monotonic(), "obs": None,
                           "done": threading.Event(),
                           "slot": [None], "abandoned": False,
                           "deadline": None})()])
        assert ab.algorithms is not None
        assert ab.instance_id == qs.instance.id
        # the snapshot is by-reference frozen: a rebind swaps the
        # server's lists, not the batch's
        old_models = ab.models
        qs.reload()
        assert ab.models is old_models


class TestPipelineTelemetry:
    def test_phase_and_stage_series_recorded(self):
        qs = _mk_server(ServerConfig(batching=True, max_batch=8,
                                     warm_start=False))
        threads = [threading.Thread(
            target=lambda i=i: qs.batcher.submit(
                {"user": f"u{i % 8}", "num": 3})) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = qs.metrics.snapshot()
        stages = snap["pio_pipeline_stage_seconds"]
        for stage in ("assemble", "dispatch", "readback"):
            assert f'stage={stage}' in stages
        phases = snap["pio_query_phase_seconds"]
        assert "phase=device_wait" in phases
        assert "phase=queue_wait" in phases
        status = qs.pipeline_status()
        assert status["mode"] == "staged"
        assert 0.0 <= status["overlap"]["deviceIdleFraction"] <= 1.0
        assert status["deadlineExceeded"] == 0

    def test_readback_phase_is_max_not_sum(self):
        """Satellite: the batch readback phase reports the worst
        query's serialization, not the sum over the batch."""
        qs = _mk_server(ServerConfig(warm_start=False))
        obs_list = [{} for _ in range(6)]
        qs.query_batch([{"user": f"u{i}", "num": 3} for i in range(6)],
                       obs_list=obs_list)
        per_query = [o["readbackMs"] for o in obs_list]
        batch_ms = obs_list[0]["readbackMs"]
        # identical batch value broadcast to every query's obs
        assert all(o.get("readbackMs") is not None for o in obs_list)
        # the recorded batch phase equals the max, and is NOT the sum
        phases = qs.metrics.snapshot()["pio_query_phase_seconds"]
        readback_ms = phases["phase=readback"]["max"] * 1000
        assert readback_ms <= sum(per_query) + 1e-6
        assert readback_ms >= max(per_query) * 0.5 - 1e-6

    def test_serial_mode_still_works_and_reports(self):
        qs = _mk_server(ServerConfig(batching=True,
                                     serving_pipeline="serial",
                                     warm_start=False))
        assert isinstance(qs.batcher, MicroBatcher)
        r = qs.batcher.submit({"user": "u1", "num": 2})
        assert len(r["itemScores"]) == 2
        assert qs.pipeline_status()["mode"] == "serial"

    def test_unknown_pipeline_mode_rejected(self):
        with pytest.raises(ValueError, match="serving_pipeline"):
            _mk_server(ServerConfig(batching=True,
                                    serving_pipeline="bogus",
                                    warm_start=False))


class TestOverlapTracker:
    def test_overlap_accounting(self):
        t = [0.0]
        tr = OverlapTracker(time_fn=lambda: t[0])
        tr.enter("device")          # t=0
        t[0] = 1.0
        assert tr.enter("assemble") == 0  # host joins at t=1
        t[0] = 3.0
        tr.exit("assemble")         # overlap [1, 3] = 2s
        t[0] = 4.0
        tr.exit("device")           # device busy [0, 4]
        t[0] = 5.0
        snap = tr.snapshot()
        assert snap["wall_sec"] == pytest.approx(5.0)
        assert snap["device_busy_sec"] == pytest.approx(4.0)
        assert snap["overlap_sec"] == pytest.approx(2.0)
        assert snap["device_idle_fraction"] == pytest.approx(0.2)
        assert snap["overlap_fraction"] == pytest.approx(0.4)

    def test_enter_returns_prior_count(self):
        tr = OverlapTracker()
        assert tr.enter("device") == 0
        assert tr.enter("device") == 1  # overlapped launch
        tr.exit("device")
        tr.exit("device")
        assert tr.active("device") == 0

    def test_idle_without_traffic(self):
        tr = OverlapTracker()
        assert tr.device_idle_fraction() == 1.0
        assert tr.overlap_fraction() == 0.0
