"""Metric library semantics (reference MetricTest: Average/OptionAverage/
Stdev/Sum/Zero over multi-fold eval data + ranking helpers; best-params
selection is covered by tests/test_engine.py TestMetricEvaluator)."""

import math

import pytest

from predictionio_tpu.controller import (
    AverageMetric,
    OptionAverageMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
    ndcg_at_k,
    precision_at_k,
)


class QMinusA(AverageMetric):
    def calculate_point(self, ei, q, p, a):
        return q - a


class OptionalScore(OptionAverageMetric):
    def calculate_point(self, ei, q, p, a):
        return None if a is None else float(q)


def folds(*points_per_fold):
    """Build EvalData: each arg is a list of (q, p, a) tuples."""
    return [(None, pts) for pts in points_per_fold]


class TestMetricAggregation:
    def test_average_across_folds(self):
        # reference semantics: one global mean over the union of folds
        data = folds([(4, 0, 1), (2, 0, 1)], [(9, 0, 3)])
        assert QMinusA().calculate(data) == pytest.approx((3 + 1 + 6) / 3)

    def test_option_average_excludes_none(self):
        data = folds([(4, 0, 1), (2, 0, None), (6, 0, 1)])
        # None point excluded from numerator AND denominator
        assert OptionalScore().calculate(data) == pytest.approx(5.0)

    def test_average_empty_is_nan(self):
        assert math.isnan(QMinusA().calculate(folds([])))

    def test_stdev_population(self):
        class S(StdevMetric):
            def calculate_point(self, ei, q, p, a):
                return q

        data = folds([(2, 0, 0), (4, 0, 0), (4, 0, 0), (4, 0, 0),
                      (5, 0, 0), (5, 0, 0), (7, 0, 0), (9, 0, 0)])
        assert S().calculate(data) == pytest.approx(2.0)  # classic example

    def test_sum(self):
        class S(SumMetric):
            def calculate_point(self, ei, q, p, a):
                return q

        assert S().calculate(folds([(1, 0, 0)], [(2, 0, 0),
                                                 (3, 0, 0)])) == 6.0

    def test_zero(self):
        assert ZeroMetric().calculate(folds([(1, 2, 3)])) == 0.0

    def test_compare_ordering(self):
        m = QMinusA()
        assert m.compare(2.0, 1.0) > 0
        assert m.compare(1.0, 2.0) < 0
        assert m.compare(1.0, 1.0) == 0


class TestRankingHelpers:
    def test_precision_at_k(self):
        assert precision_at_k(["a", "b", "c"], {"a", "c"}, 2) == 0.5
        # denominator is min(k, |relevant|) — reference semantics
        assert precision_at_k(["a", "b"], {"a"}, 3) == 1.0
        assert precision_at_k(["a"], set(), 3) is None

    def test_ndcg_at_k(self):
        # perfect ranking → 1.0
        assert ndcg_at_k(["a", "b"], {"a", "b"}, 2) == pytest.approx(1.0)
        # relevant item at position 2 only
        got = ndcg_at_k(["x", "a"], {"a"}, 2)
        assert got == pytest.approx((1 / math.log2(3)) / 1.0)
        assert ndcg_at_k(["x"], set(), 2) is None


class TestParallelSweep:
    """MetricEvaluator's thread-parallel grid walk (the reference's .par
    map, MetricEvaluator.scala:224-231) must be deterministic: same
    scores, same order, same winner as the sequential walk."""

    def test_parallel_matches_sequential(self):
        import numpy as np

        from predictionio_tpu.controller.context import Context
        from predictionio_tpu.controller.evaluation import (
            Evaluation,
            MetricEvaluator,
        )
        from predictionio_tpu.controller.params import EngineParams
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App
        from predictionio_tpu.data.storage.registry import Storage
        from predictionio_tpu.models.als import ALSParams
        from predictionio_tpu.templates.recommendation import (
            DataSourceParams,
            PrecisionAtK,
            recommendation_engine,
        )

        storage = Storage(env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM"})
        app_id = storage.apps().insert(App(id=0, name="papp"))
        storage.events().init(app_id)
        rng = np.random.default_rng(1)
        storage.events().insert_batch(
            [Event(event="rate", entity_type="user",
                   entity_id=f"u{rng.integers(30)}",
                   target_entity_type="item",
                   target_entity_id=f"i{rng.integers(20)}",
                   properties={"rating": float(rng.integers(1, 6))})
             for _ in range(600)], app_id)

        grid = [EngineParams(
            datasource=("", DataSourceParams(app_name="papp", eval_k=2)),
            algorithms=[("als", ALSParams(rank=r, num_iterations=3,
                                          reg=reg, seed=3))])
            for r in (3, 5) for reg in (0.05, 0.2)]
        ctx = Context(app_name="papp", _storage=storage)
        ev = Evaluation(engine=recommendation_engine(),
                        metric=PrecisionAtK(k=3))
        seq = MetricEvaluator(ev, parallelism=1).evaluate(ctx, grid)
        par = MetricEvaluator(ev, parallelism=4).evaluate(ctx, grid)
        assert [s.score for s in seq.scores] == [s.score for s in par.scores]
        assert seq.best_index == par.best_index
        assert seq.best_score == par.best_score
