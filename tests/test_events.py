"""Event model + DataMap + BiMap tests.

Scenario parity with the reference specs
(`data/src/test/.../storage/{DataMapSpec,BiMapSpec}.scala`, validation rules
from `Event.scala:112-160`).
"""

import pytest

from predictionio_tpu.data import (
    BiMap,
    DataMap,
    DataMapError,
    Event,
    EventValidationError,
)
from predictionio_tpu.data.event import isoformat_millis, parse_iso


class TestEventValidation:
    def test_basic_event(self):
        e = Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"rating": 4.0}))
        assert e.event == "rate"
        assert e.properties.get("rating", float) == 4.0

    def test_empty_event_name_rejected(self):
        with pytest.raises(EventValidationError):
            Event(event="", entity_type="user", entity_id="u1")

    def test_unknown_reserved_event_rejected(self):
        with pytest.raises(EventValidationError):
            Event(event="$foo", entity_type="user", entity_id="u1")

    def test_special_event_with_target_rejected(self):
        with pytest.raises(EventValidationError):
            Event(event="$set", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties=DataMap({"a": 1}))

    def test_unset_requires_properties(self):
        with pytest.raises(EventValidationError):
            Event(event="$unset", entity_type="user", entity_id="u1")

    def test_target_must_be_paired(self):
        with pytest.raises(EventValidationError):
            Event(event="view", entity_type="user", entity_id="u1",
                  target_entity_id="i1")

    def test_reserved_entity_type_prefix(self):
        with pytest.raises(EventValidationError):
            Event(event="view", entity_type="pio_thing", entity_id="x")
        # built-in type is allowed
        Event(event="predict", entity_type="pio_pr", entity_id="x")

    def test_reserved_property_prefix(self):
        with pytest.raises(EventValidationError):
            Event(event="view", entity_type="user", entity_id="u1",
                  properties=DataMap({"pio_secret": 1}))

    def test_json_roundtrip(self):
        e = Event(event="buy", entity_type="user", entity_id="u9",
                  target_entity_type="item", target_entity_id="i3",
                  properties=DataMap({"qty": 2, "tags": ["a", "b"]}),
                  pr_id="pred-1")
        e2 = Event.from_json(e.to_json())
        assert e2.event == e.event
        assert e2.entity_id == e.entity_id
        assert e2.target_entity_id == e.target_entity_id
        assert e2.properties == e.properties
        assert e2.pr_id == e.pr_id
        assert e2.event_time == e.event_time

    def test_iso_parse_variants(self):
        t = parse_iso("2026-01-02T03:04:05.678Z")
        assert isoformat_millis(t) == "2026-01-02T03:04:05.678Z"
        t2 = parse_iso("2026-01-02T03:04:05.678+00:00")
        assert t2 == t


class TestDataMap:
    # mirrors DataMapSpec.scala: typed get over a mixed-type object
    DM = DataMap({
        "string": "a string",
        "int": 10,
        "double": 4.56,
        "boolean": True,
        "array": [1, 2, 3],
        "strings": ["a", "b"],
        "obj": {"k": 1},
        "null": None,
    })

    def test_typed_get(self):
        assert self.DM.get("string", str) == "a string"
        assert self.DM.get("int", int) == 10
        assert self.DM.get("double", float) == 4.56
        assert self.DM.get("boolean", bool) is True
        assert self.DM.get_list("array", int) == [1, 2, 3]
        assert self.DM.get_list("strings", str) == ["a", "b"]

    def test_int_coerces_to_float(self):
        assert self.DM.get("int", float) == 10.0

    def test_missing_field_raises(self):
        with pytest.raises(DataMapError):
            self.DM.get("nope")

    def test_missing_field_default(self):
        assert self.DM.get("nope", int, default=7) == 7

    def test_get_opt(self):
        assert self.DM.get_opt("null") is None
        assert self.DM.get_opt("nope") is None
        assert self.DM.get_opt("int", int) == 10

    def test_wrong_type_raises(self):
        with pytest.raises(DataMapError):
            self.DM.get("string", int)
        with pytest.raises(DataMapError):
            self.DM.get("int", bool)

    def test_union_right_biased(self):
        a = DataMap({"x": 1, "y": 2})
        b = DataMap({"y": 3, "z": 4})
        assert a.union(b) == DataMap({"x": 1, "y": 3, "z": 4})

    def test_without(self):
        a = DataMap({"x": 1, "y": 2})
        assert a.without(["y", "zz"]) == DataMap({"x": 1})

    def test_from_json_string(self):
        assert DataMap('{"a": 1}') == DataMap({"a": 1})


class TestBiMap:
    # mirrors BiMapSpec.scala
    def test_inverse(self):
        m = BiMap({"a": 1, "b": 2})
        assert m["a"] == 1
        assert m.inverse[2] == "b"
        assert m.inverse.inverse["a"] == 1

    def test_duplicate_values_rejected(self):
        with pytest.raises(ValueError):
            BiMap({"a": 1, "b": 1})

    def test_string_int_dense(self):
        m = BiMap.string_int(["u3", "u1", "u3", "u2", "u1"])
        assert sorted(m.values()) == [0, 1, 2]
        assert m["u3"] == 0 and m["u1"] == 1 and m["u2"] == 2
        assert len(m) == 3

    def test_map_array(self):
        m = BiMap.string_int(["a", "b", "c"])
        out = m.map_array(["c", "zz", "a"])
        assert out.tolist() == [2, -1, 0]

    def test_take(self):
        m = BiMap.string_int(["a", "b", "c"])
        t = m.take(["a", "c", "zz"])
        assert set(t.keys()) == {"a", "c"}


class TestEntityMap:
    def test_entity_id_ix_map(self):
        from predictionio_tpu.data.entitymap import EntityIdIxMap

        m = EntityIdIxMap.from_keys(["a", "b", "c"])
        assert m["a"] == 0 and m[2] == "c"
        assert "b" in m and 1 in m
        assert m.get("zz") is None
        assert len(m) == 3
        assert m.take(2).to_map() == {"a": 0, "b": 1}

    def test_entity_map_data(self):
        from predictionio_tpu.data.entitymap import EntityMap

        em = EntityMap({"u1": {"age": 30}, "u2": {"age": 40}})
        assert em.data("u1") == {"age": 30}
        assert em.data(em["u2"]) == {"age": 40}

    def test_extract_entity_map(self):
        from datetime import datetime, timezone

        from predictionio_tpu.controller import Context
        from predictionio_tpu.data import DataMap, Event
        from predictionio_tpu.data.entitymap import extract_entity_map
        from predictionio_tpu.data.storage import App, Storage

        st = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
        aid = st.apps().insert(App(0, "em"))
        st.events().init(aid)
        t = datetime(2026, 1, 1, tzinfo=timezone.utc)
        st.events().insert_batch([
            Event(event="$set", entity_type="item", entity_id="i1",
                  properties=DataMap({"price": 9.5}), event_time=t),
            Event(event="$set", entity_type="item", entity_id="i2",
                  properties=DataMap({"price": 3.0}), event_time=t),
        ], aid)
        ctx = Context(app_name="em", _storage=st)
        em = extract_entity_map(ctx.event_store, "em", "item",
                                lambda pm: float(pm.get("price")))
        assert em.data("i1") == 9.5
        assert em.data(em["i2"]) == 3.0
        assert len(em) == 2

    def test_entity_map_take_keeps_data(self):
        from predictionio_tpu.data import EntityMap

        em = EntityMap({"a": 1, "b": 2, "c": 3})
        sub = em.take(2)
        assert isinstance(sub, EntityMap)
        assert sub.data("a") == 1 and len(sub) == 2
