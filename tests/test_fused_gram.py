"""Fused gather+Gramian Pallas kernel (ISSUE 7), run in interpret mode
on CPU so tier-1 covers the kernel without a TPU: accuracy against the
materialized-gather oracle in f32, tolerance against the bf16-shadow
wire, ragged/odd tail blocks, the full training paths (explicit and
implicit, pad and bucket layouts), and mesh-sharded parity against
meshless factors on the forced-8-device CPU mesh. Plus the satellite
contracts: centralized odd-B handling in ``gram_dispatch`` and the
autotune table's graceful einsum fallback where the kernel can't lower.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.models.als import (
    ALSParams,
    RatingsCOO,
    _lhs_fn,
    _shadow_lhs_fn,
    resolved_gram_mode,
    train_als,
)
from predictionio_tpu.ops.fused_gram import (
    fused_gram,
    fused_gram_dispatch,
    fused_gram_reference,
    fused_gram_supported,
    fused_vmem_bytes,
)
from predictionio_tpu.ops.gram import gram_dispatch, gram_weighted


def make_problem(m=100, r=24, B=40, L=33, seed=0):
    rng = np.random.default_rng(seed)
    tab = rng.normal(size=(m, r)).astype(np.float32)
    idx = rng.integers(0, m, (B, L)).astype(np.int32)
    wa = rng.random((B, L)).astype(np.float32)
    wb = rng.random((B, L)).astype(np.float32)
    return tab, idx, wa, wb


def oracle(tab, idx, wa, wb):
    F = np.asarray(tab, dtype=np.float32)[idx]
    return (np.einsum("blr,bls,bl->brs", F, F, wa),
            np.einsum("blr,bl->br", F, wb))


class TestKernelInterpret:
    def test_f32_matches_gram_weighted(self):
        """Kernel output vs the einsum path's gram_weighted on the SAME
        pre-gathered rows — the equivalence `gram_mode="fused"` claims.
        f32 end to end: only summation-order noise is allowed."""
        tab, idx, wa, wb = make_problem()
        A, b = fused_gram(jnp.asarray(tab), jnp.asarray(idx),
                          jnp.asarray(wa), jnp.asarray(wb),
                          interpret=True)
        F = jnp.asarray(tab)[jnp.asarray(idx)]
        A_ein = np.asarray(gram_weighted(F, jnp.asarray(wa)))
        np.testing.assert_allclose(np.asarray(A), A_ein,
                                   rtol=1e-5, atol=1e-5)
        _, b_ref = oracle(tab, idx, wa, wb)
        np.testing.assert_allclose(np.asarray(b), b_ref,
                                   rtol=1e-5, atol=1e-5)

    def test_matches_reference_exactly_shaped(self):
        tab, idx, wa, wb = make_problem(seed=3)
        A, b = fused_gram(jnp.asarray(tab), jnp.asarray(idx),
                          jnp.asarray(wa), jnp.asarray(wb),
                          interpret=True)
        A_ref, b_ref = fused_gram_reference(
            jnp.asarray(tab), jnp.asarray(idx), jnp.asarray(wa),
            jnp.asarray(wb))
        assert A.shape == A_ref.shape and b.shape == b_ref.shape
        np.testing.assert_allclose(np.asarray(A), np.asarray(A_ref),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_wire_within_shadow_tolerance(self):
        """bf16 table on the wire: must match the bf16-SHADOW oracle
        (gather bf16, contract f32) tightly — the shadow path's
        existing quality budget, not a new one."""
        tab, idx, wa, wb = make_problem(seed=1)
        tab16 = jnp.asarray(tab).astype(jnp.bfloat16)
        A, b = fused_gram(tab16, jnp.asarray(idx), jnp.asarray(wa),
                          jnp.asarray(wb), interpret=True)
        F16 = np.asarray(tab16.astype(jnp.float32))[idx]
        A_sh = np.einsum("blr,bls,bl->brs", F16, F16, wa)
        np.testing.assert_allclose(np.asarray(A), A_sh,
                                   rtol=1e-4, atol=1e-4)
        # and against the f32 truth only bf16-quantization error
        A_f32, _ = oracle(tab, idx, wa, wb)
        np.testing.assert_allclose(np.asarray(A), A_f32,
                                   rtol=0.1, atol=0.05)

    @pytest.mark.parametrize("B,L", [(1, 5), (13, 33), (7, 1),
                                     (19, 70)])
    def test_ragged_tails(self, B, L):
        """B not a block multiple, L not a chunk multiple: pad-and-
        slice must be invisible (pad slots carry w=0)."""
        tab, idx, wa, wb = make_problem(B=B, L=L, seed=B * 31 + L)
        A, b = fused_gram(jnp.asarray(tab), jnp.asarray(idx),
                          jnp.asarray(wa), jnp.asarray(wb),
                          chunk=16, interpret=True)
        A_ref, b_ref = oracle(tab, idx, wa, wb)
        assert A.shape == (B,) + A_ref.shape[1:]
        np.testing.assert_allclose(np.asarray(A), A_ref,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(b), b_ref,
                                   rtol=1e-5, atol=1e-5)

    def test_zero_weight_rows_are_exactly_zero(self):
        tab, idx, wa, wb = make_problem(B=9, L=12)
        wa[3:] = 0.0
        wb[3:] = 0.0
        A, b = fused_gram(jnp.asarray(tab), jnp.asarray(idx),
                          jnp.asarray(wa), jnp.asarray(wb),
                          interpret=True)
        assert np.all(np.asarray(A)[3:] == 0.0)
        assert np.all(np.asarray(b)[3:] == 0.0)

    def test_dispatch_runs_kernel_on_cpu(self):
        """No TPU attached → dispatch runs the interpret-mode kernel
        (the debugging contract), not the reference fallback."""
        tab, idx, wa, wb = make_problem(B=6, L=9)
        A, b = fused_gram_dispatch(jnp.asarray(tab), jnp.asarray(idx),
                                   jnp.asarray(wa), jnp.asarray(wb))
        A_ref, b_ref = oracle(tab, idx, wa, wb)
        np.testing.assert_allclose(np.asarray(A), A_ref,
                                   rtol=1e-5, atol=1e-5)

    def test_vmem_budget_math(self):
        # chunking caps the working set however long L grows
        assert fused_vmem_bytes(8192, 128) == fused_vmem_bytes(
            8192, 128, chunk=512)
        assert fused_vmem_bytes(512, 128, wire_bytes=2) \
            < fused_vmem_bytes(512, 128, wire_bytes=4)
        # r=128 f32 double buffer alone is 512 KiB
        assert fused_vmem_bytes(512, 128) > 2 * 512 * 128 * 4


class TestLhsFn:
    """models/als.py::_lhs_fn — the one place the gather exists."""

    def test_fused_equals_einsum_path(self):
        tab, idx, wa, wb = make_problem(B=16, L=20)
        idx3, wa3, wb3 = (x.reshape(1, *x.shape) for x in (idx, wa, wb))
        A_e, b_e = _lhs_fn(jnp.asarray(tab), jnp.asarray(idx3),
                           jnp.asarray(wa3), jnp.asarray(wb3),
                           gram="einsum", bf16=False)
        A_f, b_f = _lhs_fn(jnp.asarray(tab), jnp.asarray(idx3),
                           jnp.asarray(wa3), jnp.asarray(wb3),
                           gram="fused", bf16=False)
        np.testing.assert_allclose(np.asarray(A_f), np.asarray(A_e),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(b_f), np.asarray(b_e),
                                   rtol=1e-5, atol=1e-5)

    def test_shadow_lhs_fn_casts_to_wire(self):
        tab, idx, wa, wb = make_problem(B=8, L=10)
        idx3, wa3, wb3 = (x.reshape(1, *x.shape) for x in (idx, wa, wb))
        A_s, _ = _shadow_lhs_fn(jnp.asarray(tab), jnp.asarray(idx3),
                                jnp.asarray(wa3), jnp.asarray(wb3),
                                gram="fused", bf16=False)
        tab16 = jnp.asarray(tab).astype(jnp.bfloat16)
        A_w, _ = _lhs_fn(tab16, jnp.asarray(idx3), jnp.asarray(wa3),
                         jnp.asarray(wb3), gram="fused", bf16=False)
        np.testing.assert_allclose(np.asarray(A_s), np.asarray(A_w),
                                   rtol=1e-6, atol=1e-6)


class TestTrainingParity:
    """gram_mode="fused" must train to the einsum path's factors —
    f32 exact within solver tolerance (acceptance criterion)."""

    def _coo(self, nu=60, ni=40, nnz=900, seed=0):
        rng = np.random.default_rng(seed)
        return RatingsCOO(
            rng.integers(0, nu, nnz).astype(np.int32),
            rng.integers(0, ni, nnz).astype(np.int32),
            (rng.random(nnz).astype(np.float32) * 4 + 1),
            nu, ni)

    @pytest.mark.parametrize("implicit", [False, True])
    @pytest.mark.parametrize("layout", ["pad", "bucket"])
    def test_fused_vs_einsum_factors(self, implicit, layout):
        coo = self._coo()
        kw = dict(rank=6, num_iterations=2, seed=3, history_mode=layout,
                  implicit_prefs=implicit, alpha=8.0)
        U1, V1 = train_als(coo, ALSParams(**kw, gram_mode="einsum"))
        U2, V2 = train_als(coo, ALSParams(**kw, gram_mode="fused"))
        np.testing.assert_allclose(np.asarray(U2), np.asarray(U1),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(V2), np.asarray(V1),
                                   rtol=2e-4, atol=2e-5)

    def test_fused_bf16_shadow_within_existing_tolerance(self):
        """bf16-gather + fused kernel stays inside the SAME budget the
        shadow path's einsum run is held to (TestGatherDtype)."""
        coo = self._coo(seed=4)
        kw = dict(rank=6, num_iterations=3, seed=4, history_mode="pad",
                  implicit_prefs=True, alpha=8.0)
        U1, V1 = train_als(coo, ALSParams(**kw, gram_mode="einsum"))
        U2, V2 = train_als(coo, ALSParams(**kw, gram_mode="fused",
                                          gather_dtype="bfloat16"))
        np.testing.assert_allclose(np.asarray(U2), np.asarray(U1),
                                   rtol=0.1, atol=0.02)

    def test_split_layout_routes_through_fused(self):
        coo = self._coo(seed=5)
        kw = dict(rank=5, num_iterations=2, seed=5, max_history=8,
                  history_mode="split", implicit_prefs=False)
        with pytest.warns(UserWarning):
            U1, V1 = train_als(coo, ALSParams(**kw, gram_mode="einsum"))
        with pytest.warns(UserWarning):
            U2, V2 = train_als(coo, ALSParams(**kw, gram_mode="fused"))
        np.testing.assert_allclose(np.asarray(U2), np.asarray(U1),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the forced-8-device CPU mesh")
class TestMeshParity:
    """Mesh-sharded fused training (kernel per device on local rows via
    shard_map, Gramian all-reduce overlapped) vs meshless factors."""

    def test_sharded_fused_matches_meshless(self):
        from predictionio_tpu.parallel.mesh import make_mesh

        rng = np.random.default_rng(7)
        nu, ni, nnz = 64, 48, 800
        coo = RatingsCOO(rng.integers(0, nu, nnz).astype(np.int32),
                         rng.integers(0, ni, nnz).astype(np.int32),
                         np.ones(nnz, np.float32), nu, ni)
        mesh = make_mesh(data=4, model=2)
        kw = dict(rank=6, num_iterations=2, seed=3, history_mode="pad",
                  implicit_prefs=True, alpha=8.0, gram_mode="fused")
        U0, V0 = train_als(coo, ALSParams(**kw))
        Um, Vm = train_als(coo, ALSParams(**kw), mesh=mesh)
        np.testing.assert_allclose(np.asarray(Um)[:nu],
                                   np.asarray(U0)[:nu],
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(Vm)[:ni],
                                   np.asarray(V0)[:ni],
                                   rtol=2e-4, atol=2e-5)

    def test_sharded_fused_matches_sharded_einsum(self):
        from predictionio_tpu.parallel.mesh import make_serving_mesh

        rng = np.random.default_rng(9)
        nu, ni, nnz = 56, 40, 700
        coo = RatingsCOO(rng.integers(0, nu, nnz).astype(np.int32),
                         rng.integers(0, ni, nnz).astype(np.int32),
                         np.ones(nnz, np.float32), nu, ni)
        # the (batch, model) SERVING mesh: rows_spec is axis-name
        # agnostic, so the fused shard_map must be too
        mesh = make_serving_mesh()
        kw = dict(rank=4, num_iterations=2, seed=2,
                  history_mode="bucket", implicit_prefs=True, alpha=4.0)
        U1, V1 = train_als(coo, ALSParams(**kw, gram_mode="einsum"),
                           mesh=mesh)
        U2, V2 = train_als(coo, ALSParams(**kw, gram_mode="fused"),
                           mesh=mesh)
        np.testing.assert_allclose(np.asarray(U2), np.asarray(U1),
                                   rtol=2e-4, atol=2e-5)

    def test_gramian_allreduce_matches_einsum(self):
        from predictionio_tpu.parallel.collectives import (
            gramian_allreduce,
        )
        from predictionio_tpu.parallel.mesh import make_mesh, rows_spec
        from jax.sharding import NamedSharding

        mesh = make_mesh(data=4, model=2)
        x = np.random.default_rng(0).normal(
            size=(64, 8)).astype(np.float32)
        xs = jax.device_put(x, NamedSharding(mesh, rows_spec(mesh)))
        G = gramian_allreduce(xs, mesh)
        np.testing.assert_allclose(np.asarray(G), x.T @ x,
                                   rtol=1e-5, atol=1e-4)


class TestGramDispatchOddRows:
    """Satellite: odd-B handling is centralized in gram_dispatch —
    pad-and-slice, never a silent einsum fallback, never an assert."""

    @pytest.mark.parametrize("n", [1, 3, 7])
    def test_pair_odd_rows_pad_and_slice(self, n):
        rng = np.random.default_rng(n)
        F = jnp.asarray(rng.normal(size=(n, 12, 8)).astype(np.float32))
        w = jnp.asarray(rng.random((n, 12)).astype(np.float32))
        out = gram_dispatch(F, w, mode="pair")
        ref = gram_weighted(F, w)
        assert out.shape == ref.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_pair_odd_rows_with_lead_axis(self):
        rng = np.random.default_rng(5)
        F = jnp.asarray(rng.normal(size=(2, 5, 9, 6)).astype(np.float32))
        w = jnp.asarray(rng.random((2, 5, 9)).astype(np.float32))
        out = gram_dispatch(F, w, mode="pair")
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(gram_weighted(F, w)),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_mode_on_materialized_gather_degrades(self):
        # F already exists → nothing to fuse → baseline einsum result
        rng = np.random.default_rng(2)
        F = jnp.asarray(rng.normal(size=(4, 6, 5)).astype(np.float32))
        w = jnp.asarray(rng.random((4, 6)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(gram_dispatch(F, w, mode="fused")),
            np.asarray(gram_weighted(F, w)), rtol=1e-6)


class TestAutotuneFusedFallback:
    """Satellite: a tuning entry naming "fused" must degrade to einsum
    wherever the Pallas kernel cannot lower (here: CPU), not raise."""

    def test_fused_entry_falls_back_on_cpu(self, tmp_path, monkeypatch):
        from predictionio_tpu.ops import gram_autotune as ga

        cache = tmp_path / "gram_autotune.json"
        cache.write_text(json.dumps(
            {"cpu|r64|f32": {"mode": "fused", "source": "test"}}))
        monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE", str(cache))
        ga.reset_for_tests()
        try:
            assert not fused_gram_supported()  # no TPU here
            assert ga.best_mode(64, device_kind="cpu") == "einsum"
        finally:
            ga.reset_for_tests()

    def test_fused_recordable(self, tmp_path, monkeypatch):
        from predictionio_tpu.ops import gram_autotune as ga

        cache = tmp_path / "gram_autotune.json"
        monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE", str(cache))
        ga.reset_for_tests()
        try:
            assert ga.record(64, "fused", device_kind="TPU v5 lite0",
                             measured={"source": "bench_race"})
            saved = json.loads(cache.read_text())
            assert saved["TPU v5 lite|r64|f32"]["mode"] == "fused"
        finally:
            ga.reset_for_tests()

    def test_defaults_carry_fused_at_all_ranks(self):
        from predictionio_tpu.ops.gram_autotune import _DEFAULTS_PATH

        table = json.loads(open(_DEFAULTS_PATH).read())
        for r in (32, 64, 128):
            assert table[f"TPU v5 lite|r{r}|f32"]["mode"] == "fused"

    def test_resolved_gram_mode_helper(self):
        assert resolved_gram_mode(
            ALSParams(gram_mode="fused")) == "fused"
        # auto on CPU: heuristic einsum (no fused without lowering)
        assert resolved_gram_mode(
            ALSParams(rank=64, gram_mode="auto")) in ("einsum", "pair")


class TestParamsValidation:
    def test_fused_accepted(self):
        assert ALSParams(gram_mode="fused").gram_mode == "fused"

    def test_bogus_rejected(self):
        with pytest.raises(ValueError, match="gram_mode"):
            ALSParams(gram_mode="fusion")
