"""SLO engine battery (ISSUE 15): burn-rate math under a synthetic
clock, multi-window agreement, budget exhaustion at the configured
rate, label-scoped isolation, cold-window insufficiency, /slo.json +
pio_slo_* rendering through a real in-process engine server, the
breach→flight-recorder force-retention wiring, and the capacity gate's
ratchet semantics."""

import json
import os
import urllib.request

import pytest

from predictionio_tpu.obs import MetricsRegistry, StreamingHistogram
from predictionio_tpu.obs.histogram import window_quantile
from predictionio_tpu.obs.trace import Tracer
from predictionio_tpu.slo import (
    SLOEngine,
    SLOSpec,
    default_specs,
    gate_capacity,
    load_specs,
    ratchet_gates,
    write_gates,
)


# ---------------------------------------------------------------------------
# spec validation + (de)serialization
# ---------------------------------------------------------------------------

class TestSLOSpec:
    def test_validation(self):
        with pytest.raises(ValueError):
            SLOSpec(name="", objective="availability")
        with pytest.raises(ValueError):
            SLOSpec(name="x", objective="uptime")
        with pytest.raises(ValueError):
            SLOSpec(name="x", objective="availability", target=1.0)
        with pytest.raises(ValueError):
            SLOSpec(name="x", objective="latency")  # no threshold
        with pytest.raises(ValueError):
            SLOSpec(name="x", objective="availability",
                    window_fast_sec=600, window_slow_sec=60)
        with pytest.raises(ValueError):
            SLOSpec(name="x", objective="availability",
                    window_slow_sec=3600, budget_window_sec=60)

    def test_resolved_metric_by_objective_and_scope(self):
        assert SLOSpec(name="a", objective="availability") \
            .resolved_metric() == "pio_http_requests_total"
        assert SLOSpec(name="f", objective="freshness",
                       threshold_ms=1000).resolved_metric() \
            == "pio_stream_freshness_seconds"
        lat = SLOSpec(name="l", objective="latency", threshold_ms=100)
        assert lat.resolved_metric() == "pio_query_latency_seconds"
        assert SLOSpec(name="l2", objective="latency", threshold_ms=100,
                       scope={"route": "/queries.json"}) \
            .resolved_metric() == "pio_http_request_duration_seconds"
        assert SLOSpec(name="l3", objective="latency", threshold_ms=100,
                       scope={"arm": "candidate"}) \
            .resolved_metric() == "pio_release_latency_seconds"
        assert SLOSpec(name="l4", objective="latency", threshold_ms=100,
                       metric="my_hist").resolved_metric() == "my_hist"

    def test_json_roundtrip(self):
        spec = SLOSpec(name="x", objective="latency", target=0.95,
                       threshold_ms=150.0, scope={"route": "/q"},
                       window_fast_sec=5, window_slow_sec=20,
                       budget_window_sec=60)
        again = SLOSpec.from_json(spec.to_json())
        assert again == spec

    def test_from_json_rejects_unknown_fields(self):
        with pytest.raises(ValueError, match="unknown"):
            SLOSpec.from_json({"name": "x",
                               "objective": "availability",
                               "burn": 2})

    def test_load_specs_file(self, tmp_path):
        path = tmp_path / "specs.json"
        path.write_text(json.dumps({
            "specs": [{"name": "a", "objective": "availability"}],
            "capacity": {"staged": {"min_knee_qps": 5}}}))
        specs, gates = load_specs(str(path))
        assert specs[0].name == "a"
        assert gates["staged"]["min_knee_qps"] == 5
        path.write_text(json.dumps({"specs": []}))
        with pytest.raises(ValueError):
            load_specs(str(path))

    def test_default_specs(self):
        names = {s.name for s in default_specs()}
        assert "queries-availability" in names
        assert "stream-freshness" not in names
        names = {s.name for s in default_specs(streaming=True)}
        assert "stream-freshness" in names

    def test_committed_ci_specs_parse(self):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "slo", "specs", "ci.json")
        specs, gates = load_specs(path)
        assert {s.objective for s in specs} == {
            "availability", "latency", "freshness"}
        assert gates  # the CI capacity gate has committed limits


# ---------------------------------------------------------------------------
# burn-rate math under a synthetic clock
# ---------------------------------------------------------------------------

def make_engine(spec, families):
    """Registry + engine + a fake clock list: ``clock[0]`` is now."""
    reg = MetricsRegistry()
    made = {}
    for name, kind in families.items():
        made[name] = (reg.counter(name) if kind == "counter"
                      else reg.histogram(name))
    clock = [0.0]
    eng = SLOEngine(reg, [spec] if isinstance(spec, SLOSpec) else spec,
                    clock=lambda: clock[0])
    return reg, eng, clock, made


AVAIL = dict(name="avail", objective="availability", target=0.9,
             scope={"route": "/q"}, window_fast_sec=5,
             window_slow_sec=20, budget_window_sec=60)


class TestBurnMath:
    def test_fast_slow_agree_under_constant_rate(self):
        """A constant error fraction reads the SAME burn on both
        windows once both are covered — the multi-window pair only
        disagrees during transients."""
        spec = SLOSpec(**AVAIL)
        _, eng, clock, fams = make_engine(
            spec, {"pio_http_requests_total": "counter"})
        ok = fams["pio_http_requests_total"].labels(route="/q",
                                                    status="200")
        bad = fams["pio_http_requests_total"].labels(route="/q",
                                                     status="500")
        for t in range(30):
            clock[0] = float(t)
            ok.inc(5)
            bad.inc(5)  # 50% errors, budget 10% → burn 5
            eng.observe()
        sp = eng.status()["specs"][0]
        assert sp["state"] == "ok"  # 5 < burn_fast default 14.4
        assert sp["burnFast"] == pytest.approx(5.0)
        assert sp["burnSlow"] == pytest.approx(5.0)

    def test_budget_exhaustion_exactly_at_configured_rate(self):
        """Burning at exactly 1× budget over the whole budget window
        leaves 0 remaining; at 0.5× it leaves half."""
        for frac, remaining in ((0.10, 0.0), (0.05, 0.5)):
            spec = SLOSpec(**AVAIL)
            _, eng, clock, fams = make_engine(
                spec, {"pio_http_requests_total": "counter"})
            ok = fams["pio_http_requests_total"].labels(route="/q",
                                                        status="200")
            bad = fams["pio_http_requests_total"].labels(route="/q",
                                                         status="503")
            for t in range(70):  # past the 60s budget window
                clock[0] = float(t)
                ok.inc(100 * (1 - frac))
                bad.inc(100 * frac)
                eng.observe()
            sp = eng.status()["specs"][0]
            assert sp["budgetRemaining"] == pytest.approx(
                remaining, abs=1e-6)

    def test_breach_transition_counts_violations_once(self):
        spec = SLOSpec(**dict(AVAIL, burn_fast=2.0, burn_slow=2.0))
        _, eng, clock, fams = make_engine(
            spec, {"pio_http_requests_total": "counter"})
        ok = fams["pio_http_requests_total"].labels(route="/q",
                                                    status="200")
        bad = fams["pio_http_requests_total"].labels(route="/q",
                                                     status="500")
        edges = []
        eng.on_transition = lambda s, b, info: edges.append(b)
        for t in range(30):
            clock[0] = float(t)
            ok.inc(10)
            eng.observe()
        assert eng.status()["specs"][0]["state"] == "ok"
        for t in range(30, 70):  # sustained 50% errors → burn 5 ≥ 2
            clock[0] = float(t)
            ok.inc(5)
            bad.inc(5)
            eng.observe()
        sp = eng.status()["specs"][0]
        assert sp["state"] == "breach"
        assert sp["violations"] == 1  # ONE transition, many ticks
        assert eng.burning() == ["avail"]
        for t in range(70, 140):  # recover
            clock[0] = float(t)
            ok.inc(10)
            eng.observe()
        sp = eng.status()["specs"][0]
        assert sp["state"] == "ok"
        assert sp["violations"] == 1
        assert edges == [True, False]

    def test_latency_objective_histogram_buckets(self):
        spec = SLOSpec(name="lat", objective="latency", target=0.9,
                       threshold_ms=100.0, burn_fast=1.5,
                       burn_slow=1.5, window_fast_sec=5,
                       window_slow_sec=20, budget_window_sec=60)
        _, eng, clock, fams = make_engine(
            spec, {"pio_query_latency_seconds": "histogram"})
        hist = fams["pio_query_latency_seconds"].labels()
        for t in range(40):
            clock[0] = float(t)
            for i in range(10):
                # 30% of samples way past the 100ms threshold:
                # budget 10% → burn 3 ≥ 1.5 on both windows
                hist.observe(0.5 if i < 3 else 0.01)
            eng.observe()
        sp = eng.status()["specs"][0]
        assert sp["state"] == "breach"
        assert sp["burnFast"] == pytest.approx(3.0, rel=0.05)
        assert sp["current"]["p99Ms"] is not None
        assert sp["current"]["badFraction"] == pytest.approx(
            0.3, rel=0.05)

    def test_label_scope_isolates_one_routes_breach(self):
        """Errors on route A breach A's spec; B's spec — same family,
        different scope — stays ok."""
        spec_a = SLOSpec(**dict(AVAIL, name="route-a",
                                scope={"route": "/a"},
                                burn_fast=2.0, burn_slow=2.0))
        spec_b = SLOSpec(**dict(AVAIL, name="route-b",
                                scope={"route": "/b"},
                                burn_fast=2.0, burn_slow=2.0))
        _, eng, clock, fams = make_engine(
            [spec_a, spec_b], {"pio_http_requests_total": "counter"})
        fam = fams["pio_http_requests_total"]
        a_ok = fam.labels(route="/a", status="200")
        a_bad = fam.labels(route="/a", status="500")
        b_ok = fam.labels(route="/b", status="200")
        for t in range(40):
            clock[0] = float(t)
            a_ok.inc(5)
            a_bad.inc(5)
            b_ok.inc(10)
            eng.observe()
        by_name = {s["name"]: s for s in eng.status()["specs"]}
        assert by_name["route-a"]["state"] == "breach"
        assert by_name["route-b"]["state"] == "ok"
        assert eng.burning() == ["route-a"]

    def test_cold_window_is_insufficient_data_not_breach(self):
        """100% errors from tick one must NOT breach while the slow
        window still reaches back past the first sample (ISSUE 15
        satellite: a cold window says nothing)."""
        spec = SLOSpec(**dict(AVAIL, burn_fast=1.0, burn_slow=1.0))
        _, eng, clock, fams = make_engine(
            spec, {"pio_http_requests_total": "counter"})
        bad = fams["pio_http_requests_total"].labels(route="/q",
                                                     status="500")
        for t in range(10):  # < window_slow_sec=20
            clock[0] = float(t)
            bad.inc(10)
            eng.observe()
        sp = eng.status()["specs"][0]
        assert sp["state"] == "insufficient_data"
        assert sp["violations"] == 0
        # burn is reported (since-start) but never acted on
        assert sp["burnFast"] == pytest.approx(10.0)
        for t in range(10, 40):  # windows now covered → breach
            clock[0] = float(t)
            bad.inc(10)
            eng.observe()
        sp = eng.status()["specs"][0]
        assert sp["state"] == "breach"
        assert sp["violations"] == 1

    def test_idle_and_missing_metric(self):
        spec = SLOSpec(**AVAIL)
        reg = MetricsRegistry()
        clock = [0.0]
        eng = SLOEngine(reg, [spec], clock=lambda: clock[0])
        for t in range(5):
            clock[0] = float(t)
            eng.observe()  # family does not exist yet
        assert eng.status()["specs"][0]["state"] == "insufficient_data"
        fam = reg.counter("pio_http_requests_total")
        fam.labels(route="/q", status="200").inc(0)
        for t in range(5, 40):
            clock[0] = float(t)
            eng.observe()
        # family exists, windows covered, zero traffic → idle
        assert eng.status()["specs"][0]["state"] == "idle"

    def test_metrics_rendering(self):
        spec = SLOSpec(**dict(AVAIL, burn_fast=2.0, burn_slow=2.0))
        reg, eng, clock, fams = make_engine(
            spec, {"pio_http_requests_total": "counter"})
        eng.register_metrics(reg)
        bad = fams["pio_http_requests_total"].labels(route="/q",
                                                     status="500")
        for t in range(40):
            clock[0] = float(t)
            bad.inc(10)
            eng.observe()
        text = reg.render()
        assert 'pio_slo_burn_rate{slo="avail",window="fast"}' in text
        assert 'pio_slo_burn_rate{slo="avail",window="slow"}' in text
        assert 'pio_slo_breach{slo="avail"} 1' in text
        assert 'pio_slo_violations_total{slo="avail"} 1' in text
        assert 'pio_slo_budget_remaining{slo="avail"} 0' in text

    def test_ticker_start_stop(self):
        import time as _time

        spec = SLOSpec(**AVAIL)
        reg = MetricsRegistry()
        reg.counter("pio_http_requests_total") \
            .labels(route="/q", status="200").inc()
        eng = SLOEngine(reg, [spec])
        eng.start(0.01)
        eng.start(0.01)  # idempotent
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline \
                and eng.status()["ticks"] < 3:
            _time.sleep(0.01)
        assert eng.status()["ticks"] >= 3
        assert eng.status()["running"]
        eng.stop()
        assert not eng.status()["running"]
        eng.stop()  # idempotent

    def test_duplicate_spec_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            SLOEngine(reg, [SLOSpec(**AVAIL), SLOSpec(**AVAIL)])


# ---------------------------------------------------------------------------
# breach → flight-recorder force-retention
# ---------------------------------------------------------------------------

class TestForceRetention:
    def test_breach_forces_trace_retention(self):
        """The QueryServer wiring in miniature: while a spec burns,
        every finished trace is retained with reason ``slo``; after
        recovery the normal tail-sampling policy resumes."""
        spec = SLOSpec(**dict(AVAIL, burn_fast=2.0, burn_slow=2.0))
        reg, eng, clock, fams = make_engine(
            spec, {"pio_http_requests_total": "counter"})
        tracer = Tracer(ring=16)

        def on_transition(s, breached, info):
            tracer.force_retention("slo" if eng.burning() else None)

        eng.on_transition = on_transition
        ok = fams["pio_http_requests_total"].labels(route="/q",
                                                    status="200")
        bad = fams["pio_http_requests_total"].labels(route="/q",
                                                     status="500")
        for t in range(30):
            clock[0] = float(t)
            ok.inc(10)
            eng.observe()
        trace = tracer.begin("healthy")
        retained, _ = tracer.finish(trace, status=200, duration=0.001)
        assert not retained  # fast + healthy → dropped
        for t in range(30, 70):
            clock[0] = float(t)
            bad.inc(10)
            eng.observe()
        assert eng.burning() == ["avail"]
        trace = tracer.begin("during-burn")
        retained, reason = tracer.finish(trace, status=200,
                                         duration=0.001)
        assert retained and reason == "slo"
        assert tracer.recorder.get(trace.trace_id) is not None
        # stronger reasons keep their specific attribution
        trace = tracer.begin("errored-during-burn")
        _, reason = tracer.finish(trace, status=500, duration=0.001)
        assert reason == "error"
        for t in range(70, 140):
            clock[0] = float(t)
            ok.inc(10)
            eng.observe()
        assert eng.burning() == []
        trace = tracer.begin("after-recovery")
        retained, _ = tracer.finish(trace, status=200, duration=0.001)
        assert not retained


# ---------------------------------------------------------------------------
# window_quantile regression battery (ISSUE 15 satellite)
# ---------------------------------------------------------------------------

class TestWindowQuantileColdWindows:
    def test_empty_window_is_none(self):
        h = StreamingHistogram([0.1, 1.0])
        h.observe(0.05)
        snap = h.bucket_counts()
        assert window_quantile(snap, snap, 0.99) is None

    def test_no_samples_at_all_is_none(self):
        h = StreamingHistogram([0.1, 1.0])
        snap = h.bucket_counts()
        assert window_quantile(snap, snap, 0.5) is None

    def test_partial_window_uses_only_the_delta(self):
        h = StreamingHistogram([0.1, 1.0, 10.0])
        for _ in range(100):
            h.observe(0.05)  # old traffic, before the window
        start = h.bucket_counts()
        for _ in range(10):
            h.observe(5.0)  # everything IN the window is slow
        q = window_quantile(start, h.bucket_counts(), 0.5)
        assert q is not None and q > 1.0  # old fast samples invisible

    def test_wrapped_window_reset_between_snapshots_is_none(self):
        """A histogram reset (rebind swapping series) makes 'now' hold
        FEWER counts than 'start' in some bucket — the delta is not a
        histogram of anything and must read as no-data, not as a
        quantile."""
        h = StreamingHistogram([0.1, 1.0])
        for _ in range(50):
            h.observe(0.05)
        start = h.bucket_counts()
        h.reset()
        for _ in range(10):
            h.observe(5.0)
        assert window_quantile(start, h.bucket_counts(), 0.5) is None

    def test_mismatched_bounds_is_none(self):
        a = StreamingHistogram([0.1, 1.0])
        b = StreamingHistogram([0.2, 2.0])
        a.observe(0.05)
        b.observe(0.05)
        assert window_quantile(a.bucket_counts(),
                               b.bucket_counts(), 0.5) is None
        c = StreamingHistogram([0.1])
        c.observe(0.05)
        assert window_quantile(c.bucket_counts(),
                               a.bucket_counts(), 0.5) is None


# ---------------------------------------------------------------------------
# the capacity gate (ratchet semantics)
# ---------------------------------------------------------------------------

CAPACITY = {
    "step_sec": 3.0,
    "configs": {
        "staged": {
            "step_sec": 3.0,
            "frontier": [{"offered_qps": 8.0}, {"offered_qps": 32.0}],
            "knee_qps": 32.0,
            "p99_at_80pct_knee_ms": 120.0,
            "freshness_under_load_ms": 800.0,
        },
    },
}


class TestCapacityGate:
    def test_pass(self):
        gates = {"staged": {"min_knee_qps": 16.0,
                            "max_p99_at_80pct_knee_ms": 500.0}}
        assert gate_capacity(CAPACITY, gates) == []

    def test_regression_names_spec_window_and_value(self):
        gates = {"staged": {"min_knee_qps": 64.0}}
        failures = gate_capacity(CAPACITY, gates)
        assert len(failures) == 1
        msg = failures[0]
        assert "staged" in msg
        assert "knee_qps 32.0" in msg          # the measured value
        assert "min_knee_qps 64.0" in msg      # the committed spec
        assert "3.0s/rate" in msg              # the window
        assert "8.0-32.0 qps" in msg

    def test_missing_config_and_missing_measurement_fail(self):
        failures = gate_capacity(
            CAPACITY, {"sharded": {"min_knee_qps": 1.0}})
        assert "no measurement" in failures[0]
        failures = gate_capacity(
            CAPACITY,
            {"staged": {"max_device_idle_fraction": 0.5}})
        assert "was not measured" in failures[0]

    def test_unknown_gate_key_fails_loud(self):
        failures = gate_capacity(
            CAPACITY, {"staged": {"min_tps": 5}})
        assert "unknown gate key" in failures[0]

    def test_ratchet_tightens_never_loosens(self):
        gates = {"staged": {"min_knee_qps": 16.0,
                            "max_p99_at_80pct_knee_ms": 100.0}}
        new, changes = ratchet_gates(CAPACITY, gates)
        # knee 32 × 0.8 = 25.6 > 16 → floor rises
        assert new["staged"]["min_knee_qps"] == pytest.approx(25.6)
        # measured p99 120 is WORSE than the committed 100 ceiling:
        # the ratchet must not loosen it
        assert new["staged"]["max_p99_at_80pct_knee_ms"] == 100.0
        assert len(changes) == 1
        new2, changes2 = ratchet_gates(CAPACITY, new)
        assert changes2 == []  # fixed point

    def test_write_gates_preserves_specs(self, tmp_path):
        path = tmp_path / "ci.json"
        path.write_text(json.dumps({
            "specs": [{"name": "a", "objective": "availability"}],
            "capacity": {"staged": {"min_knee_qps": 1.0}}}))
        write_gates(str(path), {"staged": {"min_knee_qps": 2.0}})
        specs, gates = load_specs(str(path))
        assert specs[0].name == "a"
        assert gates["staged"]["min_knee_qps"] == 2.0


# ---------------------------------------------------------------------------
# the live HTTP surface: /slo.json, /status.json block, /metrics
# ---------------------------------------------------------------------------

def _boot(tmp_path, spec_file=None):
    from datetime import datetime, timezone

    import numpy as np

    from predictionio_tpu.controller import Context
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
    )
    from predictionio_tpu.models.als import ALSModel, ALSParams
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        create_engine_server,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )

    rng = np.random.default_rng(0)
    n_users = n_items = rank = 16
    model = ALSModel(
        user_factors=rng.standard_normal(
            (n_users, rank)).astype(np.float32),
        item_factors=rng.standard_normal(
            (n_items, rank)).astype(np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "slotest"))
    ctx = Context(app_name="slotest", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="slo-test", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="slo-test", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    qs = QueryServer(
        ctx, recommendation_engine(),
        default_engine_params("slotest", rank=rank), [model], inst,
        ServerConfig(warm_start=False, slo_specs=spec_file,
                     slo_interval_ms=50.0))
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    return qs, srv


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read())


class TestHTTPSurface:
    def test_slo_json_status_block_and_metrics(self, tmp_path):
        spec_file = tmp_path / "specs.json"
        spec_file.write_text(json.dumps({"specs": [{
            "name": "smoke-latency", "objective": "latency",
            "target": 0.9, "threshold_ms": 200.0,
            "scope": {"route": "/queries.json"},
            "window_fast_sec": 0.2, "window_slow_sec": 0.5,
            "budget_window_sec": 2.0}]}))
        qs, srv = _boot(tmp_path, spec_file=str(spec_file))
        try:
            import time as _time

            for i in range(20):
                body = json.dumps({"user": f"u{i % 16}",
                                   "num": 3}).encode()
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/queries.json",
                    data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30).read()
            deadline = _time.monotonic() + 10
            payload = {}
            while _time.monotonic() < deadline:
                # keep traffic flowing: the smoke windows are so
                # short that a finished burst drains back to idle
                body = json.dumps({"user": "u1", "num": 3}).encode()
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/queries.json",
                    data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30).read()
                payload = _get(srv.port, "/slo.json")
                sp = (payload.get("specs") or [{}])[0]
                if sp.get("state") in ("ok", "breach"):
                    break
                _time.sleep(0.05)
            assert payload["enabled"] and payload["running"]
            assert payload["specs"][0]["name"] == "smoke-latency"
            assert payload["specs"][0]["state"] in ("ok", "breach")
            status = _get(srv.port, "/status.json")
            assert status["slo"]["specs"][0]["name"] == "smoke-latency"
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=30).read().decode()
            assert 'pio_slo_burn_rate{slo="smoke-latency"' in text
            assert "pio_slo_violations_total" in text
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/",
                timeout=30).read().decode()
            assert "slo.json" in page
        finally:
            qs.stop_slo()
            srv.shutdown()

    def test_default_specs_active_without_spec_file(self, tmp_path):
        qs, srv = _boot(tmp_path)
        try:
            payload = _get(srv.port, "/slo.json")
            assert payload["enabled"]
            names = {s["name"] for s in payload["specs"]}
            assert "queries-availability" in names
        finally:
            qs.stop_slo()
            srv.shutdown()

    def test_slo_disabled_reports_hint(self, tmp_path):
        """slo_interval_ms=0 turns the engine off; /slo.json and the
        status block say so instead of 404ing."""
        qs, srv = _boot(tmp_path)
        try:
            # a server without an engine (slo_interval_ms=0 leaves
            # qs.slo as None) reports disabled with the enable hint
            qs.stop_slo()
            qs.slo = None
            payload = _get(srv.port, "/slo.json")
            assert payload["enabled"] is False
            assert "hint" in payload
        finally:
            srv.shutdown()


class TestDeployFlagSync:
    def test_cli_deploy_flags_cover_slo_config(self):
        """`ptpu deploy --slo-specs/--slo-interval-ms` defaults must
        track ServerConfig's (the pattern the trace/stream flags
        follow)."""
        from predictionio_tpu.cli import build_parser
        from predictionio_tpu.server.engineserver import ServerConfig

        args = build_parser().parse_args(["deploy"])
        cfg = ServerConfig()
        assert (args.slo_specs or None) == cfg.slo_specs
        assert args.slo_interval_ms == cfg.slo_interval_ms

    def test_slo_check_cli(self, tmp_path, capsys):
        from predictionio_tpu.cli import main as cli_main

        cap = tmp_path / "CAPACITY.json"
        cap.write_text(json.dumps(CAPACITY))
        specs = tmp_path / "ci.json"
        specs.write_text(json.dumps({
            "specs": [{"name": "a", "objective": "availability"}],
            "capacity": {"staged": {"min_knee_qps": 16.0}}}))
        rc = cli_main(["slo", "check", "--capacity", str(cap),
                       "--specs", str(specs)], storage=object())
        assert rc == 0
        assert "PASS" in capsys.readouterr().out
        specs.write_text(json.dumps({
            "specs": [{"name": "a", "objective": "availability"}],
            "capacity": {"staged": {"min_knee_qps": 64.0}}}))
        rc = cli_main(["slo", "check", "--capacity", str(cap),
                       "--specs", str(specs)], storage=object())
        assert rc == 1
        err = capsys.readouterr().err
        assert "knee_qps 32.0" in err and "64.0" in err

    def test_slo_check_update_ratchets(self, tmp_path, capsys):
        from predictionio_tpu.cli import main as cli_main

        cap = tmp_path / "CAPACITY.json"
        cap.write_text(json.dumps(CAPACITY))
        specs = tmp_path / "ci.json"
        specs.write_text(json.dumps({
            "specs": [{"name": "a", "objective": "availability"}],
            "capacity": {"staged": {"min_knee_qps": 16.0}}}))
        rc = cli_main(["slo", "check", "--capacity", str(cap),
                       "--specs", str(specs), "--update"],
                      storage=object())
        assert rc == 0
        _, gates = load_specs(str(specs))
        assert gates["staged"]["min_knee_qps"] == pytest.approx(25.6)
