"""Fused gather→score→top-k Pallas serving kernel (ISSUE 13), run in
interpret mode on CPU so tier-1 covers it without a TPU: exactness
against the ``_serve_topk`` einsum reference on f32 and tolerance on
the bf16/int8 quantized wires, ragged B tails and non-chunk-multiple
catalogs, the global-id ``base`` contract the sharded ranker relies
on, routing parity through every serving mode (single / replicated
lanes / sharded on the 8-device CPU mesh), the staged pipeline end to
end, and the autotune table's support-gated einsum fallback."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.models import als
from predictionio_tpu.models.als import (
    ALSModel,
    ALSParams,
    QuantizedFactors,
    quantize_serving_model,
    recommend_batch,
    recommend_pinned,
    recommend_products,
    resolved_topk_mode,
    set_serving_topk_mode,
)
from predictionio_tpu.ops.fused_topk import (
    TOPK_MAX_K,
    fused_topk,
    fused_topk_dispatch,
    fused_topk_reference,
    fused_topk_supported,
    fused_topk_vmem_bytes,
)


@pytest.fixture(autouse=True)
def _reset_topk_mode():
    yield
    set_serving_topk_mode(None)


def make_tables(m=120, I=200, r=16, seed=0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(m, r)).astype(np.float32)
    V = rng.normal(size=(I, r)).astype(np.float32)
    return U, V


def quantize(arr):
    amax = np.abs(arr).max(axis=1, keepdims=True)
    scale = np.maximum(amax, 1e-12).astype(np.float32) / 127.0
    data = np.clip(np.rint(arr / scale), -127, 127).astype(np.int8)
    return data, scale


class TestKernelInterpret:
    def test_f32_exact_vs_serve_topk(self):
        """f32 kernel vs the einsum serving program — ids EXACT, same
        tie semantics (descending score, lowest id first)."""
        U, V = make_tables()
        idx = np.random.default_rng(1).integers(0, U.shape[0], 24)
        s, i = fused_topk(jnp.asarray(U), jnp.asarray(idx.astype(np.int32)),
                          jnp.asarray(V), k=10, n_items=V.shape[0],
                          chunk=64, interpret=True)
        s_ref, i_ref = als._serve_topk(jnp.asarray(U), jnp.asarray(V),
                                       idx, k=10, n_items=V.shape[0])
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(s), np.asarray(s_ref),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("B,I,k", [(1, 33, 8), (13, 97, 10),
                                       (7, 512, 16), (19, 130, 1)])
    def test_ragged_tails(self, B, I, k):
        """B not a block multiple, catalog not a chunk multiple: the
        internal pad-and-slice must be invisible."""
        U, V = make_tables(I=I, seed=B * 31 + I)
        idx = np.random.default_rng(B).integers(
            0, U.shape[0], B).astype(np.int32)
        s, i = fused_topk(jnp.asarray(U), jnp.asarray(idx),
                          jnp.asarray(V), k=k, n_items=I, chunk=32,
                          interpret=True)
        s_ref, i_ref = fused_topk_reference(
            jnp.asarray(U), jnp.asarray(idx), jnp.asarray(V),
            k=k, n_items=I)
        assert s.shape == (B, k) and i.shape == (B, k)
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(s), np.asarray(s_ref),
                                   rtol=1e-6, atol=1e-6)

    def test_padded_items_masked(self):
        """n_items below the padded catalog: padding rows never appear
        in the result (the -inf mask parity with _serve_topk)."""
        U, V = make_tables(I=140)
        idx = np.arange(8, dtype=np.int32)
        s, i = fused_topk(jnp.asarray(U), jnp.asarray(idx),
                          jnp.asarray(V), k=12, n_items=100, chunk=64,
                          interpret=True)
        assert np.asarray(i).max() < 100

    def test_int8_wire_matches_dequant_reference(self):
        """int8 rows + per-row scales on the wire: must match the
        dequantized reference tightly — the f32-accumulation
        contract, not a new quality budget."""
        U, V = make_tables(seed=3)
        Uq, us = quantize(U)
        Vq, vs = quantize(V)
        idx = np.random.default_rng(3).integers(
            0, U.shape[0], 15).astype(np.int32)
        s, i = fused_topk(jnp.asarray(Uq), jnp.asarray(idx),
                          jnp.asarray(Vq), jnp.asarray(us),
                          jnp.asarray(vs), k=10, n_items=V.shape[0],
                          chunk=64, interpret=True)
        s_ref, i_ref = fused_topk_reference(
            jnp.asarray(Uq), jnp.asarray(idx), jnp.asarray(Vq),
            jnp.asarray(us), jnp.asarray(vs), k=10,
            n_items=V.shape[0])
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(s), np.asarray(s_ref),
                                   rtol=1e-5, atol=1e-5)
        # and against the f32 truth only quantization error
        _, i_f32 = fused_topk_reference(
            jnp.asarray(U), jnp.asarray(idx), jnp.asarray(V),
            k=10, n_items=V.shape[0])
        overlap = np.mean([len(set(a.tolist()) & set(b.tolist())) / 10
                           for a, b in zip(np.asarray(i),
                                           np.asarray(i_f32))])
        assert overlap >= 0.8

    def test_bf16_wire(self):
        U, V = make_tables(seed=4)
        idx = np.arange(9, dtype=np.int32)
        U16 = jnp.asarray(U).astype(jnp.bfloat16)
        V16 = jnp.asarray(V).astype(jnp.bfloat16)
        s, i = fused_topk(U16, jnp.asarray(idx), V16, k=10,
                          n_items=V.shape[0], chunk=64, interpret=True)
        s_ref, i_ref = fused_topk_reference(U16, jnp.asarray(idx), V16,
                                            k=10, n_items=V.shape[0])
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))

    def test_base_offsets_global_ids(self):
        """The sharded ranker's contract: ids come back offset by
        ``base`` and the n_items mask applies to GLOBAL ids."""
        U, V = make_tables(I=64)
        idx = np.arange(4, dtype=np.int32)
        s, i = fused_topk(jnp.asarray(U), jnp.asarray(idx),
                          jnp.asarray(V), base=jnp.asarray(1000),
                          k=8, n_items=1060, chunk=32, interpret=True)
        arr = np.asarray(i)
        assert arr.min() >= 1000
        assert arr.max() < 1060  # global ids 1060..1063 are masked

    def test_dispatch_runs_kernel_on_cpu(self):
        """No TPU attached → dispatch runs the interpret-mode kernel
        (the debugging contract), not the reference fallback."""
        assert not fused_topk_supported()  # CPU host
        U, V = make_tables()
        idx = np.arange(5, dtype=np.int32)
        s, i = fused_topk_dispatch(jnp.asarray(U), jnp.asarray(idx),
                                   jnp.asarray(V), k=8,
                                   n_items=V.shape[0])
        _, i_ref = fused_topk_reference(jnp.asarray(U),
                                        jnp.asarray(idx),
                                        jnp.asarray(V), k=8,
                                        n_items=V.shape[0])
        assert np.array_equal(np.asarray(i), np.asarray(i_ref))

    def test_vmem_budget_math(self):
        # the chunked sweep caps the working set however large the
        # catalog grows; quantized wires shrink the dominant term
        assert fused_topk_vmem_bytes(128, 128, wire_bytes=1) \
            < fused_topk_vmem_bytes(128, 128, wire_bytes=4)
        # r=128 f32 double-buffered item tile alone is 512 KiB
        assert fused_topk_vmem_bytes(128, 16) > 2 * 512 * 128 * 4
        # the trace-time assert mirrors this bound
        assert fused_topk_vmem_bytes(128, TOPK_MAX_K) \
            < 16 * 1024 * 1024

    def test_k_over_budget_rejected(self):
        U, V = make_tables()
        with pytest.raises(AssertionError, match="fused_topk"):
            fused_topk(jnp.asarray(U),
                       jnp.asarray(np.arange(4, dtype=np.int32)),
                       jnp.asarray(V), k=TOPK_MAX_K * 2,
                       n_items=V.shape[0], interpret=True)


class TestServingRoutes:
    """`_device_topk` routing: with the process override pinned to
    "fused", every serving entry answers identically to the einsum
    lane — the switch must be invisible."""

    def _model(self, quant=None, r=16, nu=150, ni=180, seed=0):
        U, V = make_tables(m=nu, I=ni, r=r, seed=seed)
        m = ALSModel(
            user_factors=jax.device_put(U),
            item_factors=jax.device_put(V), n_users=nu, n_items=ni,
            user_ids=BiMap({f"u{i}": i for i in range(nu)}),
            item_ids=BiMap({f"i{i}": i for i in range(ni)}),
            params=ALSParams(rank=r))
        if quant:
            m = quantize_serving_model(m, quant)
        return m

    @pytest.mark.parametrize("quant", [None, "int8", "bf16"])
    def test_recommend_batch_parity(self, quant):
        m = self._model(quant)
        set_serving_topk_mode("einsum")
        ids_e, s_e = recommend_batch(m, np.arange(20), 10)
        set_serving_topk_mode("fused")
        ids_f, s_f = recommend_batch(m, np.arange(20), 10)
        assert np.array_equal(ids_e, ids_f)
        np.testing.assert_allclose(s_e, s_f, rtol=1e-5, atol=1e-5)

    def test_recommend_products_and_pinned_parity(self):
        m = self._model("int8")
        set_serving_topk_mode("fused")
        ids_1, _ = recommend_products(m, 7, 10)
        pinned, nbytes = als.pin_user_rows(m, [7], 1)
        assert isinstance(pinned, QuantizedFactors)  # hot tier stays
        assert nbytes > 0                            # quantized
        ids_2, _ = recommend_pinned(m, pinned, 0, 10)
        set_serving_topk_mode("einsum")
        ids_3, _ = recommend_products(m, 7, 10)
        assert np.array_equal(ids_1, ids_2)
        assert np.array_equal(ids_1, ids_3)

    def test_large_k_falls_back_to_einsum(self):
        """k past the on-chip merge budget must route to einsum, not
        assert inside the kernel."""
        m = self._model(ni=600)
        set_serving_topk_mode("fused")
        ids, scores = recommend_batch(m, np.arange(4), 400)
        set_serving_topk_mode("einsum")
        ids_e, _ = recommend_batch(m, np.arange(4), 400)
        assert np.array_equal(ids, ids_e)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the forced-8-device CPU mesh")
class TestShardedParity:
    """The sharded collective ranking picks up the kernel per shard
    (base-offset local top-k) and answers identically."""

    def _model(self, quant=None):
        rng = np.random.default_rng(7)
        nu, ni, r = 64, 56, 8
        U = rng.normal(size=(nu, r)).astype(np.float32)
        V = rng.normal(size=(ni, r)).astype(np.float32)
        m = ALSModel(
            user_factors=U, item_factors=V, n_users=nu, n_items=ni,
            user_ids=BiMap({f"u{i}": i for i in range(nu)}),
            item_ids=BiMap({f"i{i}": i for i in range(ni)}),
            params=ALSParams(rank=r))
        if quant:
            m = quantize_serving_model(m, quant)
        return m

    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_sharded_fused_matches_single_einsum(self, quant):
        from predictionio_tpu.models.als import shard_model
        from predictionio_tpu.parallel.mesh import make_serving_mesh

        m = self._model(quant)
        set_serving_topk_mode("einsum")
        ids_e, _ = recommend_batch(m, np.arange(12), 10)
        sm = shard_model(m, make_serving_mesh())
        set_serving_topk_mode("fused")
        ids_f, _ = recommend_batch(sm, np.arange(12), 10)
        assert np.array_equal(ids_e, ids_f)

    def test_pinned_sharded_fused(self):
        from predictionio_tpu.models.als import (
            pin_user_rows,
            shard_model,
        )
        from predictionio_tpu.parallel.mesh import make_serving_mesh

        m = self._model("int8")
        sm = shard_model(m, make_serving_mesh())
        set_serving_topk_mode("fused")
        pinned, _ = pin_user_rows(sm, [3, 5], 2)
        ids_p, _ = recommend_pinned(sm, pinned, 1, 10)
        set_serving_topk_mode("einsum")
        ids_e, _ = recommend_products(sm, 5, 10)
        assert np.array_equal(ids_p, ids_e)


class TestStagedPipelineEndToEnd:
    """serving_quant=int8 + serving_topk=fused through the REAL staged
    pipeline (QueryServer + batcher) answers exactly like the einsum
    lane on the same quantized tables — acceptance criterion."""

    def _boot(self, topk):
        from datetime import datetime, timezone

        from predictionio_tpu.controller import Context
        from predictionio_tpu.data.storage import App, Storage
        from predictionio_tpu.data.storage.base import (
            STATUS_COMPLETED,
            EngineInstance,
        )
        from predictionio_tpu.server.engineserver import (
            QueryServer,
            ServerConfig,
        )
        from predictionio_tpu.templates.recommendation import (
            default_engine_params,
            recommendation_engine,
        )

        rng = np.random.default_rng(11)
        nu, ni, r = 200, 160, 16
        model = ALSModel(
            user_factors=rng.standard_normal((nu, r)).astype(np.float32),
            item_factors=rng.standard_normal((ni, r)).astype(np.float32),
            n_users=nu, n_items=ni,
            user_ids=BiMap({f"u{i}": i for i in range(nu)}),
            item_ids=BiMap({f"i{i}": i for i in range(ni)}),
            params=ALSParams(rank=r))
        storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
        storage.apps().insert(App(0, "ft"))
        ctx = Context(app_name="ft", _storage=storage)
        now = datetime.now(timezone.utc)
        inst = EngineInstance(
            id="ft", status=STATUS_COMPLETED, start_time=now,
            end_time=now, engine_id="ft", engine_version="1",
            engine_variant="e.json", engine_factory="s")
        cfg = ServerConfig(batching=True, serving_pipeline="staged",
                           warm_start=False, serving_quant="int8",
                           serving_topk=topk)
        return QueryServer(ctx, recommendation_engine(),
                           default_engine_params("ft", rank=r),
                           [model], inst, cfg)

    def test_fused_pipeline_matches_einsum(self):
        import concurrent.futures as cf

        try:
            qs_f = self._boot("fused")
            answers_f = {}
            with cf.ThreadPoolExecutor(8) as pool:
                futs = {u: pool.submit(qs_f.serve,
                                       {"user": f"u{u}", "num": 10})
                        for u in range(24)}
                for u, f in futs.items():
                    answers_f[u] = f.result(timeout=120)
            qs_e = self._boot("einsum")
            for u in range(24):
                expect = qs_e.serve({"user": f"u{u}", "num": 10})
                got = answers_f[u]
                assert [s["item"] for s in got["itemScores"]] \
                    == [s["item"] for s in expect["itemScores"]]
        finally:
            set_serving_topk_mode(None)


class TestTopkAutotune:
    """Satellite: the gram_autotune-style serving top-k mode table —
    support-gated exactly like best_mode."""

    def test_fused_entry_falls_back_on_cpu(self, tmp_path, monkeypatch):
        from predictionio_tpu.ops import gram_autotune as ga

        cache = tmp_path / "gram_autotune.json"
        cache.write_text(json.dumps(
            {"cpu|topk|r64|f32": {"mode": "fused", "source": "test"}}))
        monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE", str(cache))
        ga.reset_for_tests()
        try:
            assert not fused_topk_supported()  # no TPU here
            assert ga.best_topk_mode(64, device_kind="cpu") == "einsum"
        finally:
            ga.reset_for_tests()

    def test_einsum_entry_honored(self, tmp_path, monkeypatch):
        from predictionio_tpu.ops import gram_autotune as ga

        cache = tmp_path / "gram_autotune.json"
        cache.write_text(json.dumps(
            {"TPU v5 lite|topk|r64|int8": {"mode": "einsum",
                                           "source": "test"}}))
        monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE", str(cache))
        ga.reset_for_tests()
        try:
            assert ga.best_topk_mode(
                64, "int8", device_kind="TPU v5 lite0") == "einsum"
        finally:
            ga.reset_for_tests()

    def test_recordable(self, tmp_path, monkeypatch):
        from predictionio_tpu.ops import gram_autotune as ga

        cache = tmp_path / "gram_autotune.json"
        monkeypatch.setenv("PIO_GRAM_AUTOTUNE_CACHE", str(cache))
        ga.reset_for_tests()
        try:
            assert ga.record_topk(64, "fused", "int8",
                                  device_kind="TPU v5 lite0",
                                  measured={"source": "serving_bench"})
            saved = json.loads(cache.read_text())
            assert saved["TPU v5 lite|topk|r64|int8"]["mode"] == "fused"
            assert not ga.record_topk(64, "bogus", "int8",
                                      device_kind="TPU v5 lite0")
        finally:
            ga.reset_for_tests()

    def test_defaults_carry_fused_for_all_quants(self):
        from predictionio_tpu.ops.gram_autotune import _DEFAULTS_PATH

        table = json.loads(open(_DEFAULTS_PATH).read())
        for r in (32, 64, 128):
            for q in ("f32", "bf16", "int8"):
                assert table[f"TPU v5 lite|topk|r{r}|{q}"]["mode"] \
                    == "fused"

    def test_resolved_topk_mode_override_and_validation(self):
        set_serving_topk_mode("fused")
        assert resolved_topk_mode(64, "int8") == "fused"
        set_serving_topk_mode("auto")
        assert resolved_topk_mode(64, "off") == "einsum"  # CPU host
        with pytest.raises(ValueError, match="serving topk"):
            set_serving_topk_mode("fusion")
