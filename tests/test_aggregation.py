"""Property-aggregation algebra tests.

Scenario parity with the reference's `LEventAggregatorSpec.scala` and
`PEventAggregatorSpec.scala`, using the same fixture event sequences as
`data/src/test/.../storage/TestEvents.scala` (u1/u2 event streams, shuffled
order, delete-in-the-middle).
"""

from datetime import datetime, timedelta, timezone

from predictionio_tpu.data import (
    DataMap,
    Event,
    aggregate_properties,
    aggregate_properties_ordered,
    aggregate_properties_single,
)
from predictionio_tpu.data.aggregation import (
    merge_aggregates,
    partial_aggregate,
)


def dt(ms):
    return datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)


U1_BASE = dt(654321)
U2_BASE = dt(6543210)
DAY = timedelta(days=1)


def set_ev(eid, props, t):
    return Event(event="$set", entity_type="user", entity_id=eid,
                 properties=DataMap(props), event_time=t)


def unset_ev(eid, keys, t):
    return Event(event="$unset", entity_type="user", entity_id=eid,
                 properties=DataMap({k: None for k in keys}), event_time=t)


def delete_ev(eid, t):
    return Event(event="$delete", entity_type="user", entity_id=eid,
                 event_time=t)


# fixture streams from TestEvents.scala
u1e1 = set_ev("u1", {"a": 1, "b": "value2", "d": [1, 2, 3]}, U1_BASE)
u1e2 = set_ev("u1", {"a": 2}, U1_BASE + 1 * DAY)
u1e3 = set_ev("u1", {"b": "value4"}, U1_BASE + 2 * DAY)
u1e4 = unset_ev("u1", ["b"], U1_BASE + 3 * DAY)
u1e5 = set_ev("u1", {"e": "new"}, U1_BASE + 4 * DAY)
u1ed = delete_ev("u1", U1_BASE + 5 * DAY)
U1_EXPECTED = {"a": 2, "d": [1, 2, 3], "e": "new"}
U1_LAST = U1_BASE + 4 * DAY

u2e1 = set_ev("u2", {"a": 21, "b": "value12", "d": [7, 5, 6]}, U2_BASE)
u2e2 = unset_ev("u2", ["a"], U2_BASE + 1 * DAY)
u2e3 = set_ev("u2", {"b": "value9", "g": "new11"}, U2_BASE + 2 * DAY)
U2_EXPECTED = {"b": "value9", "d": [7, 5, 6], "g": "new11"}
U2_LAST = U2_BASE + 2 * DAY

SHUFFLED = [u1e5, u2e2, u1e3, u1e1, u2e3, u2e1, u1e4, u1e2]


class TestMonoidAggregation:
    def test_two_entities(self):
        result = aggregate_properties(SHUFFLED)
        assert set(result.keys()) == {"u1", "u2"}
        assert result["u1"].to_dict() == U1_EXPECTED
        assert result["u2"].to_dict() == U2_EXPECTED
        assert result["u1"].first_updated == U1_BASE
        assert result["u1"].last_updated == U1_LAST
        assert result["u2"].first_updated == U2_BASE
        assert result["u2"].last_updated == U2_LAST

    def test_deleted_entity_dropped(self):
        events = [u1e5, u2e2, u1e3, u1ed, u1e1, u2e3, u2e1, u1e4, u1e2]
        result = aggregate_properties(events)
        assert set(result.keys()) == {"u2"}
        assert result["u2"].to_dict() == U2_EXPECTED

    def test_set_after_delete_recreates(self):
        revive = set_ev("u1", {"z": 9}, U1_BASE + 6 * DAY)
        result = aggregate_properties([u1e1, u1ed, revive])
        assert result["u1"].to_dict() == {"z": 9}

    def test_order_insensitive(self):
        import itertools
        events = [u1e1, u1e2, u1e4, u1e3]
        expected = aggregate_properties(events)["u1"].to_dict()
        for perm in itertools.permutations(events):
            assert aggregate_properties(list(perm))["u1"].to_dict() == expected

    def test_shard_merge_matches_global(self):
        # split the shuffled stream across 3 "hosts", aggregate independently,
        # merge — must equal the global aggregate (aggregateByKey semantics)
        shards = [SHUFFLED[0::3], SHUFFLED[1::3], SHUFFLED[2::3]]
        partials = [partial_aggregate(s) for s in shards]
        merged = partials[0]
        for p in partials[1:]:
            merged = merge_aggregates(merged, p)
        out = {k: op.to_property_map() for k, op in merged.items()}
        out = {k: v for k, v in out.items() if v is not None}
        glob = aggregate_properties(SHUFFLED)
        assert {k: v.to_dict() for k, v in out.items()} == \
               {k: v.to_dict() for k, v in glob.items()}

    def test_unset_only_entity_absent(self):
        result = aggregate_properties([unset_ev("ux", ["a"], U1_BASE)])
        assert result == {}

    def test_unset_before_set_keeps_field(self):
        # unset strictly before the set time does not remove the field
        events = [unset_ev("u", ["a"], U1_BASE),
                  set_ev("u", {"a": 5}, U1_BASE + DAY)]
        assert aggregate_properties(events)["u"].to_dict() == {"a": 5}

    def test_unset_at_same_time_removes(self):
        events = [set_ev("u", {"a": 5}, U1_BASE),
                  unset_ev("u", ["a"], U1_BASE)]
        assert aggregate_properties(events)["u"].to_dict() == {}


class TestOrderedAggregation:
    def test_two_entities(self):
        result = aggregate_properties_ordered(SHUFFLED)
        assert result["u1"].to_dict() == U1_EXPECTED
        assert result["u2"].to_dict() == U2_EXPECTED
        assert result["u1"].first_updated == U1_BASE
        assert result["u1"].last_updated == U1_LAST

    def test_single_entity(self):
        pm = aggregate_properties_single([u1e5, u1e3, u1e1, u1e4, u1e2])
        assert pm is not None
        assert pm.to_dict() == U1_EXPECTED
        assert pm.first_updated == U1_BASE
        assert pm.last_updated == U1_LAST

    def test_delete_in_middle_of_unsorted_stream(self):
        # LEventAggregatorSpec: delete event placed mid-stream still wins
        # because fold is over time-sorted events
        pm = aggregate_properties_single([u1e4, u1e2, u1ed, u1e3, u1e1, u1e5])
        assert pm is None

    def test_non_special_events_ignored(self):
        rate = Event(event="rate", entity_type="user", entity_id="u1",
                     target_entity_type="item", target_entity_id="i1",
                     properties=DataMap({"rating": 5}),
                     event_time=U1_BASE + 9 * DAY)
        pm = aggregate_properties_single([u1e1, rate])
        assert pm is not None
        assert pm.to_dict() == {"a": 1, "b": "value2", "d": [1, 2, 3]}
        assert pm.last_updated == U1_BASE  # rate event doesn't touch updated times


class TestMonoidVsOrderedParity:
    def test_same_result_on_fixture_streams(self):
        m = aggregate_properties(SHUFFLED)
        o = aggregate_properties_ordered(SHUFFLED)
        assert {k: v.to_dict() for k, v in m.items()} == \
               {k: v.to_dict() for k, v in o.items()}
