"""Classification, similar-product, and e-commerce template tests
(SURVEY §2.2 parity: the behaviors the reference templates exercise)."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.registry import set_storage

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)

MEM_ENV = {
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
}


def make_ctx(app_name: str, events) -> Context:
    storage = Storage(env=MEM_ENV)
    app_id = storage.apps().insert(App(0, app_name))
    storage.events().init(app_id)
    storage.events().insert_batch(list(events), app_id)
    return Context(app_name=app_name, _storage=storage)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def classification_events():
    """Users whose plan is determined by attr0 vs attr2 dominance."""
    rng = np.random.default_rng(7)
    events = []
    for u in range(60):
        plan = float(u % 2)
        if plan == 0.0:
            attrs = [rng.integers(5, 10), rng.integers(0, 3),
                     rng.integers(0, 3)]
        else:
            attrs = [rng.integers(0, 3), rng.integers(0, 3),
                     rng.integers(5, 10)]
        events.append(Event(
            event="$set", entity_type="user", entity_id=f"u{u}",
            properties=DataMap({"plan": plan,
                                "attr0": float(attrs[0]),
                                "attr1": float(attrs[1]),
                                "attr2": float(attrs[2])}),
            event_time=T0 + timedelta(minutes=u)))
    return events


@pytest.fixture(scope="module")
def cls_ctx():
    return make_ctx("clsapp", classification_events())


class TestClassificationTemplate:
    def test_naive_bayes_lifecycle(self, cls_ctx):
        from predictionio_tpu.templates.classification import (
            Query, classification_engine, default_engine_params)

        engine = classification_engine()
        ep = default_engine_params("clsapp", algo="naive")
        result = engine.train(cls_ctx, ep)
        algo = engine.make_algorithms(ep)[0]
        # strongly attr0-dominant → plan 0; attr2-dominant → plan 1
        assert algo.predict(result.models[0],
                            Query(8.0, 1.0, 0.0)).label == 0.0
        assert algo.predict(result.models[0],
                            Query(0.0, 1.0, 8.0)).label == 1.0

    def test_random_forest_lifecycle(self, cls_ctx):
        from predictionio_tpu.templates.classification import (
            Query, classification_engine, default_engine_params)

        engine = classification_engine()
        ep = default_engine_params("clsapp", algo="randomforest",
                                   num_classes=2, num_trees=8, max_depth=4,
                                   seed=3)
        result = engine.train(cls_ctx, ep)
        algo = engine.make_algorithms(ep)[0]
        assert algo.predict(result.models[0],
                            Query(8.0, 1.0, 0.0)).label == 0.0
        assert algo.predict(result.models[0],
                            Query(0.0, 1.0, 8.0)).label == 1.0

    def test_batch_predict_matches_single(self, cls_ctx):
        from predictionio_tpu.templates.classification import (
            Query, classification_engine, default_engine_params)

        engine = classification_engine()
        ep = default_engine_params("clsapp", algo="randomforest",
                                   num_classes=2, num_trees=5, seed=1)
        model = engine.train(cls_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        queries = [Query(8.0, 1.0, 0.0), Query(0.0, 0.0, 7.0),
                   Query(6.0, 2.0, 1.0)]
        batch = algo.batch_predict(model, queries)
        single = [algo.predict(model, q) for q in queries]
        assert [b.label for b in batch] == [s.label for s in single]

    def test_eval_kfold_accuracy(self, cls_ctx):
        from predictionio_tpu.controller import Evaluation
        from predictionio_tpu.templates.classification import (
            Accuracy, DataSourceParams, NaiveBayesParams,
            classification_engine)
        from predictionio_tpu.controller.params import EngineParams
        from predictionio_tpu.workflow import run_evaluation

        engine = classification_engine()
        ep = EngineParams(
            datasource=("", DataSourceParams(app_name="clsapp", eval_k=3)),
            algorithms=[("naive", NaiveBayesParams())])
        evaluation = Evaluation(engine=engine, metric=Accuracy())
        result = run_evaluation(cls_ctx, evaluation, [ep])
        assert result.best_score > 0.8  # separable by construction

    def test_model_pickles(self, cls_ctx):
        import pickle

        from predictionio_tpu.templates.classification import (
            Query, classification_engine, default_engine_params)

        engine = classification_engine()
        ep = default_engine_params("clsapp", algo="naive")
        model = engine.train(cls_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        algo.batch_predict(model, [Query(8.0, 1.0, 0.0)])  # warm jit cache
        clone = pickle.loads(pickle.dumps(model))
        assert algo.predict(clone, Query(8.0, 1.0, 0.0)).label == 0.0


# ---------------------------------------------------------------------------
# similar product
# ---------------------------------------------------------------------------

def similarproduct_events():
    """Two disjoint view communities + like/dislike signals; items carry
    categories c0 (items 0-9) / c1 (items 10-19)."""
    rng = np.random.default_rng(11)
    events = []
    for u in range(30):
        events.append(Event(event="$set", entity_type="user",
                            entity_id=f"u{u}", event_time=T0))
    for i in range(20):
        events.append(Event(
            event="$set", entity_type="item", entity_id=f"i{i}",
            properties=DataMap(
                {"categories": ["c0" if i < 10 else "c1"]}),
            event_time=T0))
    t = T0
    for u in range(30):
        pool = range(0, 10) if u % 2 == 0 else range(10, 20)
        for i in rng.choice(list(pool), size=6, replace=False):
            events.append(Event(
                event="view", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                event_time=t))
            t += timedelta(seconds=30)
        # like one in-pool item; dislike one out-of-pool item
        events.append(Event(
            event="like", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item",
            target_entity_id=f"i{rng.choice(list(pool))}", event_time=t))
        other = range(10, 20) if u % 2 == 0 else range(0, 10)
        events.append(Event(
            event="dislike", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item",
            target_entity_id=f"i{rng.choice(list(other))}", event_time=t))
        t += timedelta(seconds=30)
    return events


@pytest.fixture(scope="module")
def sp_ctx():
    return make_ctx("spapp", similarproduct_events())


class TestSimilarProductTemplate:
    def _train(self, ctx, algo_name, params=None):
        from predictionio_tpu.controller.params import EngineParams
        from predictionio_tpu.models.als import ALSParams
        from predictionio_tpu.templates.similarproduct import (
            CooccurrenceParams, DataSourceParams, similarproduct_engine)

        engine = similarproduct_engine()
        if params is None:
            params = (CooccurrenceParams() if algo_name == "cooccurrence"
                      else ALSParams(rank=8, num_iterations=10,
                                     implicit_prefs=True, alpha=1.0, seed=5))
        ep = EngineParams(
            datasource=("", DataSourceParams(app_name="spapp")),
            algorithms=[(algo_name, params)])
        result = engine.train(ctx, ep)
        return engine, ep, result.models[0]

    def test_als_similar_items_stay_in_community(self, sp_ctx):
        from predictionio_tpu.templates.similarproduct import Query

        engine, ep, model = self._train(sp_ctx, "als")
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(items=["i0"], num=5))
        assert pred.item_scores
        top = [int(s.item[1:]) for s in pred.item_scores]
        assert "i0" not in [s.item for s in pred.item_scores]
        in_comm = sum(1 for i in top if i < 10)
        assert in_comm >= 3, f"community leak: {top}"

    def test_cooccurrence_counts(self, sp_ctx):
        from predictionio_tpu.templates.similarproduct import Query

        engine, ep, model = self._train(sp_ctx, "cooccurrence")
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(items=["i0"], num=5))
        assert pred.item_scores
        # co-occurrence can only surface same-community items
        assert all(int(s.item[1:]) < 10 for s in pred.item_scores)
        scores = [s.score for s in pred.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_like_algorithm(self, sp_ctx):
        from predictionio_tpu.templates.similarproduct import Query

        engine, ep, model = self._train(sp_ctx, "likealgo")
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(items=["i1"], num=5))
        assert pred.item_scores  # ±1 signal still yields neighbors

    def test_filters(self, sp_ctx):
        from predictionio_tpu.templates.similarproduct import Query

        engine, ep, model = self._train(sp_ctx, "cooccurrence")
        algo = engine.make_algorithms(ep)[0]
        white = algo.predict(model, Query(
            items=["i0"], num=10, white_list=["i2", "i4"]))
        assert {s.item for s in white.item_scores} <= {"i2", "i4"}
        black = algo.predict(model, Query(
            items=["i0"], num=10, black_list=["i2"]))
        assert "i2" not in {s.item for s in black.item_scores}
        cat = algo.predict(model, Query(
            items=["i0"], num=10, categories=["c1"]))
        assert all(int(s.item[1:]) >= 10 for s in cat.item_scores) \
            or not cat.item_scores
        catbl = algo.predict(model, Query(
            items=["i0"], num=10, category_black_list=["c0"]))
        assert all(int(s.item[1:]) >= 10 for s in catbl.item_scores) \
            or not catbl.item_scores

    def test_serving_standardizes_and_combines(self, sp_ctx):
        from predictionio_tpu.templates.similarproduct import (
            ItemScore, PredictedResult, Query, SimilarProductServing)

        serving = SimilarProductServing()
        a = PredictedResult((ItemScore("i1", 100.0), ItemScore("i2", 50.0)))
        b = PredictedResult((ItemScore("i1", 0.9), ItemScore("i3", 0.1)))
        out = serving.serve(Query(items=["i9"], num=3), [a, b])
        items = [s.item for s in out.item_scores]
        assert items[0] == "i1"  # ranked first by both algorithms
        assert set(items) <= {"i1", "i2", "i3"}
        # raw magnitudes must not dominate: z-scores are scale-free
        assert out.item_scores[0].score == pytest.approx(
            0.7071067 + 0.7071067, rel=1e-4)


# ---------------------------------------------------------------------------
# e-commerce
# ---------------------------------------------------------------------------

def ecommerce_events():
    rng = np.random.default_rng(23)
    events = []
    for u in range(20):
        events.append(Event(event="$set", entity_type="user",
                            entity_id=f"u{u}", event_time=T0))
    for i in range(12):
        events.append(Event(
            event="$set", entity_type="item", entity_id=f"i{i}",
            properties=DataMap({"categories": ["c0" if i < 6 else "c1"]}),
            event_time=T0))
    t = T0
    for u in range(20):
        pool = range(0, 6) if u % 2 == 0 else range(6, 12)
        for i in rng.choice(list(pool), size=4, replace=False):
            events.append(Event(
                event="view", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                event_time=t))
            t += timedelta(seconds=10)
    # i3 is the most-bought item
    for u in range(10):
        events.append(Event(
            event="buy", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item", target_entity_id="i3",
            event_time=t))
    events.append(Event(
        event="buy", entity_type="user", entity_id="u1",
        target_entity_type="item", target_entity_id="i7", event_time=t))
    return events


@pytest.fixture(scope="module")
def ec_ctx():
    ctx = make_ctx("ecapp", ecommerce_events())
    set_storage(ctx.storage)  # serving-time lookups go through the global
    yield ctx
    set_storage(None)


def ec_engine_and_params(**kw):
    from predictionio_tpu.templates.ecommerce import (
        default_engine_params, ecommerce_engine)

    engine = ecommerce_engine()
    ep = default_engine_params("ecapp", rank=8, num_iterations=10, seed=9,
                               **kw)
    return engine, ep


class TestECommerceTemplate:
    def test_known_user(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params()
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(user="u0", num=4))
        assert pred.item_scores
        top = [int(s.item[1:]) for s in pred.item_scores]
        assert sum(1 for i in top if i < 6) >= 2, f"taste leak: {top}"

    def test_unknown_user_falls_back_to_popular(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params()
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(user="stranger", num=3))
        assert pred.item_scores
        assert pred.item_scores[0].item == "i3"  # most-bought

    def test_unknown_user_with_recent_views_gets_similar(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params()
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        # give a fresh user a recent view on a c1 item
        app_id, _ = ec_ctx.event_store.resolve("ecapp")
        ec_ctx.storage.events().insert(Event(
            event="view", entity_type="user", entity_id="newbie",
            target_entity_type="item", target_entity_id="i7",
            event_time=T0 + timedelta(days=1)), app_id)
        pred = algo.predict(model, Query(user="newbie", num=4))
        assert pred.item_scores
        top = [int(s.item[1:]) for s in pred.item_scores]
        assert sum(1 for i in top if i >= 6) >= 2, f"similar leak: {top}"

    def test_unseen_only_blacklists_seen(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params(unseen_only=True)
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        seen = {e.target_entity_id for e in ec_ctx.event_store.find(
            "ecapp", entity_type="user", entity_id="u0",
            event_names=["view", "buy"])}
        pred = algo.predict(model, Query(user="u0", num=6))
        assert not ({s.item for s in pred.item_scores} & seen)

    def test_unavailable_items_constraint(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params()
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        app_id, _ = ec_ctx.event_store.resolve("ecapp")
        ec_ctx.storage.events().insert(Event(
            event="$set", entity_type="constraint",
            entity_id="unavailableItems",
            properties=DataMap({"items": ["i3"]}),
            event_time=T0 + timedelta(days=2)), app_id)
        try:
            pred = algo.predict(model, Query(user="stranger", num=3))
            assert "i3" not in {s.item for s in pred.item_scores}
        finally:
            ec_ctx.storage.events().insert(Event(
                event="$set", entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": []}),
                event_time=T0 + timedelta(days=3)), app_id)

    def test_weighted_items_adjust_score(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params()
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        app_id, _ = ec_ctx.event_store.resolve("ecapp")
        # huge weight on i5 should pull it to the top for popularity path
        ec_ctx.storage.events().insert(Event(
            event="$set", entity_type="constraint",
            entity_id="weightedItems",
            properties=DataMap({"weights": [
                {"items": ["i7"], "weight": 1000.0}]}),
            event_time=T0 + timedelta(days=4)), app_id)
        try:
            pred = algo.predict(model, Query(user="stranger", num=2))
            assert pred.item_scores[0].item == "i7"
        finally:
            ec_ctx.storage.events().insert(Event(
                event="$set", entity_type="constraint",
                entity_id="weightedItems",
                properties=DataMap({"weights": []}),
                event_time=T0 + timedelta(days=5)), app_id)

    def test_category_filter(self, ec_ctx):
        from predictionio_tpu.templates.ecommerce import Query

        engine, ep = ec_engine_and_params()
        model = engine.train(ec_ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]
        pred = algo.predict(model, Query(user="u0", num=6,
                                         categories=["c1"]))
        assert all(int(s.item[1:]) >= 6 for s in pred.item_scores)

    def test_bind_serving_uses_injected_storage(self):
        """Serving-time filter reads must hit the serving Context's
        storage, not the process-global facade (ADVICE r1 medium): fresh
        algorithm instances (the engine-server bind topology) only see the
        right backend through bind_serving(ctx)."""
        from predictionio_tpu.templates.ecommerce import Query

        ctx = make_ctx("bindapp", ecommerce_events())  # NOT set as global
        from predictionio_tpu.templates.ecommerce import (
            default_engine_params, ecommerce_engine)
        engine = ecommerce_engine()
        ep = default_engine_params("bindapp", rank=8, num_iterations=10,
                                   seed=9, unseen_only=True)
        model = engine.train(ctx, ep).models[0]
        # fresh instance, as EngineServer._bind creates them
        algo = engine.make_algorithms(ep)[0]
        algo.bind_serving(ctx)
        seen = {e.target_entity_id for e in ctx.event_store.find(
            "bindapp", entity_type="user", entity_id="u0",
            event_names=["view", "buy"])}
        assert seen  # the fixture gives u0 history
        pred = algo.predict(model, Query(user="u0", num=6))
        assert not ({s.item for s in pred.item_scores} & seen)

    def test_unbound_fresh_instance_degrades_without_global(self):
        """Without bind_serving and without a global store, filter reads
        fail softly (logged, empty) — serving never hard-fails."""
        from predictionio_tpu.templates.ecommerce import Query

        ctx = make_ctx("nobind", ecommerce_events())
        from predictionio_tpu.templates.ecommerce import (
            default_engine_params, ecommerce_engine)
        engine = ecommerce_engine()
        ep = default_engine_params("nobind", rank=8, num_iterations=5,
                                   seed=9, unseen_only=True)
        model = engine.train(ctx, ep).models[0]
        algo = engine.make_algorithms(ep)[0]  # never bound
        pred = algo.predict(model, Query(user="u0", num=6))
        assert pred.item_scores  # still serves, just unfiltered
