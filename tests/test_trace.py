"""End-to-end request tracing + tail-sampled flight recorder (ISSUE 12).

Bottom-up: the W3C traceparent codec, the retention policy (slow /
error / deadline / fault kept, fast dropped, ring bounded under 100k
requests), the Perfetto export shape, OpenMetrics exemplar grammar and
content negotiation — then live-HTTP coverage: a traced /queries.json
query retained with its full stage timeline (dispatch/readback
included), and the headline propagation contract: an event ingested
with traceparent T is stamped, the streaming fold-in pass ADOPTS T,
and the hot-swap that made it servable appears under the SAME trace id
on /trace.json.
"""

import json
import logging
import re
import threading
import urllib.error
import urllib.request
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.obs import MetricsRegistry, StreamingHistogram
from predictionio_tpu.obs.trace import (
    FlightRecorder,
    Tracer,
    activate_traces,
    add_stage_spans,
    format_traceparent,
    mark_active_traces,
    parse_traceparent,
)
from predictionio_tpu.server.http import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    json_response,
    mount_metrics,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
RANK = 8
TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


# ---------------------------------------------------------------------------
# W3C traceparent codec
# ---------------------------------------------------------------------------
class TestTraceparent:
    def test_round_trip(self):
        tid, sid = "ab" * 16, "cd" * 8
        parsed = parse_traceparent(format_traceparent(tid, sid))
        assert parsed == (tid, sid)

    @pytest.mark.parametrize("bad", [
        None, "", "garbage",
        "00-" + "ab" * 16 + "-" + "cd" * 8,          # missing flags
        "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # forbidden version
        "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",   # zero trace id
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # zero span id
        "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
    ])
    def test_invalid_ignored(self, bad):
        assert parse_traceparent(bad) is None

    def test_begin_adopts_and_mints(self):
        tracer = Tracer()
        t = tracer.begin("q", traceparent=TP)
        assert t.trace_id == "ab" * 16
        assert t.parent_span_id == "cd" * 8
        fresh = tracer.begin("q", traceparent="nonsense")
        assert re.fullmatch(r"[0-9a-f]{32}", fresh.trace_id)
        assert fresh.trace_id != t.trace_id
        assert re.fullmatch(
            r"00-[0-9a-f]{32}-[0-9a-f]{16}-01", t.traceparent())


# ---------------------------------------------------------------------------
# retention policy
# ---------------------------------------------------------------------------
class TestRetention:
    def test_error_deadline_fault_kept_fast_dropped(self):
        tracer = Tracer()
        ok, _ = tracer.finish(tracer.begin("q"), status=200,
                              duration=0.001)
        assert not ok  # fast + healthy: dropped
        ok, reason = tracer.finish(tracer.begin("q"), status=500,
                                   duration=0.001)
        assert ok and reason == "error"
        ok, reason = tracer.finish(tracer.begin("q"), status=503,
                                   duration=0.001)
        assert ok and reason == "deadline"
        faulted = tracer.begin("q")
        faulted.mark("fault")
        ok, reason = tracer.finish(faulted, status=200, duration=0.001)
        assert ok and reason == "fault"

    def test_adaptive_slow_threshold_off_live_p99(self):
        tracer = Tracer(min_samples=100)
        assert tracer.slow_threshold() is None  # nothing learned yet
        for _ in range(200):
            tracer.finish(tracer.begin("q"), status=200,
                          duration=0.002)
        thr = tracer.slow_threshold()
        assert thr is not None and 0.001 < thr < 0.02
        ok, reason = tracer.finish(tracer.begin("q"), status=200,
                                   duration=0.5)
        assert ok and reason == "slow"
        # and a typical-latency request still drops
        ok, _ = tracer.finish(tracer.begin("q"), status=200,
                              duration=0.002)
        assert not ok

    def test_fixed_threshold_overrides_adaptive(self):
        tracer = Tracer(slow_ms=10.0)
        ok, reason = tracer.finish(tracer.begin("q"), status=200,
                                   duration=0.05)
        assert ok and reason == "slow"
        ok, _ = tracer.finish(tracer.begin("q"), status=200,
                              duration=0.005)
        assert not ok

    def test_ring_bounded_under_100k_requests(self):
        """100k traced requests (1% retained) must leave exactly
        ``ring`` traces resident — constant memory however long the
        server lives."""
        tracer = Tracer(ring=64)
        for i in range(100_000):
            status = 500 if i % 100 == 0 else 200
            tracer.finish(tracer.begin("q"), status=status,
                          duration=0.001)
        assert len(tracer.recorder) == 64
        assert tracer.recorder.dropped == 1000 - 64
        st = tracer.status()
        assert st["requests"] == 100_000
        assert st["retainedByReason"]["error"] == 1000

    def test_recorder_id_lookup_and_slowest(self):
        rec = FlightRecorder(capacity=8)
        tracer = Tracer()
        ids = []
        for i in range(5):
            t = tracer.begin(f"q{i}")
            tracer.finish(t, status=500, duration=0.01 * (i + 1))
            rec.add(t)
            ids.append(t.trace_id)
        assert rec.get(ids[2]).trace_id == ids[2]
        assert rec.get("f" * 32) is None
        slowest = rec.slowest(2)
        assert [t.trace_id for t in slowest] == [ids[4], ids[3]]

    def test_fault_marking_is_thread_local(self):
        t1, t2 = Tracer().begin("a"), Tracer().begin("b")
        seen = []

        def other_thread():
            with activate_traces([t2]):
                seen.append(True)

        with activate_traces([t1]):
            th = threading.Thread(target=other_thread)
            th.start()
            th.join()
            mark_active_traces("fault", faultPoint="p")
        assert "fault" in t1.marks and t1.attrs["faultPoint"] == "p"
        assert "fault" not in t2.marks  # other thread's batch untouched


# ---------------------------------------------------------------------------
# span recording + Perfetto export
# ---------------------------------------------------------------------------
class TestExport:
    def test_stage_spans_lay_out_sequentially(self):
        tracer = Tracer()
        t = tracer.begin("q")
        phases = {"assemble": 0.001, "supplement": 0.002,
                  "dispatch": 0.003, "readback": 0.004}
        add_stage_spans(t, t.t_mono, phases)
        names = [s.name for s in t.spans]
        assert names == ["assemble", "supplement", "dispatch",
                         "readback"]  # canonical order
        # back-to-back: each span starts where the previous ended
        for a, b in zip(t.spans, t.spans[1:]):
            assert b.t_start == pytest.approx(a.t_end)

    def test_perfetto_shape(self):
        tracer = Tracer()
        t = tracer.begin("POST /queries.json", traceparent=TP,
                         request_id="req1")
        with t.span("dispatch", lane=0):
            pass
        tracer.finish(t, status=500, duration=0.25)
        doc = t.to_trace_events()
        assert doc["otherData"]["traceId"] == "ab" * 16
        evs = doc["traceEvents"]
        assert evs[0]["name"] == "POST /queries.json"
        assert evs[0]["ph"] == "X"
        assert evs[0]["dur"] == pytest.approx(250_000, rel=0.01)
        assert evs[0]["args"]["requestId"] == "req1"
        child = [e for e in evs if e["name"] == "dispatch"][0]
        assert child["args"]["parentId"] == t.root_span_id
        assert child["args"]["lane"] == 0
        json.dumps(doc)  # fully serializable

    def test_span_ctx_records_errors(self):
        t = Tracer().begin("q")
        with pytest.raises(ValueError):
            with t.span("fold_in"):
                raise ValueError("boom")
        assert t.spans[0].attrs["error"] == "boom"


# ---------------------------------------------------------------------------
# exemplars + OpenMetrics grammar
# ---------------------------------------------------------------------------
EXEMPLAR_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*_bucket\{[^}]*\} [0-9]+ '
    r'# \{trace_id="[0-9a-f]{32}"\} [0-9.eE+-]+( [0-9]+(\.[0-9]+)?)?$')


class TestExemplars:
    def _registry_with_exemplar(self):
        reg = MetricsRegistry()
        fam = reg.histogram("pio_query_latency_seconds", "q")
        tracer = Tracer(ring=4)
        t = tracer.begin("q")
        child = fam.labels()
        child.observe(0.05)
        t.exemplar(child, 0.05)
        tracer.finish(t, status=500, duration=0.05)
        return reg, t

    def test_exemplar_grammar(self):
        reg, t = self._registry_with_exemplar()
        lines = reg.render(openmetrics=True).splitlines()
        ex = [ln for ln in lines if "# {" in ln]
        assert len(ex) == 1
        assert EXEMPLAR_RE.match(ex[0]), ex[0]
        assert t.trace_id in ex[0]

    def test_exemplars_absent_from_004_format(self):
        reg, _ = self._registry_with_exemplar()
        plain = reg.render()
        assert "# {" not in plain
        assert "# EOF" not in plain

    def test_openmetrics_terminator_and_counter_metadata(self):
        reg, _ = self._registry_with_exemplar()
        reg.counter("pio_events_ingested_total", "x").inc()
        om = reg.render(openmetrics=True)
        assert om.rstrip().endswith("# EOF")
        # counter family metadata drops _total, samples keep it
        assert "# TYPE pio_events_ingested counter" in om
        assert "pio_events_ingested_total 1" in om

    def test_unretained_trace_writes_no_exemplar(self):
        reg = MetricsRegistry()
        child = reg.histogram("pio_query_latency_seconds", "q").labels()
        tracer = Tracer()
        t = tracer.begin("q")
        child.observe(0.001)
        t.exemplar(child, 0.001)
        tracer.finish(t, status=200, duration=0.001)  # dropped
        assert "# {" not in reg.render(openmetrics=True)

    def test_exemplar_lands_in_value_bucket(self):
        h = StreamingHistogram(bounds=[0.1, 1.0, 10.0])
        h.record_exemplar(0.5, "ab" * 16)
        assert list(h.exemplars().keys()) == [1]  # 0.1 < 0.5 <= 1.0


# ---------------------------------------------------------------------------
# HTTP middleware (toy app)
# ---------------------------------------------------------------------------
@pytest.fixture()
def toy_server():
    app = HTTPApp("toy")
    reg = MetricsRegistry()

    @app.route("GET", "/ok")
    def ok(req: Request) -> Response:
        return json_response({"ok": True})

    @app.route("GET", "/boom")
    def boom(req: Request) -> Response:
        return json_response({"message": "nope"}, 500)

    mount_metrics(app, reg, server_name="toy")
    srv = AppServer(app, "127.0.0.1", 0).start_background()
    yield app, srv, srv.port
    srv.shutdown()


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    try:
        resp = urllib.request.urlopen(req, timeout=30)
        body = resp.read()
        return resp.status, dict(resp.headers), body
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class TestMiddleware:
    def test_traceparent_propagated_and_minted(self, toy_server):
        app, srv, port = toy_server
        status, headers, _ = _get(port, "/ok", {"traceparent": TP})
        assert status == 200
        echoed = parse_traceparent(headers["traceparent"])
        assert echoed[0] == "ab" * 16       # same trace id
        assert echoed[1] != "cd" * 8        # our own span id
        _, headers2, _ = _get(port, "/ok")
        assert parse_traceparent(headers2["traceparent"])[0] \
            != "ab" * 16                    # minted fresh

    def test_error_retained_and_served_from_trace_json(self, toy_server):
        app, srv, port = toy_server
        status, headers, _ = _get(port, "/boom", {"traceparent": TP})
        assert status == 500
        assert headers.get("X-Trace-Retained") == "error"
        _, _, body = _get(port, "/trace.json?id=" + "ab" * 16)
        doc = json.loads(body)
        assert doc["otherData"]["traceId"] == "ab" * 16
        assert doc["otherData"]["retainedReason"] == "error"
        # status + slowest listings work too
        _, _, body = _get(port, "/trace.json")
        st = json.loads(body)
        assert st["retained"] >= 1 and st["requests"] >= 1
        _, _, body = _get(port, "/trace.json?slowest=5")
        assert any(t["traceId"] == "ab" * 16
                   for t in json.loads(body)["traces"])

    def test_unknown_trace_404(self, toy_server):
        app, srv, port = toy_server
        status, _, _ = _get(port, "/trace.json?id=" + "f" * 32)
        assert status == 404

    def test_metrics_content_negotiation(self, toy_server):
        app, srv, port = toy_server
        _, headers, body = _get(port, "/metrics")
        assert headers["Content-Type"].startswith("text/plain")
        assert b"# EOF" not in body
        _, headers, body = _get(
            port, "/metrics",
            {"Accept": "application/openmetrics-text"})
        assert headers["Content-Type"].startswith(
            "application/openmetrics-text")
        assert body.rstrip().endswith(b"# EOF")

    def test_build_info_labels(self, toy_server):
        app, srv, port = toy_server
        _, _, body = _get(port, "/metrics")
        line = [ln for ln in body.decode().splitlines()
                if ln.startswith("pio_build_info{")][0]
        for label in ("server=", "version=", "jax=", "backend=",
                      "process_count=", "devices="):
            assert label in line, line

    def test_trace_metrics_exported(self, toy_server):
        app, srv, port = toy_server
        _get(port, "/boom")
        _, _, body = _get(port, "/metrics")
        text = body.decode()
        assert re.search(r'pio_trace_retained_total\{reason="error"\} '
                         r'[1-9]', text)
        assert "pio_trace_requests_total" in text
        assert "pio_trace_ring_size" in text

    def test_access_log_sampling(self, toy_server, caplog):
        app, srv, port = toy_server
        app.access_log_sample = 0.0  # drop ALL successes
        with caplog.at_level(logging.INFO, "predictionio_tpu.access"):
            _get(port, "/ok")
            _get(port, "/boom")
        lines = [json.loads(r.message) for r in caplog.records
                 if r.name == "predictionio_tpu.access"]
        statuses = [ln["status"] for ln in lines]
        assert 200 not in statuses      # sampled away
        assert 500 in statuses          # errors ALWAYS log
        assert all("traceId" in ln for ln in lines)
        app.access_log_sample = 1.0
        with caplog.at_level(logging.INFO, "predictionio_tpu.access"):
            _get(port, "/ok")
        lines = [json.loads(r.message) for r in caplog.records
                 if r.name == "predictionio_tpu.access"]
        assert any(ln["status"] == 200 for ln in lines)
        # the in-process trace object never leaks into the log line
        assert all(not k.startswith("_")
                   for ln in lines for k in ln)


# ---------------------------------------------------------------------------
# engine server end to end (live HTTP)
# ---------------------------------------------------------------------------
def _mem_storage(app_name="mlapp"):
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, app_name))
    storage.events().init(app_id)
    return storage, app_id


def _rate(user, item, rating, t):
    return Event(event="rate", entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 properties=DataMap({"rating": float(rating)}),
                 event_time=t)


def _seed(storage, app_id, n_users=20):
    rng = np.random.default_rng(7)
    events, t = [], T0
    for u in range(n_users):
        for i in rng.choice(20, size=6, replace=False):
            events.append(_rate(f"u{u}", f"i{i}", 5.0, t))
            t += timedelta(minutes=1)
    storage.events().insert_batch(events, app_id)
    return t


def _deploy(storage, **config_kw):
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )
    from predictionio_tpu.workflow import (
        get_latest_completed,
        load_models_for_deploy,
        run_train,
    )

    ctx = Context(app_name="mlapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("mlapp", rank=RANK, num_iterations=4,
                               reg=0.05, seed=3)
    run_train(ctx, engine, ep, engine_id="reco",
              engine_factory="templates.recommendation")
    inst = get_latest_completed(ctx, engine_id="reco")
    models = load_models_for_deploy(ctx, engine, inst, ep)
    config_kw.setdefault("warm_start", False)
    qs = QueryServer(ctx, engine, ep, models, inst,
                     ServerConfig(**config_kw))
    return qs


@pytest.fixture(scope="module")
def traced_server():
    from predictionio_tpu.server.engineserver import (
        create_engine_server,
    )

    storage, app_id = _mem_storage()
    t_end = _seed(storage, app_id)
    # trace_slow_ms=1: every device query (ms+) is "slow" → retained,
    # so the stage-timeline assertions don't depend on load
    qs = _deploy(storage, batching=True, max_batch=8,
                 trace_slow_ms=1.0)
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    yield storage, app_id, qs, srv, srv.port, t_end
    srv.shutdown()


def _query(port, user, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/queries.json",
        data=json.dumps({"user": user, "num": 3}).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


class TestEngineServerTracing:
    def test_slow_query_retained_with_stage_timeline(self, traced_server):
        """The acceptance path: a retained query's Perfetto export
        carries the full stage timeline including device dispatch and
        readback, plus the batch/engine attribution attrs."""
        storage, app_id, qs, srv, port, _ = traced_server
        status, headers, _ = _query(port, "u1", {"traceparent": TP})
        assert status == 200
        trace_id = parse_traceparent(headers["traceparent"])[0]
        assert trace_id == "ab" * 16
        assert headers.get("X-Trace-Retained") == "slow"
        _, _, body = _get(port, f"/trace.json?id={trace_id}")
        doc = json.loads(body)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "dispatch" in names and "readback" in names, names
        assert "batch" in names  # per-batch span rides every query
        root = doc["traceEvents"][0]
        assert root["args"]["status"] == 200
        batch = [e for e in doc["traceEvents"]
                 if e["name"] == "batch"][0]
        assert batch["args"]["batchSize"] >= 1
        # stage children parent onto the batch span
        dispatch = [e for e in doc["traceEvents"]
                    if e["name"] == "dispatch"][0]
        assert dispatch["args"]["parentId"] == batch["args"]["spanId"]

    def test_engine_attrs_and_exemplar(self, traced_server):
        storage, app_id, qs, srv, port, _ = traced_server
        _query(port, "u2")
        _, _, body = _get(port, "/trace.json?slowest=1")
        top = json.loads(body)["traces"][0]
        assert top["attrs"]["engineInstanceId"] == qs.instance.id
        assert top["attrs"]["arm"] == "stable"
        _, _, body = _get(
            port, "/metrics",
            {"Accept": "application/openmetrics-text"})
        ex = [ln for ln in body.decode().splitlines()
              if "pio_query_latency_seconds_bucket" in ln
              and "# {" in ln]
        assert ex and EXEMPLAR_RE.match(ex[0]), ex[:2]

    def test_fault_injected_query_flagged(self, traced_server):
        from predictionio_tpu.faults import inject_spec, registry

        storage, app_id, qs, srv, port, _ = traced_server
        inject_spec("serving.dispatch=latency,delay_ms=5,times=1")
        try:
            _query(port, "u3", {"traceparent": format_traceparent(
                "99" * 16, "11" * 8)})
        finally:
            registry().clear("serving.dispatch")
        trace = qs.tracer.recorder.get("99" * 16)
        assert trace is not None
        assert "fault" in trace.marks
        assert trace.attrs["faultPoint"] == "serving.dispatch"

    def test_status_page_and_status_json_blocks(self, traced_server):
        storage, app_id, qs, srv, port, _ = traced_server
        _, _, body = _get(port, "/status.json")
        st = json.loads(body)
        assert st["trace"]["ringCapacity"] == 512
        assert st["trace"]["requests"] >= 1
        _, _, body = _get(port, "/")
        assert b"flight recorder" in body

    def test_profile_endpoint(self, traced_server, tmp_path_factory):
        storage, app_id, qs, srv, port, _ = traced_server
        qs.profiler.base_dir = str(
            tmp_path_factory.mktemp("profiles"))
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/profile",
            data=json.dumps({"durationMs": 50}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 202
            info = json.loads(resp.read())
        assert info["durationMs"] == 50
        # a second capture while one runs is refused
        try:
            urllib.request.urlopen(req, timeout=30)
            second = 200
        except urllib.error.HTTPError as e:
            second = e.code
        assert second == 409
        deadline = 100
        import time as _time

        while qs.profiler.active and deadline:
            _time.sleep(0.05)
            deadline -= 1
        assert not qs.profiler.active
        _, _, body = _get(port, "/profile.json")
        pj = json.loads(body)
        assert pj["history"] and pj["history"][0]["done"]
        assert isinstance(pj["compileTable"], dict)

    def test_profile_bad_window_400(self, traced_server):
        storage, app_id, qs, srv, port, _ = traced_server
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/profile",
            data=json.dumps({"durationMs": 10 ** 9}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400

    def test_tracing_off_serves_untraced(self):
        storage, app_id = _mem_storage()
        _seed(storage, app_id, n_users=6)
        qs = _deploy(storage, tracing=False)
        from predictionio_tpu.server.engineserver import (
            create_engine_server,
        )

        srv = create_engine_server(qs, host="127.0.0.1", port=0)
        srv.start_background()
        try:
            status, headers, _ = _query(srv.port, "u1")
            assert status == 200
            assert "traceparent" not in {k.lower() for k in headers}
            code, _, _ = _get(srv.port, "/trace.json")
            assert code == 404  # no tracer, no route
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# the headline contract: ingest → fold-in → hot-swap, ONE trace id
# ---------------------------------------------------------------------------
class TestEventToServableTrace:
    def test_trace_id_equal_end_to_end(self, traced_server):
        from predictionio_tpu.cache.bus import InvalidationBus
        from predictionio_tpu.data.storage.base import AccessKey
        from predictionio_tpu.server import eventserver
        from predictionio_tpu.streaming import (
            StreamConfig,
            StreamTrainer,
        )

        storage, app_id, qs, srv, port, t_end = traced_server
        storage.access_keys().insert(AccessKey("trace-key", app_id, []))
        ev_app = eventserver.build_app(storage)
        ev_srv = AppServer(ev_app, "127.0.0.1", 0).start_background()
        trainer = StreamTrainer(
            qs, StreamConfig(app_name="mlapp", consumer="t-trace",
                             canary_probes=2, interval_ms=50),
            bus=InvalidationBus())
        try:
            trainer.consume_once()  # drain the seed log
            ingest_tp = format_traceparent("ee" * 16, "22" * 8)
            body = json.dumps({
                "event": "rate", "entityType": "user",
                "entityId": "u1", "targetEntityType": "item",
                "targetEntityId": "i9",
                "properties": {"rating": 5.0},
                "eventTime": (t_end + timedelta(days=1)).isoformat(),
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ev_srv.port}/events.json"
                f"?accessKey=trace-key", data=body,
                headers={"Content-Type": "application/json",
                         "traceparent": ingest_tp})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 201
            assert trainer.consume_once() == 1
            # the fold-in pass ADOPTED the ingest trace id and the
            # engine server's recorder retained it (reason "stream")
            trace = qs.tracer.recorder.get("ee" * 16)
            assert trace is not None, "fold-in trace not retained"
            assert trace.retained_reason == "stream"
            assert trace.attrs["outcome"] == "applied"
            names = [s.name for s in trace.spans]
            for stage in ("consume", "fold_in", "canary", "hot_swap",
                          "advance"):
                assert stage in names, names
            # and it is retrievable over HTTP from the ENGINE server
            _, _, body = _get(port, "/trace.json?id=" + "ee" * 16)
            doc = json.loads(body)
            assert doc["otherData"]["traceId"] == "ee" * 16
            assert {"fold_in", "hot_swap"} <= {
                e["name"] for e in doc["traceEvents"]}
        finally:
            trainer.stop(timeout=5)
            ev_srv.shutdown()

    def test_batch_ingest_stamps_every_event(self, traced_server):
        from predictionio_tpu.data.storage.base import (
            AccessKey,
            EventFilter,
        )
        from predictionio_tpu.server import eventserver

        storage, app_id, qs, srv, port, t_end = traced_server
        storage.access_keys().insert(AccessKey("batch-key", app_id, []))
        ev_srv = AppServer(eventserver.build_app(storage),
                           "127.0.0.1", 0).start_background()
        try:
            tp = format_traceparent("dd" * 16, "33" * 8)
            t = t_end + timedelta(days=2)
            payload = [{
                "event": "rate", "entityType": "user",
                "entityId": f"u_b{k}", "targetEntityType": "item",
                "targetEntityId": "i1",
                "properties": {"rating": 4.0},
                "eventTime": (t + timedelta(seconds=k)).isoformat(),
            } for k in range(3)]
            req = urllib.request.Request(
                f"http://127.0.0.1:{ev_srv.port}/batch/events.json"
                f"?accessKey=batch-key",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json",
                         "traceparent": tp})
            with urllib.request.urlopen(req, timeout=30) as resp:
                results = json.loads(resp.read())
            assert all(r["status"] == 201 for r in results)
            stamped = [
                e for e in storage.events().find(
                    app_id, None, EventFilter(limit=-1))
                if str(e.properties.get("pio_traceparent",
                                        default="")).startswith(
                    "00-" + "dd" * 16)]
            assert len(stamped) == 3
        finally:
            ev_srv.shutdown()


# ---------------------------------------------------------------------------
# compile-time table
# ---------------------------------------------------------------------------
class TestCompileTable:
    def test_listener_builds_bounded_table(self):
        from predictionio_tpu.server.stats import RecompileSentinel

        before = RecompileSentinel.total_compiles()
        RecompileSentinel._listener(
            "/jax/core/compile/backend_compile_duration", 1.25)
        RecompileSentinel._listener(
            "/jax/core/compile/backend_compile_duration", 0.25)
        assert RecompileSentinel.total_compiles() == before + 2
        table = RecompileSentinel.compile_table()
        row = table["/jax/core/compile/backend_compile_duration"]
        assert row["count"] >= 2
        assert row["maxSec"] >= 1.25
        assert row["lastSec"] == 0.25
