"""EventStoreFacade tests: app-name resolution, channels, serving lookups."""

from datetime import datetime, timedelta, timezone

import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Channel, Storage, StorageError
from predictionio_tpu.data.store import EventStoreFacade

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
HOUR = timedelta(hours=1)


@pytest.fixture
def env():
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app_id = storage.apps().insert(App(0, "shop"))
    chan_id = storage.channels().insert(Channel(0, "mobile", app_id))
    facade = EventStoreFacade(storage)
    es = storage.events()
    es.init(app_id)
    es.init(app_id, chan_id)

    def mk(name, uid, iid, t, chan=None, props=None):
        e = Event(event=name, entity_type="user", entity_id=uid,
                  target_entity_type="item", target_entity_id=iid,
                  event_time=t, properties=DataMap(props or {}))
        es.insert(e, app_id, chan)
        return e

    mk("view", "u1", "i1", T0)
    mk("buy", "u1", "i2", T0 + HOUR)
    mk("view", "u2", "i1", T0 + 2 * HOUR)
    mk("view", "u1", "i3", T0 + 3 * HOUR, chan=chan_id)
    return facade


def test_find_by_app_name(env):
    events = list(env.find("shop"))
    assert len(events) == 3


def test_find_channel(env):
    events = list(env.find("shop", channel_name="mobile"))
    assert len(events) == 1
    assert events[0].target_entity_id == "i3"


def test_unknown_app_raises(env):
    with pytest.raises(StorageError):
        list(env.find("nope"))


def test_unknown_channel_raises(env):
    with pytest.raises(StorageError):
        list(env.find("shop", channel_name="nope"))


def test_find_by_entity_latest_first(env):
    events = env.find_by_entity("shop", "user", "u1")
    assert [e.target_entity_id for e in events] == ["i2", "i1"]
    events = env.find_by_entity("shop", "user", "u1", event_names=["view"])
    assert [e.target_entity_id for e in events] == ["i1"]


def test_find_by_entity_deadline_bounds_heavy_scan(tmp_path):
    """A heavy entity with a tiny timeout must raise at ~the deadline,
    not after materializing the whole scan (LEventStore.scala:76-120's
    bounded Await; VERDICT r1 'What's weak' #3). Exercised on localfs,
    whose replay is the slowest scan path."""
    import time

    storage = Storage(env={
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
    })
    app_id = storage.apps().insert(App(0, "heavy"))
    es = storage.events()
    es.init(app_id)
    es.insert_batch([
        Event(event="view", entity_type="user", entity_id="whale",
              target_entity_type="item", target_entity_id=f"i{i}",
              event_time=T0 + i * timedelta(seconds=1))
        for i in range(20000)], app_id)
    # read through a fresh client: the log replay (the slow path a real
    # serving process pays on first read) must itself honor the deadline
    cold = Storage(env={
        "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
    })
    facade = EventStoreFacade(cold)

    # min-of-3: a gen-2 GC pause in a long pytest session (jax keeps
    # millions of heap objects live) can dwarf the actual bounded scan
    t_bounded = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            facade.find_by_entity("heavy", "user", "whale", timeout_ms=1)
        t_bounded = min(t_bounded, time.monotonic() - t0)

    # an adequate timeout still returns the full result set
    t0 = time.monotonic()
    out = facade.find_by_entity("heavy", "user", "whale", timeout_ms=60000)
    t_full = time.monotonic() - t0
    assert len(out) == 20000
    # the deadline fired INSIDE the scan: bounded time must be well under
    # the full materialization (relative bound — absolute ms limits flake
    # under CI/host load), with a floor for very fast disks
    assert t_bounded < max(0.5, 0.6 * t_full), (t_bounded, t_full)


def test_aggregate_properties_by_name(env):
    es = env.storage.events()
    app_id, _ = env.resolve("shop")
    es.insert(Event(event="$set", entity_type="item", entity_id="i1",
                    properties=DataMap({"price": 10}), event_time=T0), app_id)
    props = env.aggregate_properties("shop", "item")
    assert props["i1"].to_dict() == {"price": 10}
