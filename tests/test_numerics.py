"""Numerics-flow tests: the five dtype-lattice rules (positive /
negative / pragma, incl. the two-hop interprocedural chain), the
metric-catalog drift gate, the `ptpu audit-numerics` census + ratchet,
the checkify NaN sentinel (unit and over live HTTP), the CLI contract,
and the acceptance fixture proving a seeded bf16-accumulation
regression fails BOTH the static rule and the audit gate."""

import copy
import json
import os
import textwrap
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.analysis import RULES, check_project, check_source
from predictionio_tpu.analysis import numerics_audit as na
from predictionio_tpu.analysis.numerics import NUMERICS_RULES
from predictionio_tpu.cli import main
from predictionio_tpu.obs import numerics as sentinel

MODELS = "predictionio_tpu/models/m.py"   # precision rules patrol here
UTILS = "predictionio_tpu/utils/u.py"     # ...and not here


def rules_of(findings):
    return [f.rule for f in findings]


def src(text):
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# rule: low-precision-reduction (direct)
# ---------------------------------------------------------------------------

class TestLowPrecisionReduction:
    def test_positive_einsum_over_bf16(self):
        code = src("""
            import jax.numpy as jnp

            def gram(table):
                shadow = table.astype(jnp.bfloat16)
                return jnp.einsum("lr,ls->rs", shadow, shadow)
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["low-precision-reduction"]
        assert "bfloat16" in findings[0].message
        assert "preferred_element_type" in findings[0].message

    def test_positive_sum_method_and_matmul(self):
        code = src("""
            import jax.numpy as jnp

            def acc(x):
                lo = x.astype(jnp.float16)
                a = lo.sum()
                b = lo @ lo
                return a, b
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["low-precision-reduction"] * 2

    def test_negative_preferred_element_type(self):
        code = src("""
            import jax.numpy as jnp

            def gram(table):
                shadow = table.astype(jnp.bfloat16)
                return jnp.einsum("lr,ls->rs", shadow, shadow,
                                  preferred_element_type=jnp.float32)
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_upcast_before_reduction(self):
        # (scoped to the rule: the bf16→f32 upcast itself is
        # dequant-outside-funnel territory, judged separately)
        code = src("""
            import jax.numpy as jnp

            def gram(table):
                shadow = table.astype(jnp.bfloat16)
                wide = shadow.astype(jnp.float32)
                return jnp.sum(wide)
        """)
        assert check_source(code, path=MODELS,
                            rule_names=["low-precision-reduction"]) \
            == []

    def test_negative_outside_hot_dirs(self):
        code = src("""
            import jax.numpy as jnp

            def gram(table):
                shadow = table.astype(jnp.bfloat16)
                return jnp.sum(shadow)
        """)
        assert check_source(code, path=UTILS) == []

    def test_conditional_shadow_ifexp_is_seen(self):
        # the fold-in idiom: `t.astype(jnp.bfloat16) if bf16 else t`
        code = src("""
            import jax.numpy as jnp

            def solve(table, bf16):
                gsrc = table.astype(jnp.bfloat16) if bf16 else table
                return jnp.sum(gsrc)
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["low-precision-reduction"]

    def test_pragma_suppresses(self):
        code = src("""
            import jax.numpy as jnp

            def gram(table):
                shadow = table.astype(jnp.bfloat16)
                return jnp.sum(shadow)  # ptpu: allow[low-precision-reduction] — short sum, loss bounded
        """)
        assert check_source(code, path=MODELS) == []


class TestLowPrecisionInterprocedural:
    LEAF = src("""
        import jax.numpy as jnp

        def accumulate(x):
            return jnp.sum(x)
    """)
    MID = src("""
        from pkg.ops.leaf import accumulate

        def shuttle(x):
            return accumulate(x) + 1
    """)

    def _project(self, caller):
        return check_project({
            "pkg/ops/leaf.py": self.LEAF,
            "pkg/ops/mid.py": self.MID,
            "pkg/models/fold.py": src(caller),
        })

    def test_two_hop_chain_flagged_at_caller(self):
        findings = self._project("""
            import jax.numpy as jnp
            from pkg.ops.mid import shuttle

            def fold(table):
                shadow = table.astype(jnp.bfloat16)
                return shuttle(shadow)
        """)
        assert rules_of(findings) == ["low-precision-reduction"]
        f = findings[0]
        # anchored at the bf16 call site, not inside the helpers
        assert f.path == "pkg/models/fold.py"
        # ...with the helper chain in the message
        assert "shuttle" in f.message and "accumulate" in f.message
        assert "bfloat16" in f.message
        # ...and hop locations machine-readable for SARIF
        assert [p for p, _, _ in f.related] == [
            "pkg/ops/mid.py", "pkg/ops/leaf.py"]

    def test_negative_upcast_at_call_site(self):
        findings = self._project("""
            import jax.numpy as jnp
            from pkg.ops.mid import shuttle

            def fold(table):
                shadow = table.astype(jnp.bfloat16)
                return shuttle(shadow.astype(jnp.float32))
        """)
        assert "low-precision-reduction" not in rules_of(findings)

    def test_pragma_at_leaf_blesses_callers(self):
        blessed_leaf = src("""
            import jax.numpy as jnp

            def accumulate(x):
                return jnp.sum(x)  # ptpu: allow[low-precision-reduction] — callers bound the length
        """)
        findings = check_project({
            "pkg/ops/leaf.py": blessed_leaf,
            "pkg/ops/mid.py": self.MID,
            "pkg/models/fold.py": src("""
                import jax.numpy as jnp
                from pkg.ops.mid import shuttle

                def fold(table):
                    shadow = table.astype(jnp.bfloat16)
                    return shuttle(shadow)
            """),
        })
        assert findings == []


# ---------------------------------------------------------------------------
# rule: dequant-outside-funnel
# ---------------------------------------------------------------------------

class TestDequantOutsideFunnel:
    def test_positive_adhoc_data_upcast(self):
        code = src("""
            import jax.numpy as jnp

            def serve(table):
                wide = table.data.astype(jnp.float32)
                return wide
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["dequant-outside-funnel"]
        assert "dequantize_table" in findings[0].message

    def test_negative_inside_blessed_funnel(self):
        code = src("""
            import jax.numpy as jnp

            def dequantize_table(table):
                return table.data.astype(jnp.float32)
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_module_level_dequant_lambda(self):
        # the als.py `_dequant_scaled = jax.jit(lambda ...)` idiom
        code = src("""
            import jax
            import jax.numpy as jnp

            _dequant_scaled = jax.jit(
                lambda d, s: d.astype(jnp.float32) * s)
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_upcast_of_unquantized_value(self):
        code = src("""
            import jax.numpy as jnp

            def widen(x):
                return x.astype(jnp.float32)
        """)
        assert check_source(code, path=MODELS) == []

    def test_pragma_suppresses(self):
        code = src("""
            import jax.numpy as jnp

            def debug_dump(table):
                return table.data.astype(jnp.float32)  # ptpu: allow[dequant-outside-funnel] — offline debug dump
        """)
        assert check_source(code, path=MODELS) == []


# ---------------------------------------------------------------------------
# rule: quantize-without-parity-gate
# ---------------------------------------------------------------------------

class TestQuantizeWithoutParityGate:
    def test_positive_raw_construction(self):
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def ship(data, scale):
                return QuantizedFactors(data, scale, "int8")
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["quantize-without-parity-gate"]
        assert "quantize_serving_model" in findings[0].message

    def test_positive_raw_quantize_rows(self):
        code = src("""
            from predictionio_tpu.models.als import _quantize_rows

            def ship(rows):
                return _quantize_rows(rows, "int8")
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["quantize-without-parity-gate"]

    def test_negative_inside_parity_funnel(self):
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def quantize_serving_model(model):
                return QuantizedFactors(model.data, model.scale, "int8")
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_copy_constructor_residency_move(self):
        # quant= carries an EXISTING table's decision — a pinning /
        # residency move, not a fresh (ungated) quantization
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def pin(t, dev):
                return QuantizedFactors(put(t.data, dev),
                                        put(t.scale, dev), t.quant)
        """)
        assert check_source(code, path=MODELS) == []

    def test_pragma_suppresses(self):
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def fixture(data, scale):
                return QuantizedFactors(data, scale, "int8")  # ptpu: allow[quantize-without-parity-gate] — test fixture
        """)
        assert check_source(code, path=MODELS) == []


# ---------------------------------------------------------------------------
# rule: unguarded-domain
# ---------------------------------------------------------------------------

class TestUnguardedDomain:
    def test_positive_division_no_guard(self):
        code = src("""
            def mean_score(total, count):
                return total / count
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["unguarded-domain"]
        assert "count" in findings[0].message

    def test_positive_log_no_guard(self):
        code = src("""
            import jax.numpy as jnp

            def ll(p):
                return jnp.log(p)
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["unguarded-domain"]

    def test_negative_maximum_guard(self):
        code = src("""
            import jax.numpy as jnp

            def ll(p):
                return jnp.log(jnp.maximum(p, 1e-9))
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_eps_shift(self):
        code = src("""
            import jax.numpy as jnp

            def norm(x, eps):
                return x / (jnp.sum(x) + eps)
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_counter_bumped_before_divide(self):
        code = src("""
            def rate(events):
                n = 0
                total = 0.0
                for e in events:
                    n += 1
                    total += e
                return total / n
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_branch_tested(self):
        code = src("""
            def safe(total, count):
                return total / count if count else 0.0
        """)
        assert check_source(code, path=MODELS) == []

    def test_negative_positive_literal_default(self):
        # the `lam: float = 1.0` Laplace idiom (classify.py)
        code = src("""
            import jax.numpy as jnp

            def smooth(counts, lam: float = 1.0):
                return jnp.log(counts + lam)
        """)
        assert check_source(code, path=MODELS) == []

    def test_pragma_suppresses(self):
        code = src("""
            def mean_score(total, count):
                return total / count  # ptpu: allow[unguarded-domain] — caller validates count
        """)
        assert check_source(code, path=MODELS) == []


# ---------------------------------------------------------------------------
# rule: requant-torn-pair
# ---------------------------------------------------------------------------

class TestRequantTornPair:
    def test_positive_torn_attribute_write(self):
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def hot_swap(table: QuantizedFactors, rows):
                table.data = rows
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["requant-torn-pair"]
        assert "stale" in findings[0].message.lower() \
            or "scale" in findings[0].message

    def test_negative_paired_write(self):
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def hot_swap(table: QuantizedFactors, rows, scales):
                table.data = rows
                table.scale = scales
        """)
        assert check_source(code, path=MODELS) == []

    def test_positive_replace_missing_scale(self):
        code = src("""
            import dataclasses
            from predictionio_tpu.models.als import QuantizedFactors

            def hot_swap(table: QuantizedFactors, rows):
                return dataclasses.replace(table, data=rows)
        """)
        findings = check_source(code, path=MODELS)
        assert rules_of(findings) == ["requant-torn-pair"]
        assert "replace" in findings[0].message

    def test_negative_replace_with_both(self):
        code = src("""
            import dataclasses
            from predictionio_tpu.models.als import QuantizedFactors

            def hot_swap(table: QuantizedFactors, rows, scales):
                return dataclasses.replace(table, data=rows,
                                           scale=scales)
        """)
        assert check_source(code, path=MODELS) == []

    def test_pragma_suppresses(self):
        code = src("""
            from predictionio_tpu.models.als import QuantizedFactors

            def debug_poke(table: QuantizedFactors, rows):
                table.data = rows  # ptpu: allow[requant-torn-pair] — scale updated by caller
        """)
        assert check_source(code, path=MODELS) == []


# ---------------------------------------------------------------------------
# satellite: metric-catalog drift gate
# ---------------------------------------------------------------------------

REGISTERING = src("""
    def wire(metrics):
        c = metrics.counter("pio_test_widgets_total", "widgets")
        g = metrics.gauge("pio_test_depth", "depth")
        return c, g
""")


class TestMetricCatalogDrift:
    @pytest.fixture()
    def catalog(self, tmp_path, monkeypatch):
        path = tmp_path / "observability.md"

        def write(text):
            path.write_text(text)
            return path

        monkeypatch.setattr(
            "predictionio_tpu.analysis.metrics_catalog.CATALOG_PATH",
            str(path))
        return write

    def test_undocumented_family_flagged_at_registration(self, catalog):
        catalog("| `pio_test_widgets_total` | counter |\n")
        findings = check_source(REGISTERING, path=MODELS,
                                rule_names=["metric-catalog-drift"])
        assert rules_of(findings) == ["metric-catalog-drift"]
        assert "pio_test_depth" in findings[0].message
        assert findings[0].path == MODELS

    def test_documented_but_never_emitted_flagged_at_doc_line(
            self, catalog):
        catalog("| `pio_test_widgets_total` | counter |\n"
                "| `pio_test_depth` | gauge |\n"
                "| `pio_test_ghost_total` | counter |\n")
        findings = check_source(REGISTERING, path=MODELS,
                                rule_names=["metric-catalog-drift"])
        assert rules_of(findings) == ["metric-catalog-drift"]
        assert "pio_test_ghost_total" in findings[0].message
        assert findings[0].path.endswith("observability.md")
        assert findings[0].line == 3

    def test_clean_when_both_sides_agree(self, catalog):
        catalog("| `pio_test_widgets_total` | counter |\n"
                "| `pio_test_depth` | gauge |\n")
        assert check_source(REGISTERING, path=MODELS,
                            rule_names=["metric-catalog-drift"]) == []

    def test_prefix_prose_is_not_a_row(self, catalog):
        # `pio_lane_*`-style prose must not register as a documented
        # family (nor demand an emitter)
        catalog("| `pio_test_widgets_total` | counter |\n"
                "| `pio_test_depth` | gauge |\n"
                "the `pio_test_lane_*` family is per-lane\n")
        assert check_source(REGISTERING, path=MODELS,
                            rule_names=["metric-catalog-drift"]) == []

    def test_silent_without_registrations(self, catalog):
        catalog("| `pio_test_ghost_total` | counter |\n")
        assert check_source("X = 1\n", path=MODELS,
                            rule_names=["metric-catalog-drift"]) == []

    def test_repo_catalog_and_code_agree(self):
        # the real gate over the real tree rides the repo-wide clean
        # test in test_check.py; here just pin that the rule is
        # registered and the catalog exists where the rule looks
        from predictionio_tpu.analysis import metrics_catalog as mc

        assert "metric-catalog-drift" in RULES
        assert os.path.exists(mc.CATALOG_PATH)


# ---------------------------------------------------------------------------
# audit-numerics: census goldens
# ---------------------------------------------------------------------------

class TestCensusJaxpr:
    def test_bf16_dot_accumulates_bf16_without_preferred(self):
        import jax
        import jax.numpy as jnp

        a = jnp.ones((4, 4), jnp.bfloat16)
        closed = jax.make_jaxpr(
            lambda x, y: jnp.einsum("ij,jk->ik", x, y))(a, a)
        rec = na.census_jaxpr(closed)
        assert rec["reductions"].get("dot_general") == {"bfloat16": 1}

    def test_preferred_element_type_widens_the_accumulator(self):
        import jax
        import jax.numpy as jnp

        a = jnp.ones((4, 4), jnp.bfloat16)
        closed = jax.make_jaxpr(
            lambda x, y: jnp.einsum(
                "ij,jk->ik", x, y,
                preferred_element_type=jnp.float32))(a, a)
        rec = na.census_jaxpr(closed)
        assert rec["reductions"]["dot_general"] == {"float32": 1}
        assert "bfloat16" not in rec["reductions"]["dot_general"]

    def test_cast_inventory_and_bytes(self):
        import jax
        import jax.numpy as jnp

        a = jnp.ones((8,), jnp.bfloat16)
        closed = jax.make_jaxpr(
            lambda x: x.astype(jnp.float32) * 2.0)(a)
        rec = na.census_jaxpr(closed)
        assert rec["casts"] == {"bfloat16->float32": 1}
        assert rec["bytes"]["float32"] >= 8 * 4

    def test_sub_jaxprs_counted_once(self):
        import jax
        import jax.numpy as jnp

        a = jnp.ones((8,), jnp.bfloat16)
        inner = jax.jit(lambda y: y.astype(jnp.float32))
        closed = jax.make_jaxpr(lambda x: inner(x))(a)
        rec = na.census_jaxpr(closed)
        # the pjit call eqn contributes only its body — the cast
        # appears exactly once, not once per nesting level
        assert rec["casts"] == {"bfloat16->float32": 1}


# ---------------------------------------------------------------------------
# audit-numerics: run + ratchet diff
# ---------------------------------------------------------------------------

SUBSET = ["quantize_serving_model", "device_topk_int8"]


@pytest.fixture(scope="class")
def manifest():
    return na.run_audit(SUBSET)


class TestRunAuditAndRatchet:
    def test_manifest_shape(self, manifest):
        assert manifest["version"] == na.MANIFEST_VERSION
        assert manifest["devices"] == na.AUDIT_DEVICE_COUNT
        assert set(manifest["entries"]) == set(SUBSET)
        for rec in manifest["entries"].values():
            assert set(rec) == {"ops", "casts", "reductions", "bytes"}

    def test_dequant_funnels_in_the_census(self, manifest):
        casts = manifest["entries"]["quantize_serving_model"]["casts"]
        assert casts.get("int8->float32") == 1
        assert casts.get("bfloat16->float32") == 1

    def test_unknown_entry_raises(self):
        with pytest.raises(na.AuditError, match="unknown entry"):
            na.run_audit(["nope"])

    def test_diff_against_itself_is_clean(self, manifest):
        violations, shrinkable = na.diff_manifests(manifest, manifest)
        assert violations == [] and shrinkable == []

    def test_committed_baseline_matches_live_trace(self, manifest):
        """The committed golden baseline reproduces on this machine
        for the audited subset — the CI gate's premise."""
        baseline = na.load_manifest(na.DEFAULT_BASELINE)
        for name in manifest["entries"]:
            rec, brec = manifest["entries"][name], \
                baseline["entries"][name]
            assert rec["casts"] == brec["casts"], name
            assert rec["reductions"] == brec["reductions"], name

    def test_new_cast_is_a_violation(self, manifest):
        base = copy.deepcopy(manifest)
        del base["entries"]["quantize_serving_model"]["casts"][
            "int8->float32"]
        violations, _ = na.diff_manifests(manifest, base)
        assert any("quantize_serving_model" in v
                   and "int8->float32" in v for v in violations)

    def test_low_precision_reduction_growth_is_a_violation(
            self, manifest):
        cur = copy.deepcopy(manifest)
        cur["entries"]["device_topk_int8"]["reductions"][
            "dot_general"] = {"bfloat16": 1}
        violations, _ = na.diff_manifests(cur, manifest)
        assert any("dot_general" in v and "bfloat16" in v
                   and "f32 accumulator" in v for v in violations)

    def test_wide_reduction_growth_is_not_a_violation(self, manifest):
        # MORE f32 reductions is not a precision regression
        cur = copy.deepcopy(manifest)
        reds = cur["entries"]["device_topk_int8"]["reductions"]
        reds["dot_general"] = dict(reds["dot_general"])
        reds["dot_general"]["float32"] = \
            reds["dot_general"].get("float32", 0) + 3
        violations, _ = na.diff_manifests(cur, manifest)
        assert violations == []

    def test_bytes_blowup_is_a_violation(self, manifest):
        cur = copy.deepcopy(manifest)
        b = cur["entries"]["device_topk_int8"]["bytes"]
        b["float32"] = int(b.get("float32", 0) * 4 + 10_000_000)
        violations, _ = na.diff_manifests(cur, manifest)
        assert any("device_topk_int8" in v and "float32" in v
                   for v in violations)

    def test_unrecorded_entry_is_a_violation(self, manifest):
        base = copy.deepcopy(manifest)
        del base["entries"]["device_topk_int8"]
        violations, _ = na.diff_manifests(manifest, base)
        assert any("device_topk_int8" in v and "baseline-grow" in v
                   for v in violations)

    def test_device_count_mismatch_is_a_violation(self, manifest):
        base = copy.deepcopy(manifest)
        base["devices"] = 4
        violations, _ = na.diff_manifests(manifest, base)
        assert any("device count" in v for v in violations)

    def test_shrink_is_reported_not_fatal(self, manifest):
        base = copy.deepcopy(manifest)
        base["entries"]["quantize_serving_model"]["casts"][
            "int8->float32"] += 5
        violations, shrinkable = na.diff_manifests(manifest, base)
        assert violations == []
        assert any("int8->float32" in s for s in shrinkable)

    def test_write_ratchets_shrink_only(self, manifest, tmp_path):
        path = str(tmp_path / "b.json")
        grown = copy.deepcopy(manifest)
        grown["entries"]["quantize_serving_model"]["casts"][
            "float32->int8"] = 7          # a key the baseline never had
        grown["entries"]["extra_entry"] = \
            copy.deepcopy(manifest["entries"]["device_topk_int8"])
        na.write_manifest(path, grown, cap=manifest)
        doc = na.load_manifest(path)
        assert "extra_entry" not in doc["entries"]
        assert "float32->int8" not in \
            doc["entries"]["quantize_serving_model"]["casts"]

    def test_baseline_grow_writes_as_is(self, manifest, tmp_path):
        path = str(tmp_path / "b.json")
        grown = copy.deepcopy(manifest)
        grown["entries"]["extra_entry"] = \
            copy.deepcopy(manifest["entries"]["device_topk_int8"])
        na.write_manifest(path, grown, cap=None)   # --baseline-grow
        doc = na.load_manifest(path)
        assert "extra_entry" in doc["entries"]

    def test_load_rejects_wrong_version(self, tmp_path):
        p = tmp_path / "v.json"
        p.write_text(json.dumps({"version": 99, "entries": {}}))
        with pytest.raises(ValueError, match="version"):
            na.load_manifest(str(p))

    def test_all_registered_entries_meet_the_floor(self):
        # the acceptance criterion: CI gates at least 10 entry points
        assert len(na.ENTRY_POINTS) >= 10
        baseline = na.load_manifest(na.DEFAULT_BASELINE)
        assert set(baseline["entries"]) == set(na.ENTRY_POINTS)


# ---------------------------------------------------------------------------
# acceptance: a seeded bf16-accumulation regression fails BOTH gates
# ---------------------------------------------------------------------------

class TestSeededRegressionFailsBothGates:
    # the regression: ops/gram.py's einsum with the f32 accumulator
    # contract dropped — a one-line diff someone could plausibly ship
    BAD = src("""
        import jax.numpy as jnp

        def gram_weighted(F, w):
            lo = F.astype(jnp.bfloat16)
            return jnp.einsum("lr,ls->rs", lo, lo)
    """)

    def test_static_rule_catches_it(self):
        findings = check_source(self.BAD,
                                path="predictionio_tpu/ops/gram.py")
        assert "low-precision-reduction" in rules_of(findings)

    def test_audit_gate_catches_it(self):
        import jax
        import jax.numpy as jnp

        a = jnp.ones((16, 4), jnp.bfloat16)
        closed = jax.make_jaxpr(
            lambda F: jnp.einsum("lr,ls->rs", F, F))(a)
        rec = na.census_jaxpr(closed)
        assert rec["reductions"]["dot_general"] == {"bfloat16": 1}, \
            "fixture broken — regression produced no bf16 reduction"
        current = {"version": na.MANIFEST_VERSION,
                   "devices": na.AUDIT_DEVICE_COUNT,
                   "entries": {"gram": rec}}
        golden = copy.deepcopy(current)
        golden["entries"]["gram"]["reductions"] = {
            "dot_general": {"float32": 1}}
        violations, _ = na.diff_manifests(current, golden)
        assert violations, "the lost f32 accumulator must fail the gate"
        assert any("dot_general" in v and "bfloat16" in v
                   for v in violations)


# ---------------------------------------------------------------------------
# runtime sentinel: unit
# ---------------------------------------------------------------------------

@pytest.fixture()
def clean_sentinel():
    sentinel.reset_for_tests()
    yield sentinel
    sentinel.reset_for_tests()


class TestSentinelUnit:
    def test_inactive_is_a_pass_through(self, clean_sentinel):
        assert not sentinel.active()
        assert sentinel.check_array("x", np.array([np.nan]))
        assert sentinel.stats() == {}   # off ⇒ nothing recorded
        out = sentinel.checked_call("x", lambda a: a + 1, 1)
        assert out == 2 and sentinel.stats() == {}

    def test_check_array_catches_nan_and_inf(self, clean_sentinel):
        sentinel.enable()
        assert sentinel.check_array("e", np.ones(3, np.float32))
        assert not sentinel.check_array(
            "e", np.array([1.0, np.nan], np.float32))
        assert not sentinel.check_array(
            "e", np.array([np.inf], np.float32))
        assert sentinel.stats() == {
            "e": {"checks": 3, "nonfinite": 2}}
        assert sentinel.nonfinite_seen()

    def test_nan_only_lets_mask_infs_through(self, clean_sentinel):
        # top-k pads with -inf: a legitimate sentinel, not corruption
        sentinel.enable()
        assert sentinel.check_array(
            "topk", np.array([1.0, -np.inf], np.float32),
            nan_only=True)
        assert not sentinel.check_array(
            "topk", np.array([np.nan], np.float32), nan_only=True)

    def test_non_float_arrays_never_flag(self, clean_sentinel):
        sentinel.enable()
        assert sentinel.check_array("i", np.array([1, 2], np.int32))
        assert not sentinel.nonfinite_seen()

    def test_checked_call_attributes_a_device_nan(self, clean_sentinel):
        import jax
        import jax.numpy as jnp

        sentinel.enable()
        fn = jax.jit(lambda x: x * 2.0)
        clean = sentinel.checked_call(
            "solve", fn, jnp.ones(4, jnp.float32))
        np.testing.assert_allclose(np.asarray(clean), 2.0)
        sentinel.checked_call(
            "solve", fn, jnp.array([1.0, np.nan], jnp.float32))
        assert sentinel.stats()["solve"] == {
            "checks": 2, "nonfinite": 1}

    def test_checked_call_degrades_for_untraceable_callables(
            self, clean_sentinel):
        # when checkify can't wrap/trace a callable, checked_call
        # falls back to a plain call + host probe of the result —
        # prime the cache the way a failed wrap would leave it
        sentinel.enable()

        def host_fn(x):
            return np.asarray(x) * np.float32(np.nan)

        sentinel._checked_cache[("host", id(host_fn))] = False
        out = sentinel.checked_call("host", host_fn,
                                    np.ones(2, np.float32))
        assert np.isnan(out).all()
        st = sentinel.stats()["host"]
        assert st["checks"] == 1 and st["nonfinite"] == 1

    def test_listener_fan_out_and_errors_swallowed(
            self, clean_sentinel):
        sentinel.enable()
        events = []
        sentinel.add_listener(lambda e, bad: events.append((e, bad)))
        sentinel.add_listener(
            lambda e, bad: (_ for _ in ()).throw(RuntimeError("boom")))
        sentinel.check_array("a", np.array([np.nan], np.float32))
        sentinel.check_array("a", np.ones(1, np.float32))
        assert events == [("a", True), ("a", False)]

    def test_debug_env_arms_the_sentinel(self, clean_sentinel,
                                         monkeypatch):
        monkeypatch.setenv("PTPU_DEBUG_NUMERICS", "1")
        assert sentinel.debug_env()
        monkeypatch.setenv("PTPU_DEBUG_NUMERICS", "0")
        assert not sentinel.debug_env()


# ---------------------------------------------------------------------------
# runtime sentinel: over live HTTP (ServerConfig.debug_numerics)
# ---------------------------------------------------------------------------

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(scope="class")
def numerics_served():
    import urllib.request

    from predictionio_tpu.controller import Context
    from predictionio_tpu.data import DataMap, Event
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.server.engineserver import (
        ServerConfig,
        deploy,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )
    from predictionio_tpu.workflow import run_train

    sentinel.reset_for_tests()
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "numapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(11)
    events, t = [], T0
    for u in range(12):
        for i in rng.choice(12, size=4, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": float(rng.integers(1, 6))}),
                event_time=t))
            t += timedelta(seconds=30)
    es.insert_batch(events, app_id)
    ctx = Context(app_name="numapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("numapp", rank=4, num_iterations=2,
                               seed=5)
    run_train(ctx, engine, ep, engine_id="num", engine_version="1")
    srv = deploy(ctx, engine, ep, engine_id="num", engine_version="1",
                 config=ServerConfig(debug_numerics=True),
                 host="127.0.0.1", port=0)
    srv.start_background()
    yield srv
    srv.shutdown()
    sentinel.reset_for_tests()


def _call(port, method, path, body=None):
    import urllib.request

    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req) as resp:
        raw = resp.read()
        ctype = resp.headers.get("Content-Type", "")
        return resp.status, (json.loads(raw) if "json" in ctype
                             else raw.decode())


class TestSentinelHTTP:
    def test_config_arms_the_global_sentinel(self, numerics_served):
        assert sentinel.active()

    def test_clean_serving_stays_undegraded(self, numerics_served):
        srv = numerics_served
        status, body = _call(srv.port, "POST", "/queries.json",
                             {"user": "u1", "num": 3})
        assert status == 200 and len(body["itemScores"]) == 3
        status, body = _call(srv.port, "GET", "/status.json")
        assert status == 200
        assert body["degraded"]["nonfinite"] is False

    def test_seeded_nan_fold_in_degrades_and_counts(
            self, numerics_served):
        from predictionio_tpu.models.als import ALSParams, fold_in_rows

        srv = numerics_served
        fixed = np.ones((16, 8), np.float32)
        fixed[0, 0] = np.nan        # one poisoned factor row
        params = ALSParams(rank=8, implicit_prefs=True,
                           gather_dtype="bfloat16")
        idx = np.zeros((2, 3), np.int32)    # histories hit row 0
        val = np.ones((2, 3), np.float32)
        cnt = np.full((2,), 3, np.int32)
        fold_in_rows(fixed, idx, val, cnt, params)

        st = sentinel.stats()["fold_in_rows"]
        assert st["checks"] >= 1 and st["nonfinite"] >= 1

        status, body = _call(srv.port, "GET", "/status.json")
        assert status == 200
        assert body["degraded"]["nonfinite"] is True
        assert body["degraded"]["active"] is True

        status, text = _call(srv.port, "GET", "/metrics")
        assert status == 200
        assert 'pio_numerics_checks_total{entry="fold_in_rows"}' \
            in text
        assert 'pio_numerics_nonfinite_total{entry="fold_in_rows"}' \
            in text


# ---------------------------------------------------------------------------
# CLI: ptpu audit-numerics + the check registry
# ---------------------------------------------------------------------------

class TestAuditNumericsCLI:
    def test_list_entries(self, capsys):
        assert main(["audit-numerics", "--list-entries"]) == 0
        out = capsys.readouterr().out
        assert "foldin_update_bf16" in out
        assert "device_topk_int8" in out

    def test_unknown_entry_exits_2(self):
        assert main(["audit-numerics", "--entry", "nope"]) == 2

    def test_subset_json_and_artifact(self, capsys, tmp_path):
        artifact = str(tmp_path / "numerics.json")
        rc = main(["audit-numerics", "--entry",
                   "quantize_serving_model", "--format", "json",
                   "--out", artifact])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        casts = doc["entries"]["quantize_serving_model"]["casts"]
        assert casts["int8->float32"] == 1
        assert os.path.exists(artifact)

    def test_text_format_shows_census(self, capsys):
        rc = main(["audit-numerics", "--entry", "device_topk_int8"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "device_topk_int8" in out
        assert "int8->float32" in out

    def test_write_and_gate_roundtrip(self, tmp_path, capsys):
        path = str(tmp_path / "b.json")
        assert main(["audit-numerics", "--entry",
                     "quantize_serving_model", "--baseline", path,
                     "--write-baseline"]) == 0
        capsys.readouterr()
        assert main(["audit-numerics", "--entry",
                     "quantize_serving_model", "--baseline",
                     path]) == 0

    def test_gate_fails_on_doctored_baseline(self, tmp_path, capsys):
        path = str(tmp_path / "b.json")
        assert main(["audit-numerics", "--entry",
                     "quantize_serving_model", "--baseline", path,
                     "--write-baseline"]) == 0
        doc = na.load_manifest(path)
        del doc["entries"]["quantize_serving_model"]["casts"][
            "int8->float32"]
        with open(path, "w") as fh:
            json.dump(doc, fh)
        capsys.readouterr()
        assert main(["audit-numerics", "--entry",
                     "quantize_serving_model", "--baseline",
                     path]) == 1
        out = capsys.readouterr().out + capsys.readouterr().err
        assert "int8->float32" in out

    def test_numerics_rules_registered_for_check(self):
        assert set(NUMERICS_RULES) <= set(RULES)
        assert "metric-catalog-drift" in RULES

    def test_check_sarif_declares_and_reports_numerics_rules(
            self, tmp_path, capsys):
        bad = tmp_path / "models"
        bad.mkdir()
        (bad / "m.py").write_text(src("""
            import jax.numpy as jnp

            def gram(table):
                shadow = table.astype(jnp.bfloat16)
                return jnp.sum(shadow)
        """))
        assert main(["check", str(tmp_path), "--format",
                     "sarif"]) == 1
        doc = json.loads(capsys.readouterr().out)
        run = doc["runs"][0]
        declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert set(NUMERICS_RULES) <= declared
        assert any(r["ruleId"] == "low-precision-reduction"
                   for r in run["results"])
