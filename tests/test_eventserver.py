"""Event Server REST conformance tests.

Mirrors the reference's ``EventServiceSpec`` and the integration harness's
``eventserver_test.py`` scenarios (auth, single/batch insert with the
partially-malformed batch semantics, filtered reads, stats, webhooks).
"""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage.base import AccessKey, App, Channel
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.server.eventserver import build_app, create_event_server
from predictionio_tpu.server.http import Request


def make_storage() -> Storage:
    st = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY"})
    app_id = st.apps().insert(App(id=0, name="testapp", description=None))
    st.access_keys().insert(
        AccessKey(key="KEY1", app_id=app_id, events=[]))
    st.access_keys().insert(
        AccessKey(key="KEYLIMITED", app_id=app_id, events=["rate"]))
    st.channels().insert(Channel(id=0, name="chan1", app_id=app_id))
    return st


@pytest.fixture()
def server():
    st = make_storage()
    srv = create_event_server(st, host="127.0.0.1", port=0, stats=True)
    srv.start_background()
    yield srv
    srv.shutdown()


def call(srv, method, path, body=None, headers=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


EVENT = {"event": "rate", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 4.5},
         "eventTime": "2024-01-02T03:04:05.678Z"}


def test_status_alive(server):
    status, body = call(server, "GET", "/")
    assert status == 200 and body == {"status": "alive"}


def test_post_requires_auth(server):
    assert call(server, "POST", "/events.json", EVENT)[0] == 401
    assert call(server, "POST", "/events.json?accessKey=WRONG", EVENT)[0] == 401


def test_basic_auth_header(server):
    import base64
    creds = base64.b64encode(b"KEY1:").decode()
    status, body = call(server, "POST", "/events.json", EVENT,
                        {"Authorization": f"Basic {creds}"})
    assert status == 201 and "eventId" in body


def test_post_get_delete_roundtrip(server):
    status, body = call(server, "POST", "/events.json?accessKey=KEY1", EVENT)
    assert status == 201
    eid = body["eventId"]

    status, got = call(server, "GET", f"/events/{eid}.json?accessKey=KEY1")
    assert status == 200
    assert got["event"] == "rate" and got["entityId"] == "u1"
    assert got["properties"] == {"rating": 4.5}
    assert got["eventTime"] == "2024-01-02T03:04:05.678Z"

    status, _ = call(server, "DELETE", f"/events/{eid}.json?accessKey=KEY1")
    assert status == 200
    status, _ = call(server, "GET", f"/events/{eid}.json?accessKey=KEY1")
    assert status == 404


def test_allowed_events_enforced(server):
    status, _ = call(server, "POST", "/events.json?accessKey=KEYLIMITED", EVENT)
    assert status == 201
    bad = dict(EVENT, event="buy")
    status, body = call(server, "POST", "/events.json?accessKey=KEYLIMITED", bad)
    assert status == 403 and "not allowed" in body["message"]


def test_malformed_event_400(server):
    status, _ = call(server, "POST", "/events.json?accessKey=KEY1",
                     {"entityType": "user"})
    assert status == 400


def test_channel_resolution(server):
    status, _ = call(server, "POST",
                     "/events.json?accessKey=KEY1&channel=chan1", EVENT)
    assert status == 201
    # channel-scoped read sees it; default channel does not
    status, body = call(server, "GET",
                        "/events.json?accessKey=KEY1&channel=chan1")
    assert status == 200 and len(body) == 1
    status, _ = call(server, "GET", "/events.json?accessKey=KEY1")
    assert status == 404
    status, _ = call(server, "POST",
                     "/events.json?accessKey=KEY1&channel=nope", EVENT)
    assert status == 401


def test_get_events_filters(server):
    for i in range(5):
        e = dict(EVENT, entityId=f"u{i}",
                 eventTime=f"2024-01-0{i + 1}T00:00:00.000Z")
        assert call(server, "POST", "/events.json?accessKey=KEY1", e)[0] == 201
    status, body = call(server, "GET", "/events.json?accessKey=KEY1")
    assert status == 200 and len(body) == 5
    status, body = call(
        server, "GET",
        "/events.json?accessKey=KEY1&startTime=2024-01-03T00:00:00.000Z")
    assert len(body) == 3
    status, body = call(server, "GET",
                        "/events.json?accessKey=KEY1&entityId=u2")
    assert len(body) == 1 and body[0]["entityId"] == "u2"
    status, body = call(server, "GET", "/events.json?accessKey=KEY1&limit=2")
    assert len(body) == 2
    # reversed requires entityType+entityId
    status, _ = call(server, "GET",
                     "/events.json?accessKey=KEY1&reversed=true")
    assert status == 400


def test_batch_semantics(server):
    batch = [
        EVENT,                                   # ok
        {"entityType": "user"},                  # malformed → 400
        {"event": "$delete", "entityType": "user",
         "entityId": "u9"},                      # ok (special event)
    ]
    status, body = call(server, "POST", "/batch/events.json?accessKey=KEY1",
                        batch)
    assert status == 200
    assert [r["status"] for r in body] == [201, 400, 201]
    assert "eventId" in body[0] and "message" in body[1]

    too_many = [EVENT] * 51
    status, body = call(server, "POST", "/batch/events.json?accessKey=KEY1",
                        too_many)
    assert status == 400


def test_batch_allowed_events(server):
    batch = [dict(EVENT, event="buy"), EVENT]
    status, body = call(server, "POST",
                        "/batch/events.json?accessKey=KEYLIMITED", batch)
    assert [r["status"] for r in body] == [403, 201]


def test_stats(server):
    call(server, "POST", "/events.json?accessKey=KEY1", EVENT)
    status, body = call(server, "GET", "/stats.json?accessKey=KEY1")
    assert status == 200
    assert body["basic"][0]["value"] == 1
    assert body["statusCode"][0] == {"key": 201, "value": 1}


def test_stats_disabled_404():
    st = make_storage()
    srv = create_event_server(st, host="127.0.0.1", port=0, stats=False)
    srv.start_background()
    try:
        status, body = call(srv, "GET", "/stats.json?accessKey=KEY1")
        assert status == 404 and "--stats" in body["message"]
    finally:
        srv.shutdown()


def test_webhook_segmentio(server):
    payload = {"type": "track", "version": "2", "user_id": "u42",
               "timestamp": "2024-05-06T07:08:09.000Z",
               "event": "signup", "properties": {"plan": "pro"}}
    status, body = call(server, "POST",
                        "/webhooks/segmentio.json?accessKey=KEY1", payload)
    assert status == 201
    eid = body["eventId"]
    _, got = call(server, "GET", f"/events/{eid}.json?accessKey=KEY1")
    assert got["event"] == "track"
    assert got["entityType"] == "user" and got["entityId"] == "u42"
    assert got["properties"]["event"] == "signup"
    assert got["properties"]["properties"] == {"plan": "pro"}
    assert got["eventTime"] == "2024-05-06T07:08:09.000Z"

    status, _ = call(server, "GET",
                     "/webhooks/segmentio.json?accessKey=KEY1")
    assert status == 200
    status, _ = call(server, "GET", "/webhooks/nope.json?accessKey=KEY1")
    assert status == 404


def test_webhook_mailchimp_form(server):
    import urllib.parse
    form = {
        "type": "subscribe", "fired_at": "2009-03-26 21:35:57",
        "data[id]": "8a25ff1d98", "data[list_id]": "a6b5da1054",
        "data[email]": "api@mailchimp.com", "data[email_type]": "html",
        "data[merges][EMAIL]": "api@mailchimp.com",
        "data[merges][FNAME]": "MailChimp", "data[merges][LNAME]": "API",
        "data[ip_opt]": "10.20.10.30", "data[ip_signup]": "10.20.10.30",
    }
    data = urllib.parse.urlencode(form).encode()
    url = (f"http://127.0.0.1:{server.port}"
           "/webhooks/mailchimp.form?accessKey=KEY1")
    req = urllib.request.Request(url, data=data, method="POST")
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 201
        eid = json.loads(resp.read())["eventId"]
    _, got = call(server, "GET", f"/events/{eid}.json?accessKey=KEY1")
    assert got["event"] == "subscribe"
    assert got["entityId"] == "8a25ff1d98"
    assert got["targetEntityType"] == "list"
    assert got["properties"]["merges"]["FNAME"] == "MailChimp"
    assert got["eventTime"] == "2009-03-26T21:35:57.000Z"


def test_input_blocker_plugin():
    from predictionio_tpu.server.plugins import (
        EventServerPlugin,
        EventServerPlugins,
    )

    class RejectAll(EventServerPlugin):
        plugin_name = "rejectall"

        def process(self, app_id, channel_id, event):
            raise ValueError("blocked by plugin")

    st = make_storage()
    plugins = EventServerPlugins()
    plugins.register(RejectAll(), blocker=True)
    app = build_app(st, plugins=plugins)
    resp = app.handle(Request(
        method="POST", path="/events.json", query={"accessKey": "KEY1"},
        headers={}, body=json.dumps(EVENT).encode()))
    assert resp.status == 500

    resp = app.handle(Request(method="GET", path="/plugins.json", query={},
                              headers={}, body=b""))
    assert "rejectall" in json.loads(resp.encoded())["plugins"]["inputblockers"]


class TestEventPluginREST:
    def test_plugin_rest_authenticated(self):
        """/plugins/<type>/<name>/<args> is key-authenticated and passes
        (appId, channelId, args) to handle_rest (EventServer.scala:174)."""
        from predictionio_tpu.server.http import AppServer
        from predictionio_tpu.server.plugins import (
            EventServerPlugin,
            EventServerPlugins,
        )

        st = make_storage()
        app_id = st.apps().get_by_name("testapp").id

        class EchoPlugin(EventServerPlugin):
            plugin_name = "echo"
            plugin_description = "echoes REST context"

            def process(self, app_id, channel_id, event):
                pass

            def handle_rest(self, app_id, channel_id, args):
                return {"appId": app_id, "channelId": channel_id,
                        "args": args}

        plugins = EventServerPlugins()
        plugins.register(EchoPlugin(), blocker=True)
        psrv = AppServer(build_app(st, plugins=plugins),
                         "127.0.0.1", 0).start_background()
        try:
            status, body = call(psrv, "GET",
                                "/plugins/inputblockers/echo/x/y"
                                "?accessKey=KEY1")
            assert status == 200
            assert body == {"appId": app_id, "channelId": None,
                            "args": ["x", "y"]}
            status, _ = call(psrv, "GET",
                             "/plugins/inputblockers/echo/x")
            assert status == 401  # no key
            status, _ = call(psrv, "GET",
                             "/plugins/inputblockers/nope?accessKey=KEY1")
            assert status == 404
        finally:
            psrv.shutdown()
