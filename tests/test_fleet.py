"""Fleet observability plane tests (ISSUE 17, docs/fleet.md):
lossless histogram merging (merge-of-splits equals the whole
population, bucket for bucket), registry JSON export fidelity, the
Space-Saving hot-key sketch's guarantees, and the FleetAggregator's
merge semantics — counter sums with reset compensation, per-replica
gauge labels with min/max/sum rollups over live replicas only, the
pio_slo_* merge skip, and cross-replica trace fan-out — all through an
injected fetch, no sockets."""

import json
import math
import time

import numpy as np
import pytest

from predictionio_tpu.fleet import FleetAggregator, FleetConfig
from predictionio_tpu.obs import (
    MetricsRegistry,
    SpaceSaving,
    StreamingHistogram,
    mount_hot_key_metrics,
)
from predictionio_tpu.server.http import HTTPError

from test_observability import validate_exposition

BOUNDS = [0.001, 0.01, 0.1, 1.0, 10.0]


def _hist_of(samples, bounds=BOUNDS) -> StreamingHistogram:
    h = StreamingHistogram(bounds)
    for v in samples:
        h.record(float(v))
    return h


# ---------------------------------------------------------------------------
# StreamingHistogram.merge / from_buckets — the federation primitive
# ---------------------------------------------------------------------------

def _splits(samples):
    """Adversarial partitions of one population: however the fleet's
    observations land on replicas, the merge must reconstruct the
    pooled distribution exactly."""
    s = list(samples)
    third = len(s) // 3
    yield "round_robin", [s[0::3], s[1::3], s[2::3]]
    srt = sorted(s)  # each replica sees a disjoint latency regime
    yield "sorted_thirds", [srt[:third], srt[third:2 * third],
                            srt[2 * third:]]
    yield "one_replica_idle", [s, [], []]
    yield "singleton_heavy", [s[:1], s[1:2], s[2:]]


class TestHistogramMerge:
    def test_merge_of_splits_equals_whole_population(self):
        rng = np.random.default_rng(42)
        samples = rng.lognormal(mean=-3.0, sigma=1.5, size=2000)
        whole = _hist_of(samples)
        for label, parts in _splits(samples):
            merged = StreamingHistogram(BOUNDS)
            for part in parts:
                merged.merge(_hist_of(part))
            assert merged.bucket_counts() == whole.bucket_counts(), label
            assert merged.count == whole.count
            assert merged.sum == pytest.approx(whole.sum)
            assert merged.min == whole.min
            assert merged.max == whole.max
            for q in (0.5, 0.9, 0.99, 0.999):
                # identical buckets ⇒ identical interpolation: the
                # merged quantile IS the pooled-population quantile
                assert merged.quantile(q) == whole.quantile(q), label

    def test_average_of_percentiles_is_not_the_answer(self):
        # the two-regime counterexample (docs/fleet.md): one fast
        # replica, one slow replica — the pooled p99 lives in the slow
        # regime, the average of per-replica p99s in neither
        fast = _hist_of([0.002] * 99 + [0.004])
        slow = _hist_of([0.5] * 50)
        merged = StreamingHistogram(BOUNDS)
        merged.merge(fast)
        merged.merge(slow)
        pooled = merged.quantile(0.99)
        avg = (fast.quantile(0.99) + slow.quantile(0.99)) / 2
        assert pooled > 0.1            # in the slow regime
        assert avg < 0.6 * pooled      # nowhere near it

    def test_merge_empty_and_into_empty(self):
        h = _hist_of([0.05, 0.2])
        h.merge(StreamingHistogram(BOUNDS))
        assert h.count == 2
        e = StreamingHistogram(BOUNDS)
        e.merge(h)
        assert e.bucket_counts() == h.bucket_counts()
        assert e.min == h.min and e.max == h.max

    def test_merge_bounds_mismatch_raises(self):
        with pytest.raises(ValueError):
            _hist_of([0.1]).merge(StreamingHistogram([1.0, 2.0]))

    def test_from_buckets_roundtrip(self):
        h = _hist_of([0.0005, 0.05, 0.05, 0.7, 42.0])
        rebuilt = StreamingHistogram.from_buckets(
            h.bucket_counts(), sum=h.sum, minimum=h.min, maximum=h.max)
        assert rebuilt.bucket_counts() == h.bucket_counts()
        assert rebuilt.count == h.count
        assert rebuilt.sum == pytest.approx(h.sum)
        assert rebuilt.quantile(0.9) == h.quantile(0.9)

    def test_from_buckets_estimates_missing_summaries(self):
        h = StreamingHistogram.from_buckets(
            [(0.1, 2), (1.0, 3), (math.inf, 3)])
        assert h.count == 3
        assert 0.0 <= h.min <= 0.1
        assert 0.1 <= h.max <= 1.0
        assert h.sum > 0.0

    def test_from_buckets_validation(self):
        with pytest.raises(ValueError):
            StreamingHistogram.from_buckets([(math.inf, 1)])
        with pytest.raises(ValueError):  # last bucket must be +Inf
            StreamingHistogram.from_buckets([(0.1, 1), (1.0, 2)])
        with pytest.raises(ValueError):  # cumulative counts regress
            StreamingHistogram.from_buckets(
                [(0.1, 5), (1.0, 3), (math.inf, 6)])

    def test_from_buckets_accepts_exported_inf_string(self):
        h = StreamingHistogram.from_buckets([[0.1, 1], ["+Inf", 2]])
        assert h.count == 2


# ---------------------------------------------------------------------------
# Space-Saving hot-key sketch
# ---------------------------------------------------------------------------

class TestSpaceSaving:
    def test_exact_under_capacity(self):
        s = SpaceSaving(capacity=8)
        for k, n in [("a", 5), ("b", 3), ("c", 1)]:
            for _ in range(n):
                s.record(k)
        top = s.top()
        assert [(t["key"], t["count"], t["error"]) for t in top] == [
            ("a", 5.0, 0.0), ("b", 3.0, 0.0), ("c", 1.0, 0.0)]
        assert s.total == 9.0

    def test_eviction_overestimates_within_error(self):
        # a 2-slot sketch over a heavy hitter and noise: the heavy
        # hitter must survive with count ≥ truth, and every reported
        # count minus its error is a lower bound on the truth
        s = SpaceSaving(capacity=2)
        truth = {"hot": 0}
        for i in range(200):
            s.record("hot")
            truth["hot"] += 1
            s.record(f"noise{i}")
        top = {t["key"]: t for t in s.top()}
        assert "hot" in top
        hot = top["hot"]
        assert hot["count"] >= truth["hot"]
        assert hot["count"] - hot["error"] <= truth["hot"]
        assert s.total == 400.0

    def test_ignores_empty_keys(self):
        s = SpaceSaving(capacity=4)
        s.record(None)
        s.record("")
        assert s.total == 0.0 and s.top() == []

    def test_merge_items_conserves_totals(self):
        a = SpaceSaving(capacity=8)
        b = SpaceSaving(capacity=8)
        for _ in range(10):
            a.record("x")
        for _ in range(4):
            b.record("x")
        for _ in range(6):
            b.record("y")
        fleet = SpaceSaving(capacity=8)
        for sk in (a, b):
            snap = sk.snapshot()
            fleet.merge_items(snap["top"], total=snap["total"])
        top = {t["key"]: t["count"] for t in fleet.top()}
        assert top == {"x": 14.0, "y": 6.0}
        assert fleet.total == 20.0

    def test_collector_exports_ranked_gauges(self):
        reg = MetricsRegistry()
        s = SpaceSaving(capacity=4)
        for _ in range(3):
            s.record("u1")
        s.record("u2")
        mount_hot_key_metrics(reg, s, top_n=2)
        text = reg.render()
        validate_exposition(text)
        assert 'pio_hot_keys{key="u1",rank="1"} 3' in text
        assert 'pio_hot_keys{key="u2",rank="2"} 1' in text


# ---------------------------------------------------------------------------
# registry JSON export (the scrape wire format)
# ---------------------------------------------------------------------------

class TestRegistryExport:
    def test_export_shapes(self):
        reg = MetricsRegistry()
        reg.counter("t_total", "c").labels(route="/q").inc(3)
        reg.gauge("t_g", "g").set(1.5)
        reg.histogram("t_h", "h", bounds=[0.1, 1.0]).observe(0.05)
        out = reg.export()
        assert out["t_total"]["kind"] == "counter"
        assert out["t_total"]["children"] == [
            {"labels": {"route": "/q"}, "value": 3.0}]
        assert out["t_g"]["children"][0]["value"] == 1.5
        hist = out["t_h"]["children"][0]
        assert hist["count"] == 1 and hist["sum"] == pytest.approx(0.05)
        assert hist["buckets"][-1][0] == "+Inf"
        # the export is exact: rebuilding from it reproduces the
        # histogram the replica held
        rebuilt = StreamingHistogram.from_buckets(
            hist["buckets"], sum=hist["sum"],
            minimum=hist["min"], maximum=hist["max"])
        assert rebuilt.count == 1

    def test_export_is_json_safe(self):
        import json as _json

        reg = MetricsRegistry()
        reg.histogram("t_h", "h", bounds=[0.1]).observe(5.0)
        _json.dumps(reg.export())


# ---------------------------------------------------------------------------
# FleetAggregator merge semantics (injected fetch, no sockets)
# ---------------------------------------------------------------------------

def _replica_registry(queries: float, lat, gauge_val: float,
                      hot=None) -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("pio_http_requests_total", "req").labels(
        route="/queries.json", status="200").inc(queries)
    h = reg.histogram("pio_http_request_duration_seconds", "lat",
                      bounds=BOUNDS).labels(route="/queries.json")
    for v in lat:
        h.observe(v)
    reg.gauge("pio_inflight_requests", "inflight").set(gauge_val)
    # a replica-local SLO verdict: must NEVER merge (_MERGE_SKIP)
    reg.gauge("pio_slo_burn_rate", "local verdict").labels(
        slo="queries", window="fast").set(9.0)
    return reg


class _Fleet:
    """Three fake replicas behind an injected fetch. Tests mutate
    ``self.regs``/``self.status``/``self.traces`` and call
    ``agg.scrape_cycle()``."""

    def __init__(self, **cfg):
        self.regs = {
            "r0": _replica_registry(10, [0.002] * 4, 1.0),
            "r1": _replica_registry(20, [0.002, 0.5], 2.0),
            "r2": _replica_registry(30, [5.0], 4.0),
        }
        self.status = {n: {"servingWarm": True} for n in self.regs}
        self.traces = {}          # name → {trace_id: body}
        self.dead = set()
        self.agg = FleetAggregator(
            FleetConfig(replicas=list(self.regs),
                        slo_interval_sec=0.0, **cfg),
            fetch=self._fetch)

    def _fetch(self, url, timeout):
        name = url.split("://", 1)[1].split("/", 1)[0]
        if name in self.dead:
            raise OSError(f"{name} is down")
        path = url.split(name, 1)[1]
        if path == "/metrics.json":
            return 200, self.regs[name].export()
        if path == "/status.json":
            return 200, self.status[name]
        if path.startswith("/trace.json?id="):
            tid = path.split("=", 1)[1]
            body = self.traces.get(name, {}).get(tid)
            return (200, body) if body else (404, {"error": "gone"})
        raise AssertionError(f"unexpected fetch {url}")

    def value(self, family, **labels):
        fam = self.agg.registry.get(family)
        assert fam is not None, family
        want = tuple(sorted(labels.items()))
        for items, child in fam.children():
            if items == want:
                return child
        raise AssertionError(f"{family}{labels} not in merged registry")


class TestFleetAggregator:
    def test_counters_sum_exactly(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        child = f.value("pio_http_requests_total",
                        route="/queries.json", status="200")
        assert child.value == 60.0
        # quiescent second cycle: delta-based merge adds nothing
        f.agg.scrape_cycle()
        assert child.value == 60.0

    def test_counter_reset_compensation(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        # r0 restarts: counter starts over at 4 — the merged series
        # must absorb the full new value, not a negative delta
        f.regs["r0"] = _replica_registry(4, [], 1.0)
        f.agg.scrape_cycle()
        child = f.value("pio_http_requests_total",
                        route="/queries.json", status="200")
        assert child.value == 64.0
        resets = f.value("pio_fleet_counter_resets_total", replica="r0")
        assert resets.value >= 1.0

    def test_histograms_merge_to_pooled_population(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        merged = f.value("pio_http_request_duration_seconds",
                         route="/queries.json")
        whole = _hist_of([0.002] * 5 + [0.5, 5.0])
        assert merged.bucket_counts() == whole.bucket_counts()
        assert merged.quantile(0.99) == whole.quantile(0.99)
        # growth on one replica arrives as a delta, not a re-count
        f.regs["r1"].get("pio_http_request_duration_seconds").labels(
            route="/queries.json").observe(0.002)
        f.agg.scrape_cycle()
        assert merged.count == whole.count + 1

    def test_histogram_reset_keeps_merged_monotone(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        merged = f.value("pio_http_request_duration_seconds",
                         route="/queries.json")
        before = merged.count
        f.regs["r2"] = _replica_registry(1, [0.01, 0.01], 4.0)
        f.agg.scrape_cycle()
        assert merged.count == before + 2

    def test_gauges_get_replica_labels_and_rollups(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        assert f.value("pio_inflight_requests", replica="r1").value == 2.0
        assert f.value("pio_inflight_requests", agg="min").value == 1.0
        assert f.value("pio_inflight_requests", agg="max").value == 4.0
        assert f.value("pio_inflight_requests", agg="sum").value == 7.0

    def test_slo_families_never_merge(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        assert f.agg.registry.get("pio_slo_burn_rate") is None

    def test_down_replica_leaves_rollups_and_up_gauge(self):
        f = _Fleet(stale_after_sec=0.01)
        f.agg.scrape_cycle()
        f.dead.add("r2")
        time.sleep(0.03)
        f.agg.scrape_cycle()
        assert f.value("pio_fleet_replica_up", replica="r2").value == 0.0
        assert f.value("pio_fleet_replica_up", replica="r0").value == 1.0
        assert f.value("pio_inflight_requests", agg="sum").value == 3.0
        assert f.value("pio_inflight_requests", agg="max").value == 2.0
        status = f.agg.fleet_status()
        assert status["replicasUp"] == 2
        by_name = {r["replica"]: r for r in status["replicas"]}
        assert by_name["r2"]["up"] is False
        assert by_name["r2"]["lastError"]

    def test_merged_exposition_is_valid(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        validate_exposition(f.agg.registry.render())

    def test_hot_keys_union_over_live_replicas(self):
        f = _Fleet()
        f.status["r0"]["hotKeys"] = {
            "capacity": 8, "total": 10.0,
            "top": [{"key": "u1", "count": 7.0, "error": 0.0},
                    {"key": "u2", "count": 3.0, "error": 0.0}]}
        f.status["r1"]["hotKeys"] = {
            "capacity": 8, "total": 5.0,
            "top": [{"key": "u1", "count": 5.0, "error": 0.0}]}
        f.agg.scrape_cycle()
        top = {t["key"]: t["count"] for t in f.agg.hot.top()}
        assert top == {"u1": 12.0, "u2": 3.0}
        assert f.agg.hot.total == 15.0
        # rebuilt (not accumulated) each cycle: cumulative replica
        # sketches must not double-count
        f.agg.scrape_cycle()
        assert f.agg.hot.total == 15.0

    def test_trace_fanout_finds_the_holding_replica(self):
        f = _Fleet()
        f.traces["r1"] = {"feed" * 8: {"traceEvents": [{"name": "q"}]}}
        found = f.agg.trace_lookup("feed" * 8)
        assert found["replica"] == "r1"
        assert found["trace"]["traceEvents"]

    def test_trace_fanout_404s_when_nowhere(self):
        f = _Fleet()
        with pytest.raises(HTTPError) as err:
            f.agg.trace_lookup("dead" * 8)
        assert err.value.status == 404

    def test_trace_fanout_survives_a_dead_replica(self):
        f = _Fleet()
        f.dead.add("r0")
        f.traces["r2"] = {"beef" * 8: {"traceEvents": []}}
        assert f.agg.trace_lookup("beef" * 8)["replica"] == "r2"

    def test_needs_at_least_one_replica(self):
        with pytest.raises(ValueError):
            FleetAggregator(FleetConfig(replicas=[]))


# ---------------------------------------------------------------------------
# Dynamic membership + draining lifecycle (ISSUE 18 satellite: a
# draining replica must leave rollups and the headroom denominator
# without pio_fleet_replica_up flap or counter-reset noise)
# ---------------------------------------------------------------------------

class TestFleetMembership:
    def test_draining_leaves_rollups_without_up_flap(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        f.status["r2"]["lifecycle"] = "draining"
        f.agg.scrape_cycle()
        # still scraped, still up — just not serving
        assert f.value("pio_fleet_replica_up", replica="r2").value == 1.0
        assert f.value("pio_inflight_requests", agg="sum").value == 3.0
        assert f.value("pio_inflight_requests", agg="max").value == 2.0
        assert f.value("pio_fleet_replicas", state="draining").value == 1.0
        status = f.agg.fleet_status()
        assert status["replicasDraining"] == 1
        assert status["replicasUp"] == 3          # no up flap
        by_name = {r["replica"]: r for r in status["replicas"]}
        assert by_name["r2"]["lifecycle"] == "draining"

    def test_draining_replica_departs_without_error_noise(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        f.status["r2"]["lifecycle"] = "draining"
        f.agg.scrape_cycle()
        f.dead.add("r2")                   # drained and terminated
        outcomes = f.agg.scrape_cycle()
        assert outcomes["r2"] == "departed"
        # expected exit: no scrape-error counter, no lingering gauges
        fam = f.agg.registry.get("pio_fleet_scrapes_total")
        errs = {dict(i).get("replica"): c.value for i, c in
                fam.children() if dict(i).get("outcome") == "error"}
        assert "r2" not in errs
        with pytest.raises(AssertionError):
            f.value("pio_fleet_replica_up", replica="r2")
        names = {r["replica"] for r in f.agg.replica_summaries()}
        assert names == {"r0", "r1"}
        assert f.agg.replica_health("r2") == "absent"

    def test_add_replica_joins_the_merge(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        f.regs["r3"] = _replica_registry(5, [0.01], 8.0)
        f.status["r3"] = {"servingWarm": True}
        assert f.agg.replica_health("r3") == "absent"
        f.agg.add_replica("r3")
        assert f.agg.replica_health("r3") == "unknown"  # not yet scraped
        f.agg.scrape_cycle()
        assert f.agg.replica_health("r3") == "up"
        child = f.value("pio_http_requests_total",
                        route="/queries.json", status="200")
        assert child.value == 65.0
        assert f.value("pio_inflight_requests", agg="sum").value == 15.0
        assert f.value("pio_fleet_replicas",
                       state="configured").value == 4.0

    def test_remove_drops_gauges_keeps_counter_history(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        assert f.agg.remove_replica("r1")
        with pytest.raises(AssertionError):
            f.value("pio_inflight_requests", replica="r1")
        f.agg.scrape_cycle()
        # merged counters are monotone history: r1's contribution stays
        child = f.value("pio_http_requests_total",
                        route="/queries.json", status="200")
        assert child.value == 60.0
        assert f.value("pio_inflight_requests", agg="sum").value == 5.0
        assert f.value("pio_fleet_replicas",
                       state="configured").value == 2.0

    def test_rejoin_resumes_anchors_without_double_count(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        f.agg.remove_replica("r1")
        f.agg.scrape_cycle()
        f.agg.add_replica("r1")
        f.agg.scrape_cycle()
        child = f.value("pio_http_requests_total",
                        route="/queries.json", status="200")
        assert child.value == 60.0       # NOT 80: anchors restored
        f.regs["r1"].get("pio_http_requests_total").labels(
            route="/queries.json", status="200").inc(5)
        f.agg.scrape_cycle()
        assert child.value == 65.0       # growth arrives as a delta

    def test_merge_invariant_across_membership_churn(self):
        # the fleet total must equal the sum of what every member
        # ever contributed, with replicas joining and leaving
        # between scrape cycles
        f = _Fleet()
        f.agg.scrape_cycle()                              # 60
        f.regs["r3"] = _replica_registry(5, [], 0.5)
        f.status["r3"] = {"servingWarm": True}
        f.agg.add_replica("r3")
        f.agg.scrape_cycle()                              # +5
        f.agg.remove_replica("r0")
        f.agg.scrape_cycle()
        f.agg.add_replica("r0")
        f.regs["r0"].get("pio_http_requests_total").labels(
            route="/queries.json", status="200").inc(2)
        f.agg.scrape_cycle()                              # +2
        child = f.value("pio_http_requests_total",
                        route="/queries.json", status="200")
        assert child.value == 67.0
        validate_exposition(f.agg.registry.render())

    def test_replica_health_down_when_stale(self):
        f = _Fleet(stale_after_sec=0.01)
        f.agg.scrape_cycle()
        f.dead.add("r2")
        time.sleep(0.03)
        f.agg.scrape_cycle()
        assert f.agg.replica_health("r2") == "down"
        assert f.agg.replica_health("r0") == "up"

    def test_capacity_signals_without_model(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        sig = f.agg.capacity_signals()
        assert sig["kneeQps"] is None
        assert sig["headroom"] is None   # no model ≠ infinite room

    def test_capacity_signals_with_model(self, tmp_path):
        cap = tmp_path / "CAPACITY.json"
        cap.write_text(json.dumps({"configs": {
            "default": {"knee_qps": 100.0}}}))
        f = _Fleet(capacity_path=str(cap))
        f.agg.scrape_cycle()
        sig = f.agg.capacity_signals()
        assert sig["kneeQps"] == 100.0
        assert sig["headroom"] == pytest.approx(1.0)   # idle fleet

    def test_headroom_denominator_excludes_draining(self, tmp_path):
        cap = tmp_path / "CAPACITY.json"
        cap.write_text(json.dumps({"configs": {
            "default": {"knee_qps": 100.0}}}))
        f = _Fleet(capacity_path=str(cap))
        f.agg.scrape_cycle()
        assert f.agg.capacity_signals()["headroom"] == pytest.approx(1.0)
        for name in f.status:
            f.status[name]["lifecycle"] = "draining"
        f.agg.scrape_cycle()
        # every replica's capacity is leaving: zero serving replicas
        # is the over-capacity sentinel, not "100% headroom"
        assert f.agg.capacity_signals()["headroom"] == -1.0

    def test_fleet_status_reports_autoscale_block(self):
        f = _Fleet()
        f.agg.scrape_cycle()
        assert f.agg.fleet_status()["autoscale"] == {"enabled": False}
