"""FastEvalEngine prefix memoization (mirrors FastEvalEngineTest's
cache-hit counting), SelfCleaningDataSource compaction, and
PersistentModel custom persistence."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import (
    Algorithm,
    Context,
    DataSource,
    Engine,
    EventWindow,
    FastEvalEngine,
    FirstServing,
    IdentityPreparator,
    LocalFileSystemPersistentModel,
    PersistentModelManifest,
    SelfCleaningDataSource,
    Serving,
)
from predictionio_tpu.controller.params import EngineParams
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)

MEM_ENV = {
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
}


# ---------------------------------------------------------------------------
# FastEvalEngine — instrumented fixture engine (the reference's Engine0
# family, core/src/test/.../SampleEngine.scala)
# ---------------------------------------------------------------------------

CALLS = {"read_eval": 0, "prepare": 0, "train": 0, "serve": 0}


class CountingDataSource(DataSource):
    def __init__(self, params=None):
        self.params = params or {}

    def read_training(self, ctx):
        return [1, 2, 3]

    def read_eval(self, ctx):
        CALLS["read_eval"] += 1
        # two folds; outputs encode params so results are checkable
        return [([1, 2], {"fold": 0}, [(10, 100), (20, 200)]),
                ([3, 4], {"fold": 1}, [(30, 300)])]


class CountingPreparator(IdentityPreparator):
    def __init__(self, params=None):
        self.params = params or {}

    def prepare(self, ctx, td):
        CALLS["prepare"] += 1
        return td


class ParamAlgo(Algorithm):
    """Prediction = query * factor (encodes its params, Engine0 style)."""

    def __init__(self, params=None):
        self.factor = (params or {}).get("factor", 1)

    def train(self, ctx, pd):
        CALLS["train"] += 1
        return {"factor": self.factor}

    def predict(self, model, q):
        return q * model["factor"]


class CountingServing(Serving):
    def __init__(self, params=None):
        self.params = params or {}

    def serve(self, q, ps):
        CALLS["serve"] += 1
        return ps[0]


def fixture_engine() -> Engine:
    return Engine(
        datasource_classes=CountingDataSource,
        preparator_classes=CountingPreparator,
        algorithm_classes={"algo": ParamAlgo, "": ParamAlgo},
        serving_classes=CountingServing,
    )


def ep(factor: int, serving_params=None) -> EngineParams:
    return EngineParams(
        datasource=("", {}),
        preparator=("", {}),
        algorithms=[("algo", {"factor": factor})],
        serving=("", serving_params or {}))


class TestFastEvalEngine:
    def setup_method(self):
        for k in CALLS:
            CALLS[k] = 0

    def test_algorithm_sweep_shares_prefix(self):
        ctx = Context(app_name="x", _storage=Storage(env=MEM_ENV))
        fe = FastEvalEngine.from_engine(fixture_engine())
        params = [ep(1), ep(2), ep(3)]
        results = fe.batch_eval(ctx, params)
        # datasource read + prepare ran ONCE for the whole sweep
        assert CALLS["read_eval"] == 1
        assert CALLS["prepare"] == 2   # once per fold, shared across sweep
        assert CALLS["train"] == 3 * 2  # per variant per fold — no sharing
        assert fe.workflow_for(ctx).miss_counts == {
            "datasource": 1, "preparator": 1, "algorithms": 3, "serving": 3}
        # results encode the right factor per variant
        for (p, folds), factor in zip(results, (1, 2, 3)):
            (ei0, qpa0), _ = folds
            assert [pred for _, pred, _ in qpa0] == [10 * factor,
                                                     20 * factor]

    def test_identical_params_fully_cached(self):
        ctx = Context(app_name="x", _storage=Storage(env=MEM_ENV))
        fe = FastEvalEngine.from_engine(fixture_engine())
        fe.batch_eval(ctx, [ep(2), ep(2), ep(2)])
        assert CALLS["read_eval"] == 1
        assert CALLS["train"] == 1 * 2  # one variant × two folds

    def test_serving_only_sweep_reuses_predictions(self):
        ctx = Context(app_name="x", _storage=Storage(env=MEM_ENV))
        fe = FastEvalEngine.from_engine(fixture_engine())
        fe.batch_eval(ctx, [ep(2, {"s": 1}), ep(2, {"s": 2})])
        assert CALLS["train"] == 2      # one variant's algo prefix, 2 folds
        assert CALLS["serve"] == 3 * 2  # 3 queries × 2 serving variants

    def test_plain_engine_recomputes(self):
        ctx = Context(app_name="x", _storage=Storage(env=MEM_ENV))
        engine = fixture_engine()
        engine.batch_eval(ctx, [ep(1), ep(2)])
        assert CALLS["read_eval"] == 2  # no memoization on the base engine


# ---------------------------------------------------------------------------
# SelfCleaningDataSource
# ---------------------------------------------------------------------------

class CleaningDS(SelfCleaningDataSource):
    def __init__(self, window):
        self._window = window
        self.app_name = "cleanapp"

    @property
    def event_window(self):
        return self._window


def _ev(event, eid, t, props=None, **kw):
    return Event(event=event, entity_type="user", entity_id=eid,
                 properties=DataMap(props or {}), event_time=t, **kw)


class TestSelfCleaningDataSource:
    def test_window_filter_keeps_set_events(self):
        now = T0 + timedelta(days=10)
        ds = CleaningDS(EventWindow(duration="2 days"))
        events = [
            _ev("view", "u1", T0),                       # old, dropped
            _ev("$set", "u1", T0, {"a": 1}),             # old but $set: kept
            _ev("view", "u2", now - timedelta(hours=1)),  # recent: kept
        ]
        out = ds.filter_window(events, now=now)
        assert [e.event for e in out] == ["$set", "view"]

    def test_compress_properties(self):
        ds = CleaningDS(EventWindow(compress_properties=True))
        events = [
            _ev("$set", "u1", T0, {"a": 1, "b": 2}),
            _ev("$set", "u1", T0 + timedelta(minutes=1), {"b": 3}),
            _ev("$unset", "u1", T0 + timedelta(minutes=2), {"a": 0}),
            _ev("view", "u1", T0 + timedelta(minutes=3)),
            _ev("$set", "u2", T0, {"z": 9}),
        ]
        out = ds.clean_events(events)
        sets = {e.entity_id: e for e in out if e.event == "$set"}
        assert sets["u1"].properties.to_dict() == {"b": 3}  # a unset, b=3
        assert sets["u2"].properties.to_dict() == {"z": 9}
        assert sum(1 for e in out if e.event == "view") == 1

    def test_remove_duplicates_keeps_earliest(self):
        ds = CleaningDS(EventWindow(remove_duplicates=True))
        events = [
            _ev("view", "u1", T0 + timedelta(minutes=5), event_id="late"),
            _ev("view", "u1", T0, event_id="early"),
            _ev("view", "u2", T0),  # different entity: not a duplicate
        ]
        out = ds.clean_events(events)
        ids = {e.event_id for e in out}
        assert "early" in ids and "late" not in ids
        assert len(out) == 2

    def test_clean_persisted_events_rewrites_store(self):
        storage = Storage(env=MEM_ENV)
        app_id = storage.apps().insert(App(0, "cleanapp"))
        storage.events().init(app_id)
        events = [
            _ev("$set", "u1", T0, {"a": 1}),
            _ev("$set", "u1", T0 + timedelta(minutes=1), {"a": 2}),
            _ev("view", "u1", T0 + timedelta(minutes=2)),
            _ev("view", "u1", T0 + timedelta(minutes=2)),  # duplicate
        ]
        storage.events().insert_batch(events, app_id)
        ctx = Context(app_name="cleanapp", _storage=storage)
        ds = CleaningDS(EventWindow(remove_duplicates=True,
                                    compress_properties=True))
        removed = ds.clean_persisted_events(ctx)
        assert removed >= 2
        remaining = list(ctx.event_store.find("cleanapp"))
        sets = [e for e in remaining if e.event == "$set"]
        views = [e for e in remaining if e.event == "view"]
        assert len(sets) == 1 and sets[0].properties.to_dict() == {"a": 2}
        assert len(views) == 1


# ---------------------------------------------------------------------------
# PersistentModel
# ---------------------------------------------------------------------------

class MyModel(LocalFileSystemPersistentModel):
    def __init__(self, weights):
        self.weights = weights


class PMAlgo(Algorithm):
    def __init__(self, params=None):
        pass

    def train(self, ctx, pd):
        return MyModel(np.arange(4.0))

    def predict(self, model, q):
        return float(model.weights.sum()) + q


class PMDataSource(DataSource):
    def __init__(self, params=None):
        pass

    def read_training(self, ctx):
        return "td"


class TestPersistentModel:
    def test_manifest_roundtrip_through_workflow(self, tmp_path,
                                                 monkeypatch):
        from predictionio_tpu.workflow import (
            get_latest_completed,
            load_models_for_deploy,
            run_train,
        )

        monkeypatch.setenv("PIO_HOME", str(tmp_path))
        storage = Storage(env=MEM_ENV)
        ctx = Context(app_name="pm", _storage=storage)
        engine = Engine(
            datasource_classes=PMDataSource,
            preparator_classes=IdentityPreparator,
            algorithm_classes=PMAlgo,
            serving_classes=FirstServing)
        params = EngineParams()
        iid = run_train(ctx, engine, params, engine_id="pm")
        # what's stored is a manifest, not the model
        import pickle
        blob = storage.models().get(iid)
        stored = pickle.loads(blob.models)
        assert isinstance(stored[0], PersistentModelManifest)
        assert stored[0].class_name.endswith("MyModel")
        # deploy loads through the manifest
        inst = get_latest_completed(ctx, engine_id="pm")
        models = load_models_for_deploy(ctx, engine, inst, params)
        assert isinstance(models[0], MyModel)
        np.testing.assert_array_equal(models[0].weights, np.arange(4.0))

    def test_load_type_mismatch_raises(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_HOME", str(tmp_path))
        MyModel(np.ones(2)).save("inst1", 0)

        class Other(LocalFileSystemPersistentModel):
            pass

        with pytest.raises(TypeError):
            Other.load("inst1", 0)
