"""Runtime concurrency layer: DebugLock order-graph/inversion/re-entry
detection, the deadlock watchdog's stack dump, pio_lock_* metric
emission, the zero-overhead disabled path, and an instrumented stress
run over the real serving-cache stack."""

import logging
import threading
import time
from dataclasses import dataclass

import pytest

from predictionio_tpu.concurrency import (
    DebugLock,
    LockRegistry,
    dump_all_stacks,
    instrument_locks,
    lock_registry,
    locks_instrumented,
    new_lock,
    new_rlock,
    register_lock_metrics,
)
from predictionio_tpu.concurrency.locks import _env_enabled


@pytest.fixture()
def restore_instrumentation():
    """Save/restore the global instrumentation flag around tests that
    flip it (the CI instrumented run has it ON for the whole suite)."""
    was = locks_instrumented()
    yield
    instrument_locks(was)


class TestFactories:
    def test_disabled_returns_plain_stdlib_locks(
            self, restore_instrumentation):
        # the acceptance bar: disabled means the literal stdlib type —
        # no wrapper, no overhead path at all
        instrument_locks(False)
        assert type(new_lock("x")) is type(threading.Lock())
        assert type(new_rlock("x")) is type(threading.RLock())

    def test_enabled_returns_debuglock(self, restore_instrumentation):
        instrument_locks(True)
        lock = new_lock("TestFactories.lock")
        rlock = new_rlock("TestFactories.rlock")
        assert isinstance(lock, DebugLock) and not lock.reentrant
        assert isinstance(rlock, DebugLock) and rlock.reentrant

    def test_env_flag_parsing(self, monkeypatch):
        for val, expect in (("1", True), ("true", True), ("on", True),
                            ("0", False), ("", False), ("no", False)):
            monkeypatch.setenv("PTPU_DEBUG_LOCKS", val)
            assert _env_enabled() is expect, val


class TestInversionDetection:
    def _cross(self, reg):
        """Two threads acquiring {A, B} in opposite orders, staggered
        so both acquisitions succeed (the graph, not an actual
        deadlock, must catch it)."""
        a = DebugLock("A", registry=reg, watchdog_sec=30)
        b = DebugLock("B", registry=reg, watchdog_sec=30)
        done = threading.Event()

        def t1():
            with a:
                with b:
                    pass
            done.set()

        def t2():
            done.wait(timeout=10)  # strictly after t1 finished
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start()
        th2.start()
        th1.join(timeout=10)
        th2.join(timeout=10)

    def test_intentional_inversion_detected(self):
        reg = LockRegistry()
        self._cross(reg)
        assert len(reg.inversions) == 1
        inv = reg.inversions[0]
        assert inv["held"] == "B" and inv["acquiring"] == "A"
        assert inv["prior_site"] != "?"

    def test_inversion_reported_once_per_pair(self):
        reg = LockRegistry()
        self._cross(reg)
        self._cross(reg)
        assert len(reg.inversions) == 1

    def test_consistent_order_is_clean(self):
        reg = LockRegistry()
        a = DebugLock("A", registry=reg)
        b = DebugLock("B", registry=reg)

        def worker():
            for _ in range(50):
                with a:
                    with b:
                        pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert reg.inversions == []
        report = reg.report()
        assert report["acquisitions"] >= 400
        assert report["edges"] == {"A": ["B"]}


class TestReentry:
    def test_nonreentrant_reentry_raises_and_is_recorded(self):
        reg = LockRegistry()
        lock = DebugLock("L", registry=reg)
        with pytest.raises(RuntimeError, match="re-entry"):
            with lock:
                with lock:
                    pass
        assert len(reg.reentries) == 1
        assert reg.reentries[0]["lock"] == "L"
        # the failed inner acquire must not have corrupted the outer
        # hold: the lock is released and reusable
        with lock:
            pass

    def test_rlock_reentry_is_fine(self):
        reg = LockRegistry()
        lock = DebugLock("R", reentrant=True, registry=reg)
        with lock:
            with lock:
                with lock:
                    pass
        assert reg.reentries == []
        with lock:  # still usable, depth fully unwound
            pass


class TestWatchdog:
    def test_long_wait_dumps_all_stacks_to_access_log(self, caplog):
        reg = LockRegistry()
        lock = DebugLock("W", registry=reg, watchdog_sec=0.15)
        release = threading.Event()
        held = threading.Event()

        def holder():
            with lock:
                held.set()
                release.wait(timeout=10)

        th = threading.Thread(target=holder, name="wd-holder")
        th.start()
        held.wait(timeout=10)
        with caplog.at_level(logging.ERROR, "predictionio_tpu.access"):
            def waiter():
                with lock:
                    pass

            tw = threading.Thread(target=waiter, name="wd-waiter")
            tw.start()
            time.sleep(0.4)  # > watchdog threshold while still blocked
            release.set()
            tw.join(timeout=10)
        th.join(timeout=10)
        assert reg.report()["watchdogDumps"] >= 1
        dump = "\n".join(r.getMessage() for r in caplog.records
                         if "lock watchdog" in r.getMessage())
        assert "'W'" in dump
        assert "wd-holder" in dump  # the holder's stack is in the dump
        assert "release.wait" in dump  # ...down to the blocking line

    def test_dump_all_stacks_returns_formatted_block(self):
        block = dump_all_stacks(
            reason="unit probe",
            logger=logging.getLogger("tests.watchdog"))
        assert "unit probe" in block
        assert threading.current_thread().name in block

    def test_timeout_acquire_still_honored(self):
        reg = LockRegistry()
        lock = DebugLock("T", registry=reg, watchdog_sec=0.1)
        release = threading.Event()

        def holder():
            with lock:
                release.wait(timeout=10)

        th = threading.Thread(target=holder)
        th.start()
        time.sleep(0.05)
        t0 = time.monotonic()
        assert lock.acquire(timeout=0.3) is False
        assert 0.2 < time.monotonic() - t0 < 2.0
        assert lock.acquire(blocking=False) is False
        release.set()
        th.join(timeout=10)


class TestLockMetrics:
    def test_pio_lock_series_emitted(self, restore_instrumentation):
        from predictionio_tpu.obs import MetricsRegistry

        instrument_locks(True)
        reg = lock_registry()
        lock = new_lock("TestLockMetrics.lock")
        for _ in range(5):
            with lock:
                pass
        metrics = MetricsRegistry()
        register_lock_metrics(metrics)
        text = metrics.render()
        for series in ("pio_lock_instrumented 1",
                       "pio_lock_acquisitions",
                       "pio_lock_contention_total",
                       "pio_lock_inversions_total",
                       "pio_lock_reentries_total",
                       "pio_lock_watchdog_dumps_total"):
            assert series in text, series
        assert 'pio_lock_wait_seconds_bucket{lock="TestLockMetrics.lock"' \
            in text
        assert 'pio_lock_hold_seconds_count{lock="TestLockMetrics.lock"}' \
            in text
        snapshot_count = [
            line for line in text.splitlines()
            if line.startswith("pio_lock_hold_seconds_count"
                               '{lock="TestLockMetrics.lock"}')]
        assert int(float(snapshot_count[0].split()[-1])) >= 5
        assert reg.report()["acquisitions"] >= 5

    def test_contention_counted(self):
        reg = LockRegistry()
        lock = DebugLock("C", registry=reg, watchdog_sec=30)
        release = threading.Event()

        def holder():
            with lock:
                release.wait(timeout=10)

        th = threading.Thread(target=holder)
        th.start()
        time.sleep(0.05)

        def contender():
            with lock:
                pass

        tc = threading.Thread(target=contender)
        tc.start()
        time.sleep(0.05)
        release.set()
        tc.join(timeout=10)
        th.join(timeout=10)
        assert reg.report()["contended"] >= 1
        assert reg.report()["contentionByLock"].get("C", 0) >= 1


# ---------------------------------------------------------------------------
# instrumented serving-stack stress: the real cache hierarchy under
# concurrent serve/ingest/flush traffic must record ZERO inversions
# ---------------------------------------------------------------------------

@dataclass
class _EchoQuery:
    user: str = "u0"
    v: int = 0


class _EchoAlgo:
    query_class = _EchoQuery

    def bind_serving(self, ctx):
        pass

    def prepare_serving_model(self, model, max_batch):
        return model

    def predict(self, model, query):
        return {"user": query.user, "doubled": query.v * 2}


class _EchoServing:
    def supplement(self, query):
        return query

    def serve(self, query, predictions):
        return predictions[0]


class _EchoEngine:
    def make_algorithms(self, engine_params):
        return [_EchoAlgo()]

    def make_serving(self, engine_params):
        return _EchoServing()


def _echo_server(**config_kwargs):
    from predictionio_tpu.data.event import utcnow
    from predictionio_tpu.data.storage.base import EngineInstance
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
    )

    class _Ctx:
        storage = None

    now = utcnow()
    instance = EngineInstance(id="i1", status="COMPLETED",
                              start_time=now, end_time=now,
                              engine_id="echo", engine_version="1",
                              engine_variant="engine.json",
                              engine_factory="tests:echo")
    cfg = ServerConfig(warm_start=False, **config_kwargs)
    return QueryServer(_Ctx(), _EchoEngine(), engine_params=None,
                       models=[None], instance=instance, config=cfg)


class TestInstrumentedServingStack:
    def test_debug_locks_config_flag_instruments_the_stack(
            self, restore_instrumentation):
        instrument_locks(False)
        server = _echo_server(debug_locks=True, serving_cache=True)
        assert locks_instrumented()
        assert isinstance(server._lock, DebugLock)
        assert isinstance(server.cache.flight._lock, DebugLock)
        assert isinstance(
            server.cache.query._shards[0].lock, DebugLock)
        # lock metrics are mounted on the server's registry
        assert "pio_lock_instrumented 1" in server.metrics.render()

    def test_stress_serve_ingest_flush_zero_inversions(
            self, restore_instrumentation):
        from predictionio_tpu.cache import InvalidationBus, ServingCache

        instrument_locks(True)
        reg = lock_registry()
        base_inv = len(reg.inversions)
        bus = InvalidationBus()
        cache = ServingCache(query_entries=64, query_ttl_sec=5.0,
                             hot_capacity=8, hot_refresh_every=4,
                             pin_fn=lambda keys: ({k: 1 for k in keys},
                                                  8 * len(keys)),
                             bus=bus)
        stop = threading.Event()
        errors = []

        def serve_loop(i):
            try:
                n = 0
                while not stop.is_set():
                    n += 1
                    key = ("ns", f"q{i}-{n % 7}")
                    token = cache.epoch_token(f"user:u{n % 5}")
                    found, _ = cache.query.lookup(key)
                    if not found:
                        cache.put_query_fresh(
                            key, {"n": n}, (f"user:u{n % 5}",), token)
                    if cache.hot is not None:
                        cache.hot.record(f"u{n % 5}")
                        cache.hot.lookup(f"u{n % 5}")
            except Exception as e:  # noqa: BLE001 — surface in-test
                errors.append(e)

        def ingest_loop():
            try:
                n = 0
                while not stop.is_set():
                    n += 1
                    bus.publish(1, "user", f"u{n % 5}", "view")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def flush_loop():
            try:
                while not stop.is_set():
                    cache.flush_all()
                    cache.stats()
                    time.sleep(0.01)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = ([threading.Thread(target=serve_loop, args=(i,))
                    for i in range(4)]
                   + [threading.Thread(target=ingest_loop),
                      threading.Thread(target=flush_loop)])
        for t in threads:
            t.start()
        time.sleep(0.8)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert errors == []
        assert reg.inversions[base_inv:] == []
        assert reg.report()["acquisitions"] > 1000

    def test_stress_query_server_promote_swap_zero_inversions(
            self, restore_instrumentation):
        instrument_locks(False)
        server = _echo_server(debug_locks=True, serving_cache=True,
                              hot_entities=8, hot_refresh_every=4)
        reg = lock_registry()
        base_inv = len(reg.inversions)
        stop = threading.Event()
        errors = []

        def serve_loop(i):
            try:
                n = 0
                while not stop.is_set():
                    n += 1
                    result = server.serve(
                        {"user": f"u{n % 5}", "v": n % 11})
                    assert result["doubled"] == (n % 11) * 2
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def rebind_loop():
            # the promote-swap hot spot: _bind under the server lock
            # flushes every cache tier (nested acquisition) while
            # serve() traffic fills them in the other order of events
            try:
                while not stop.is_set():
                    server._bind(server.engine_params, [None],
                                 server.instance)
                    time.sleep(0.02)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = ([threading.Thread(target=serve_loop, args=(i,))
                    for i in range(4)]
                   + [threading.Thread(target=rebind_loop)])
        for t in threads:
            t.start()
        time.sleep(0.8)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert errors == []
        assert reg.inversions[base_inv:] == []
        assert reg.reentries == []
