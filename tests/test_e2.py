"""e2 library tests, value-matched to the reference's e2 test suite
(``e2/src/test/scala/org/apache/predictionio/e2/engine/*Test.scala``,
``…/evaluation/CrossValidationTest.scala``)."""

import math

import numpy as np
import pytest

from predictionio_tpu.e2 import (
    BinaryVectorizer,
    CategoricalNaiveBayesModel,
    LabeledPoint,
    MarkovChainModel,
    split_data,
    train_markov_chain,
    train_naive_bayes,
)

TOL = 1e-4

BANANA, ORANGE, OTHER = "Banana", "Orange", "Other Fruit"
LONG, NOT_LONG = "Long", "Not Long"
SWEET, NOT_SWEET = "Sweet", "Not Sweet"
YELLOW, NOT_YELLOW = "Yellow", "Not Yellow"

FRUIT_POINTS = [
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [NOT_LONG, NOT_SWEET, NOT_YELLOW]),
    LabeledPoint(ORANGE, [NOT_LONG, SWEET, NOT_YELLOW]),
    LabeledPoint(ORANGE, [NOT_LONG, NOT_SWEET, NOT_YELLOW]),
    LabeledPoint(OTHER, [LONG, SWEET, NOT_YELLOW]),
    LabeledPoint(OTHER, [NOT_LONG, SWEET, NOT_YELLOW]),
    LabeledPoint(OTHER, [LONG, SWEET, YELLOW]),
    LabeledPoint(OTHER, [NOT_LONG, NOT_SWEET, NOT_YELLOW]),
]


@pytest.fixture(scope="module")
def fruit_model() -> CategoricalNaiveBayesModel:
    return train_naive_bayes(FRUIT_POINTS)


class TestCategoricalNaiveBayes:
    # CategoricalNaiveBayesTest.scala "have log priors and log likelihoods"
    def test_priors(self, fruit_model):
        assert fruit_model.prior(BANANA) == pytest.approx(-.7885, abs=TOL)
        assert fruit_model.prior(ORANGE) == pytest.approx(-1.7047, abs=TOL)
        assert fruit_model.prior(OTHER) == pytest.approx(-1.0116, abs=TOL)

    def test_likelihoods(self, fruit_model):
        m = fruit_model
        assert m.likelihood(BANANA, 0, LONG) == pytest.approx(-.2231, abs=TOL)
        assert m.likelihood(BANANA, 0, NOT_LONG) == pytest.approx(
            -1.6094, abs=TOL)
        assert m.likelihood(BANANA, 1, SWEET) == pytest.approx(-.2231, abs=TOL)
        assert m.likelihood(BANANA, 2, YELLOW) == pytest.approx(
            -.2231, abs=TOL)
        # value never observed under a label → absent, not merely small
        assert m.likelihood(ORANGE, 0, LONG) is None
        assert m.likelihood(ORANGE, 0, NOT_LONG) == pytest.approx(0.0, abs=TOL)
        assert m.likelihood(ORANGE, 1, SWEET) == pytest.approx(-.6931, abs=TOL)
        assert m.likelihood(ORANGE, 2, NOT_YELLOW) == pytest.approx(
            0.0, abs=TOL)
        assert m.likelihood(ORANGE, 2, YELLOW) is None
        assert m.likelihood(OTHER, 1, SWEET) == pytest.approx(-.2877, abs=TOL)
        assert m.likelihood(OTHER, 2, NOT_YELLOW) == pytest.approx(
            -.2877, abs=TOL)

    # "be the log score of the given point"
    def test_log_score(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, [LONG, NOT_SWEET, NOT_YELLOW]))
        assert score == pytest.approx(-4.2304, abs=TOL)

    # "be negative infinity for a point with a non-existing feature"
    def test_log_score_unknown_feature(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, [LONG, NOT_SWEET, "Not Exist"]))
        assert score == float("-inf")

    # "be none for a point with a non-existing label"
    def test_log_score_unknown_label(self, fruit_model):
        assert fruit_model.log_score(
            LabeledPoint("Not Exist", [LONG, NOT_SWEET, YELLOW])) is None

    # "use the provided default likelihood function"
    def test_default_likelihood(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, [LONG, NOT_SWEET, "Not Exist"]),
            default_likelihood=lambda ls: math.log(1e-9))
        assert score is not None and score != float("-inf")
        assert score == pytest.approx(
            fruit_model.prior(BANANA)
            + fruit_model.likelihood(BANANA, 0, LONG)
            + fruit_model.likelihood(BANANA, 1, NOT_SWEET)
            + math.log(1e-9), abs=TOL)

    def test_predict(self, fruit_model):
        assert fruit_model.predict([LONG, SWEET, YELLOW]) == BANANA

    def test_predict_batch_matches_pointwise(self, fruit_model):
        batch = [p.features for p in FRUIT_POINTS]
        got = fruit_model.predict_batch(batch)
        want = [fruit_model.predict(f) for f in batch]
        assert got == want

    def test_pickle_after_predict_batch(self, fruit_model):
        import pickle

        fruit_model.predict_batch([[LONG, SWEET, YELLOW]])
        clone = pickle.loads(pickle.dumps(fruit_model))
        assert clone.predict_batch([[LONG, SWEET, YELLOW]]) == [BANANA]


class TestMarkovChain:
    # MarkovChainTest.scala fixtures
    def test_two_by_two(self):
        model = train_markov_chain(
            rows=[0, 0, 1, 1], cols=[0, 1, 0, 1],
            tallies=[3, 7, 10, 10], n_states=2, top_n=2)
        assert model.n == 2
        assert model.row(0) == [(0, pytest.approx(0.3)),
                                (1, pytest.approx(0.7))]
        assert model.row(1) == [(0, pytest.approx(0.5)),
                                (1, pytest.approx(0.5))]

    def test_top_n_only_normalized_by_full_total(self):
        rows = [0, 0, 1, 1, 1, 1, 1, 2, 2, 2, 3, 3, 3, 4, 4, 4]
        cols = [1, 2, 0, 1, 2, 3, 4, 1, 2, 4, 0, 3, 4, 1, 3, 4]
        tallies = [12, 8, 3, 3, 9, 2, 8, 10, 8, 10, 2, 3, 4, 7, 8, 10]
        model = train_markov_chain(rows, cols, tallies, n_states=5, top_n=2)
        assert model.row(0) == [(1, pytest.approx(.6)),
                                (2, pytest.approx(.4))]
        assert model.row(1) == [(2, pytest.approx(9 / 25)),
                                (4, pytest.approx(8 / 25))]
        # tie at 10: keep lower column index (1 before 4)
        assert model.row(2) == [(1, pytest.approx(10 / 28)),
                                (4, pytest.approx(10 / 28))]
        assert model.row(3) == [(3, pytest.approx(3 / 9)),
                                (4, pytest.approx(4 / 9))]
        assert model.row(4) == [(3, pytest.approx(8 / 25)),
                                (4, pytest.approx(.4))]

    def test_predict(self):
        model = train_markov_chain(
            rows=[0, 0, 1, 1], cols=[0, 1, 0, 1],
            tallies=[3, 7, 10, 10], n_states=2, top_n=2)
        nxt = model.predict([0.4, 0.6])
        np.testing.assert_allclose(nxt, [0.42, 0.58], atol=1e-6)

    def test_pickle_after_predict(self):
        import pickle

        model = train_markov_chain(
            rows=[0, 0, 1, 1], cols=[0, 1, 0, 1],
            tallies=[3, 7, 10, 10], n_states=2, top_n=2)
        model.predict([0.4, 0.6])  # populates the jit cache
        clone = pickle.loads(pickle.dumps(model))
        np.testing.assert_allclose(clone.predict([0.4, 0.6]),
                                   [0.42, 0.58], atol=1e-6)


class TestBinaryVectorizer:
    # BinaryVectorizerTest.scala semantics
    def test_from_pairs_and_to_binary(self):
        vz = BinaryVectorizer.from_pairs(
            [("food", "orange"), ("food", "banana"), ("mood", "happy")])
        assert vz.num_features == 3
        np.testing.assert_array_equal(
            vz.to_binary([("food", "banana"), ("mood", "happy")]),
            [0.0, 1.0, 1.0])
        # unknown pairs ignored
        np.testing.assert_array_equal(
            vz.to_binary([("food", "kiwi"), ("height", "tall")]),
            [0.0, 0.0, 0.0])

    def test_from_maps_filters_properties(self):
        vz = BinaryVectorizer.from_maps(
            [{"food": "orange", "height": "tall"},
             {"food": "banana", "mood": "happy"}],
            properties={"food", "mood"})
        assert vz.num_features == 3  # height excluded
        assert set(vz.properties) == {
            ("food", "orange"), ("food", "banana"), ("mood", "happy")}

    def test_to_matrix(self):
        vz = BinaryVectorizer.from_pairs([("a", "1"), ("b", "2")])
        m = vz.to_matrix([[("a", "1")], [("b", "2"), ("a", "1")], []])
        np.testing.assert_array_equal(
            m, [[1, 0], [1, 1], [0, 0]])


class TestCrossValidation:
    # CrossValidationTest.scala: fold i's test points are idx % k == i
    def test_split_data(self):
        data = list(range(10))
        folds = split_data(
            eval_k=3, dataset=data, evaluator_info="info",
            training_data_creator=list,
            query_creator=lambda d: ("q", d),
            actual_creator=lambda d: ("a", d))
        assert len(folds) == 3
        for fold_idx, (td, ei, qa) in enumerate(folds):
            assert ei == "info"
            test_points = [d for i, d in enumerate(data)
                           if i % 3 == fold_idx]
            assert [q for q, _ in qa] == [("q", d) for d in test_points]
            assert [a for _, a in qa] == [("a", d) for d in test_points]
            assert td == [d for i, d in enumerate(data)
                          if i % 3 != fold_idx]
            assert len(td) + len(qa) == len(data)
