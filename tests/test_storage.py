"""Storage-backend conformance suite.

The reference duplicated `LEventsSpec`/`PEventsSpec` per backend
(`storage/jdbc/src/test`, `storage/hbase/src/test`) as the de-facto DAO
contract test; here one parametrized suite runs the same scenarios against
every registered backend.
"""

from datetime import datetime, timedelta, timezone

import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import (
    ANY,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    EventFilter,
    Model,
    STATUS_COMPLETED,
    STATUS_EVALCOMPLETED,
    STATUS_INIT,
    Storage,
)
from predictionio_tpu.data.storage.memory import (
    MemoryAccessKeys,
    MemoryApps,
    MemoryChannels,
    MemoryEngineInstances,
    MemoryEvaluationInstances,
    MemoryEventStore,
    MemoryModels,
)
from predictionio_tpu.data.storage.sqlite import (
    SQLiteAccessKeys,
    SQLiteApps,
    SQLiteChannels,
    SQLiteClient,
    SQLiteEngineInstances,
    SQLiteEvaluationInstances,
    SQLiteEventStore,
    SQLiteModels,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
HOUR = timedelta(hours=1)

APP = 7


def ev(name, eid, t, etype="user", **kw):
    return Event(event=name, entity_type=etype, entity_id=eid,
                 event_time=t, **kw)


@pytest.fixture(params=["memory", "sqlite", "localfs", "segmentfs",
                        "remote"])
def backend(request, tmp_path):
    if request.param == "remote":
        # the network-capable backend: a real storage server (sqlite-
        # backed) on a loopback port, driven through the REMOTE client —
        # same conformance surface as every in-process backend
        from conftest import start_sqlite_backed_storage_server
        from predictionio_tpu.data.storage.remote import (
            RemoteAccessKeys,
            RemoteApps,
            RemoteChannels,
            RemoteClient,
            RemoteEngineInstances,
            RemoteEvaluationInstances,
            RemoteEventStore,
            RemoteModels,
        )
        srv, _ = start_sqlite_backed_storage_server(
            tmp_path, secret="testsecret")
        client = RemoteClient(f"http://127.0.0.1:{srv.port}",
                              secret="testsecret")
        yield {
            "events": RemoteEventStore(client),
            "apps": RemoteApps(client),
            "access_keys": RemoteAccessKeys(client),
            "channels": RemoteChannels(client),
            "engine_instances": RemoteEngineInstances(client),
            "evaluation_instances": RemoteEvaluationInstances(client),
            "models": RemoteModels(client),
        }
        srv.shutdown()
        return
    if request.param == "segmentfs":
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSAccessKeys,
            SegmentFSApps,
            SegmentFSChannels,
            SegmentFSClient,
            SegmentFSEngineInstances,
            SegmentFSEvaluationInstances,
            SegmentFSEventStore,
            SegmentFSModels,
        )
        client = SegmentFSClient(str(tmp_path / "segmentfs"))
        yield {
            "events": SegmentFSEventStore(client),
            "apps": SegmentFSApps(client),
            "access_keys": SegmentFSAccessKeys(client),
            "channels": SegmentFSChannels(client),
            "engine_instances": SegmentFSEngineInstances(client),
            "evaluation_instances": SegmentFSEvaluationInstances(client),
            "models": SegmentFSModels(client),
        }
        client.close()
        return
    if request.param == "localfs":
        from predictionio_tpu.data.storage.localfs import (
            LocalFSAccessKeys,
            LocalFSApps,
            LocalFSChannels,
            LocalFSClient,
            LocalFSEngineInstances,
            LocalFSEvaluationInstances,
            LocalFSEventStore,
            LocalFSModels,
        )
        client = LocalFSClient(str(tmp_path / "localfs"))
        yield {
            "events": LocalFSEventStore(client),
            "apps": LocalFSApps(client),
            "access_keys": LocalFSAccessKeys(client),
            "channels": LocalFSChannels(client),
            "engine_instances": LocalFSEngineInstances(client),
            "evaluation_instances": LocalFSEvaluationInstances(client),
            "models": LocalFSModels(client),
        }
        client.close()
        return
    if request.param == "memory":
        yield {
            "events": MemoryEventStore(),
            "apps": MemoryApps(),
            "access_keys": MemoryAccessKeys(),
            "channels": MemoryChannels(),
            "engine_instances": MemoryEngineInstances(),
            "evaluation_instances": MemoryEvaluationInstances(),
            "models": MemoryModels(),
        }
    else:
        client = SQLiteClient(str(tmp_path / "test.db"))
        yield {
            "events": SQLiteEventStore(client),
            "apps": SQLiteApps(client),
            "access_keys": SQLiteAccessKeys(client),
            "channels": SQLiteChannels(client),
            "engine_instances": SQLiteEngineInstances(client),
            "evaluation_instances": SQLiteEvaluationInstances(client),
            "models": SQLiteModels(client),
        }
        client.close()


class TestEventStoreConformance:
    def test_insert_get_delete(self, backend):
        es = backend["events"]
        es.init(APP)
        e = ev("view", "u1", T0, target_entity_type="item",
               target_entity_id="i1", properties=DataMap({"x": 1}))
        eid = es.insert(e, APP)
        got = es.get(eid, APP)
        assert got is not None
        assert got.event_id == eid
        assert got.entity_id == "u1"
        assert got.target_entity_id == "i1"
        assert got.properties == DataMap({"x": 1})
        assert got.event_time == T0
        assert es.delete(eid, APP) is True
        assert es.get(eid, APP) is None
        assert es.delete(eid, APP) is False

    def test_find_time_ordering_and_filters(self, backend):
        es = backend["events"]
        es.init(APP)
        events = [
            ev("view", "u1", T0 + 2 * HOUR, target_entity_type="item",
               target_entity_id="i2"),
            ev("rate", "u1", T0, target_entity_type="item",
               target_entity_id="i1", properties=DataMap({"rating": 4})),
            ev("view", "u2", T0 + HOUR, target_entity_type="item",
               target_entity_id="i1"),
            ev("$set", "u1", T0 + 3 * HOUR, properties=DataMap({"a": 1})),
        ]
        es.insert_batch(events, APP)

        allv = list(es.find(APP))
        assert [e.event_time for e in allv] == sorted(e.event_time for e in allv)
        assert len(allv) == 4

        rev = list(es.find(APP, filter=EventFilter(reversed=True, limit=2)))
        assert len(rev) == 2
        assert rev[0].event_time == T0 + 3 * HOUR

        u1 = list(es.find(APP, filter=EventFilter(entity_id="u1")))
        assert len(u1) == 3

        views = list(es.find(APP, filter=EventFilter(event_names=["view"])))
        assert len(views) == 2

        window = list(es.find(APP, filter=EventFilter(
            start_time=T0 + HOUR, until_time=T0 + 3 * HOUR)))
        assert len(window) == 2  # until is exclusive, start inclusive

        tgt = list(es.find(APP, filter=EventFilter(target_entity_id="i1")))
        assert len(tgt) == 2
        no_tgt = list(es.find(APP, filter=EventFilter(target_entity_id=None)))
        assert len(no_tgt) == 1 and no_tgt[0].event == "$set"
        any_tgt = list(es.find(APP, filter=EventFilter(target_entity_id=ANY)))
        assert len(any_tgt) == 4

    def test_find_columnar_shard_pushdown(self, backend):
        """``shard=(i, n)`` conformance (VERDICT r3 missing #1): shards
        tile the unfiltered projection — their union (as a multiset of
        rows) equals the full read, both unfiltered and with a filter
        applied within each shard; the batch carries global-row
        bookkeeping."""
        es = backend["events"]
        es.init(APP)
        es.insert_batch(
            [ev("rate" if k % 3 else "buy", f"u{k % 7}", T0 + k * HOUR,
                target_entity_type="item", target_entity_id=f"i{k % 5}",
                properties=DataMap({"rating": float(k % 5 + 1)}))
             for k in range(53)], APP)

        def rows(b):
            return sorted(
                (e.event, e.entity_id, e.target_entity_id,
                 e.event_time.isoformat())
                for e in b.to_events())

        full = es.find_columnar(APP, ordered=False)
        shards = [es.find_columnar(APP, ordered=False, shard=(i, 4))
                  for i in range(4)]
        assert sum(s.n for s in shards) == full.n == 53
        assert max(s.n for s in shards) - min(s.n for s in shards) <= 1
        assert sorted(sum((rows(s) for s in shards), [])) == rows(full)
        offs = sorted(getattr(s, "shard_offset") for s in shards)
        assert offs[0] == 0
        assert all(getattr(s, "shard_total") == 53 for s in shards)

        filt = EventFilter(event_names=["rate"])
        ffull = es.find_columnar(APP, filter=filt, ordered=False)
        fshards = [es.find_columnar(APP, filter=filt, ordered=False,
                                    shard=(i, 4)) for i in range(4)]
        assert sorted(sum((rows(s) for s in fshards), [])) == rows(ffull)

        with pytest.raises(ValueError):
            es.find_columnar(APP, shard=(4, 4))

    def test_channel_isolation(self, backend):
        es = backend["events"]
        es.init(APP)
        es.init(APP, 3)
        es.insert(ev("view", "u1", T0), APP)
        es.insert(ev("buy", "u1", T0), APP, 3)
        assert [e.event for e in es.find(APP)] == ["view"]
        assert [e.event for e in es.find(APP, 3)] == ["buy"]

    def test_app_isolation_and_remove(self, backend):
        es = backend["events"]
        es.init(APP)
        es.init(APP + 1)
        es.insert(ev("view", "u1", T0), APP)
        assert list(es.find(APP + 1)) == []
        assert es.remove(APP)
        assert list(es.find(APP)) == []

    def test_aggregate_properties_through_store(self, backend):
        es = backend["events"]
        es.init(APP)
        es.insert_batch([
            ev("$set", "u1", T0, properties=DataMap({"a": 1, "b": 2})),
            ev("$unset", "u1", T0 + HOUR, properties=DataMap({"b": None})),
            ev("$set", "u2", T0, properties=DataMap({"a": 9})),
            ev("$delete", "u2", T0 + HOUR),
            ev("view", "u1", T0 + 2 * HOUR, target_entity_type="item",
               target_entity_id="i1"),
        ], APP)
        props = es.aggregate_properties(APP, entity_type="user")
        assert set(props) == {"u1"}
        assert props["u1"].to_dict() == {"a": 1}

    def test_aggregate_required_keys(self, backend):
        es = backend["events"]
        es.init(APP)
        es.insert_batch([
            ev("$set", "u1", T0, properties=DataMap({"a": 1})),
            ev("$set", "u2", T0, properties=DataMap({"a": 1, "b": 2})),
        ], APP)
        props = es.aggregate_properties(APP, entity_type="user",
                                        required=["b"])
        assert set(props) == {"u2"}


class TestMetadataConformance:
    def test_apps(self, backend):
        apps = backend["apps"]
        app_id = apps.insert(App(0, "myapp", "desc"))
        assert app_id is not None and app_id > 0
        assert apps.get(app_id).name == "myapp"
        assert apps.get_by_name("myapp").id == app_id
        assert apps.insert(App(0, "myapp")) is None  # duplicate name
        apps.update(App(app_id, "myapp", "newdesc"))
        assert apps.get(app_id).description == "newdesc"
        id2 = apps.insert(App(0, "app2"))
        assert {a.name for a in apps.get_all()} == {"myapp", "app2"}
        apps.delete(app_id)
        assert apps.get(app_id) is None
        assert apps.get(id2) is not None

    def test_access_keys(self, backend):
        keys = backend["access_keys"]
        k = keys.insert(AccessKey("", 1, ["view", "rate"]))
        assert k
        got = keys.get(k)
        assert got.app_id == 1
        assert tuple(got.events) == ("view", "rate")
        k2 = keys.insert(AccessKey("explicit-key", 2, []))
        assert k2 == "explicit-key"
        assert {a.key for a in keys.get_by_app_id(1)} == {k}
        keys.delete(k)
        assert keys.get(k) is None

    def test_channels(self, backend):
        ch = backend["channels"]
        cid = ch.insert(Channel(0, "mychan", 1))
        assert cid is not None
        assert ch.get(cid).name == "mychan"
        assert ch.insert(Channel(0, "bad name!", 1)) is None
        assert ch.insert(Channel(0, "x" * 17, 1)) is None
        assert [c.id for c in ch.get_by_app_id(1)] == [cid]
        ch.delete(cid)
        assert ch.get(cid) is None

    def test_engine_instances_lifecycle(self, backend):
        eis = backend["engine_instances"]
        base = EngineInstance(
            id="", status=STATUS_INIT, start_time=T0, end_time=T0,
            engine_id="eng", engine_version="1", engine_variant="default",
            engine_factory="my.Factory", algorithms_params='[{"als":{}}]')
        i1 = eis.insert(base)
        i2 = eis.insert(base.copy(start_time=T0 + HOUR))
        assert eis.get_latest_completed("eng", "1", "default") is None
        eis.update(eis.get(i1).copy(status=STATUS_COMPLETED))
        eis.update(eis.get(i2).copy(status=STATUS_COMPLETED))
        latest = eis.get_latest_completed("eng", "1", "default")
        assert latest.id == i2
        assert latest.algorithms_params == '[{"als":{}}]'
        assert eis.get_latest_completed("eng", "2", "default") is None
        eis.delete(i1)
        assert eis.get(i1) is None

    def test_evaluation_instances(self, backend):
        evs = backend["evaluation_instances"]
        i = evs.insert(EvaluationInstance(
            id="", status=STATUS_INIT, start_time=T0, end_time=T0,
            evaluation_class="my.Eval"))
        evs.update(evs.get(i).copy(status=STATUS_EVALCOMPLETED,
                                   evaluator_results="metric=0.5"))
        done = evs.get_completed()
        assert [x.id for x in done] == [i]
        assert done[0].evaluator_results == "metric=0.5"

    def test_models(self, backend):
        models = backend["models"]
        models.insert(Model("inst-1", b"\x00\x01binary"))
        assert models.get("inst-1").models == b"\x00\x01binary"
        models.insert(Model("inst-1", b"replaced"))
        assert models.get("inst-1").models == b"replaced"
        models.delete("inst-1")
        assert models.get("inst-1") is None


class TestRegistry:
    def test_default_config_sqlite(self, tmp_path):
        s = Storage(env={"PIO_HOME": str(tmp_path)})
        s.verify_all_data_objects()
        es = s.events()
        es.init(1)
        es.insert(ev("view", "u1", T0), 1)
        assert len(list(es.find(1))) == 1
        s.close()
        # durable across re-open
        s2 = Storage(env={"PIO_HOME": str(tmp_path)})
        assert len(list(s2.events().find(1))) == 1
        s2.close()

    def test_env_config_memory(self):
        s = Storage(env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        s.verify_all_data_objects()
        assert s.apps().insert(App(0, "a")) == 1

    def test_mixed_sources(self, tmp_path):
        s = Storage(env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_SOURCES_DB_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_DB_PATH": str(tmp_path / "m.db"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
        })
        assert isinstance(s.events(), MemoryEventStore)
        assert isinstance(s.apps(), SQLiteApps)
        s.close()

    def test_unknown_source_rejected(self):
        import pytest as _pytest
        from predictionio_tpu.data.storage import StorageError
        with _pytest.raises(StorageError):
            Storage(env={
                "PIO_STORAGE_SOURCES_X_TYPE": "memory",
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NOPE",
            })


class TestLocalFSBackend:
    def test_env_config_and_durability(self, tmp_path):
        env = {
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "store"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
        s = Storage(env=env)
        s.verify_all_data_objects()
        app_id = s.apps().insert(App(0, "fsapp"))
        s.events().init(app_id)
        eid = s.events().insert(ev("view", "u1", T0), app_id)
        s.models().insert(Model(id="m1", models=b"\x00\x01"))
        s.close()
        # a fresh Storage over the same directory sees everything
        s2 = Storage(env=env)
        assert s2.apps().get_by_name("fsapp").id == app_id
        got = s2.events().get(eid, app_id)
        assert got is not None and got.entity_id == "u1"
        assert s2.models().get("m1").models == b"\x00\x01"

    def test_delete_tombstones_survive_reopen(self, tmp_path):
        env = {
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": str(tmp_path / "store"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
        s = Storage(env=env)
        app_id = s.apps().insert(App(0, "tomb"))
        s.events().init(app_id)
        eid = s.events().insert(ev("view", "u1", T0), app_id)
        assert s.events().delete(eid, app_id)
        s.close()
        s2 = Storage(env=env)
        assert s2.events().get(eid, app_id) is None


class TestSegmentFSMultiProcess:
    """The pod story: N OS processes appending to the same SEGMENTFS
    log concurrently (immutable content-addressed segments + locked
    manifest swaps) must lose nothing, and a concurrent reader only
    ever sees fully-published events."""

    def test_concurrent_writers_across_processes(self, tmp_path):
        import subprocess
        import sys
        import textwrap

        root = tmp_path / "shared"
        worker = tmp_path / "w.py"
        worker.write_text(textwrap.dedent("""
            import sys
            from datetime import datetime, timezone
            from predictionio_tpu.data.event import Event
            from predictionio_tpu.data.storage.segmentfs import (
                SegmentFSClient, SegmentFSEventStore)
            pid, root = sys.argv[1], sys.argv[2]
            es = SegmentFSEventStore(SegmentFSClient(root))
            es.init(1)
            for b in range(5):
                es.insert_batch([
                    Event(event="rate", entity_type="user",
                          entity_id=f"p{pid}-b{b}-{i}",
                          event_time=datetime(2024, 1, 1,
                                              tzinfo=timezone.utc))
                    for i in range(20)], 1)
            print("done", pid)
        """))
        import os as _os
        env = dict(_os.environ)
        env["PYTHONPATH"] = _os.pathsep.join(
            [_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(_os.pathsep))
        env.setdefault("JAX_PLATFORMS", "cpu")
        procs = [subprocess.Popen(
            [sys.executable, str(worker), str(i), str(root)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(4)]
        outs = [p.communicate(timeout=120)[0].decode() for p in procs]
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out[-2000:]

        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )

        es = SegmentFSEventStore(SegmentFSClient(str(root)))
        got = {e.entity_id for e in es.find(1)}
        assert len(got) == 4 * 5 * 20  # every event from every process

    def test_compaction_keeps_readers_safe(self, tmp_path):
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )

        es = SegmentFSEventStore(SegmentFSClient(str(tmp_path / "s")))
        es.init(1)
        ids = es.insert_batch([ev("e1", f"x{i}", T0)
                               for i in range(10)], 1)
        for eid in ids[:8]:
            assert es.delete(eid, 1)
        # compaction happened (dead > live); survivors intact
        left = {e.event_id for e in es.find(1)}
        assert left == set(ids[8:])
        # unreferenced segments survive the grace window, then gc
        assert es.gc(1, grace_s=3600) == 0
        n = es.gc(1, grace_s=0.0)
        assert n > 0
        assert {e.event_id for e in es.find(1)} == set(ids[8:])


class TestSegmentFSColumnarSidecar:
    """Round-3 (VERDICT r2 task 3): the pod backend shares one columnar
    sidecar on the shared filesystem — one host encodes, others mmap."""

    def _store(self, td):
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        c = SegmentFSClient(str(td))
        es = SegmentFSEventStore(c)
        es.init(1)
        return es

    def _seed(self, es, n=60, seed=3):
        import numpy as np

        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        rng = np.random.default_rng(seed)
        evs = [Event(event="rate", entity_type="user",
                     entity_id=f"u{int(u)}", target_entity_type="item",
                     target_entity_id=f"i{int(i)}",
                     properties=DataMap({"rating": float(r)}))
               for u, i, r in zip(rng.integers(0, 9, n),
                                  rng.integers(0, 7, n),
                                  rng.integers(1, 6, n))]
        return es.insert_batch(evs, 1)

    def test_columnar_matches_rows_and_second_host_mmaps(self, tmp_path):
        import os

        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        es = self._store(tmp_path)
        self._seed(es)
        b = es.find_columnar(1)
        rows = sorted((e.event, e.entity_id, e.target_entity_id)
                      for e in es.find(1))
        cols = sorted((e.event, e.entity_id, e.target_entity_id)
                      for e in b.to_events())
        assert cols == rows
        # the sidecar landed on the SHARED dir; a fresh client (second
        # host) reuses it without touching the jsonl segments
        es2 = SegmentFSEventStore(SegmentFSClient(str(tmp_path)))
        b2 = es2.find_columnar(1, ordered=False, with_props=False)
        assert b2.n == b.n
        assert es2.c.segment_cache == {}  # no jsonl parse happened
        assert os.path.isdir(str(tmp_path / "events" / "app_1"
                                 / "columnar"))

    def test_delta_append_extends_sidecar(self, tmp_path):
        es = self._store(tmp_path)
        self._seed(es, n=30)
        assert es.find_columnar(1).n == 30
        self._seed(es, n=12, seed=9)
        assert es.find_columnar(1).n == 42

    def test_replace_and_delete_force_rebuild(self, tmp_path):
        from predictionio_tpu.data.datamap import DataMap
        es = self._store(tmp_path)
        ids = self._seed(es, n=25)
        es.find_columnar(1)
        ev = es.get(ids[4], 1)
        es.insert_batch([ev.copy(properties=DataMap({"rating": 9.0}))], 1)
        b = es.find_columnar(1, ordered=False)
        assert b.n == 25
        assert 9.0 in set(b.float_prop("rating"))
        assert es.delete(ids[5], 1)
        assert es.find_columnar(1, ordered=False).n == 24

    def test_aggregation_via_sidecar(self, tmp_path):
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        es = self._store(tmp_path)
        es.insert_batch(
            [Event(event="$set", entity_type="item", entity_id=f"i{k}",
                   properties=DataMap({"cat": f"c{k % 2}"}))
             for k in range(10)], 1)
        props = es.aggregate_properties(1, entity_type="item")
        assert props["i3"]["cat"] == "c1"

    def test_foreign_hash_impl_forces_rebuild(self, tmp_path):
        """A sidecar written by a host with the OTHER bulk_hash64
        implementation (pandas siphash vs blake2b) must be rebuilt, not
        dup-checked against hashes that can never match (advisor r3)."""
        import json

        from predictionio_tpu.data.columnar import hash_impl
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        es = self._store(tmp_path)
        self._seed(es, n=20)
        es.find_columnar(1)
        mpath = (tmp_path / "events" / "app_1" / "columnar"
                 / "manifest.json")
        man = json.loads(mpath.read_text())
        assert man["hash_impl"] == hash_impl()
        old_segs = {s["name"] for s in man["segments"]}
        man["hash_impl"] = ("blake2b" if hash_impl() == "pd" else "pd")
        mpath.write_text(json.dumps(man))
        # a fresh host (cold replay cache) must invalidate + re-encode
        es2 = SegmentFSEventStore(SegmentFSClient(str(tmp_path)))
        b = es2.find_columnar(1, ordered=False)
        assert b.n == 20
        man2 = json.loads(mpath.read_text())
        assert man2["hash_impl"] == hash_impl()
        assert not old_segs & {s["name"] for s in man2["segments"]}

    def test_partial_multichunk_rebuild_self_heals(self, tmp_path,
                                                   monkeypatch):
        """A crash BETWEEN chunk appends of a multi-chunk rebuild must
        not leave a manifest claiming completeness over a partial
        sidecar (advisor r3 medium): intermediate chunks carry a
        sentinel watermark, so the next reader rebuilds and serves the
        full projection."""
        from predictionio_tpu.data import columnar as col_mod
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )

        monkeypatch.setattr(SegmentFSEventStore, "COLUMNAR_CHUNK", 8)
        es = self._store(tmp_path)
        ids = self._seed(es, n=25)
        es.find_columnar(1)
        assert es.delete(ids[3], 1)  # delete ⇒ next sync rebuilds

        real_append = col_mod.SegmentLog.append
        calls = {"n": 0}

        def crashing_append(self, *a, **k):
            calls["n"] += 1
            if calls["n"] == 2:  # die between chunk 1 and chunk 2
                raise RuntimeError("simulated crash mid-rebuild")
            return real_append(self, *a, **k)

        monkeypatch.setattr(col_mod.SegmentLog, "append",
                            crashing_append)
        try:
            es.find_columnar(1, ordered=False)
        except RuntimeError:
            pass
        monkeypatch.setattr(col_mod.SegmentLog, "append", real_append)
        # the partially-rebuilt sidecar must NOT be trusted: a fresh
        # host sees the sentinel watermark, rebuilds, and serves all
        # 24 live events (not the 8 rows of the crashed first chunk)
        es2 = SegmentFSEventStore(SegmentFSClient(str(tmp_path)))
        b = es2.find_columnar(1, ordered=False)
        assert b.n == 24
        rows = sorted((e.event, e.entity_id, e.target_entity_id)
                      for e in es2.find(1))
        cols = sorted((e.event, e.entity_id, e.target_entity_id)
                      for e in b.to_events())
        assert cols == rows

    def test_missing_hash_file_crash_window_self_heals(self, tmp_path):
        """A crash between the sidecar segment commit and its id-hash
        write leaves a hash-less segment; the next sync must rebuild
        (not trust, not crash) and serve the correct projection."""
        import os

        es = self._store(tmp_path)
        self._seed(es, n=30)
        es.find_columnar(1)
        cdir = tmp_path / "events" / "app_1" / "columnar"
        hashes = list(cdir.glob("seg-*/id_hash.npy"))
        assert hashes
        os.unlink(hashes[0])
        self._seed(es, n=10, seed=5)  # delta sync hits the crash window
        b = es.find_columnar(1, ordered=False)
        assert b.n == 40
        rows = sorted((e.event, e.entity_id) for e in es.find(1))
        cols = sorted((e.event, e.entity_id)
                      for e in es.find_columnar(1).to_events())
        assert rows == cols

    def test_rebuild_retires_old_segments_with_grace(self, tmp_path):
        """A rebuild must not unlink sidecar files other hosts may still
        mmap (NFS gives no unlink-keeps-inode guarantee); old segment
        dirs are retired and swept only after the grace window."""
        import os

        from predictionio_tpu.data.columnar import SegmentLog
        es = self._store(tmp_path)
        ids = self._seed(es, n=40)
        es.find_columnar(1)
        cdir = str(tmp_path / "events" / "app_1" / "columnar")
        before = {s for s in os.listdir(cdir) if s.startswith("seg-")}
        es.delete(ids[0], 1)
        assert es.find_columnar(1, ordered=False).n == 39
        after = {s for s in os.listdir(cdir) if s.startswith("seg-")}
        assert before & after, "old segments must survive the rebuild"
        log = SegmentLog(cdir)
        with log.lock():
            assert log.sweep(0.0) >= 1
        assert es.find_columnar(1, ordered=False).n == 39

    def test_sidecar_ahead_of_stale_manifest_view_not_destroyed(
            self, tmp_path):
        """A host whose jsonl-manifest read lags (NFS attribute cache)
        must treat an AHEAD sidecar as newer, never as corrupt."""
        import json as _json
        import os

        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSClient,
            SegmentFSEventStore,
        )
        es = self._store(tmp_path)
        self._seed(es, n=20)
        es.find_columnar(1)
        es2 = SegmentFSEventStore(SegmentFSClient(str(tmp_path)))
        self._seed(es2, n=10, seed=8)
        assert es2.find_columnar(1, ordered=False).n == 30
        # simulate host A's stale view: its cached read path re-reads the
        # manifest under the sidecar lock, so it sees 30 — and the
        # sidecar generation ids (unique names) must be unchanged
        cdir = str(tmp_path / "events" / "app_1" / "columnar")
        man_before = _json.loads(
            open(os.path.join(cdir, "manifest.json")).read())
        assert es.find_columnar(1, ordered=False).n == 30
        man_after = _json.loads(
            open(os.path.join(cdir, "manifest.json")).read())
        assert [s["name"] for s in man_before["segments"]] == \
            [s["name"] for s in man_after["segments"]]


class TestRemoteBackend:
    """REMOTE-specific behavior beyond conformance (VERDICT r2 missing
    #1): env-scheme wiring, ETag-cached bulk reads, auth."""

    @pytest.fixture()
    def served(self, tmp_path):
        from conftest import start_sqlite_backed_storage_server
        srv, _ = start_sqlite_backed_storage_server(tmp_path,
                                                    secret="s3cret")
        yield srv
        srv.shutdown()

    def _env(self, srv):
        return {
            "PIO_STORAGE_SOURCES_NET_TYPE": "remote",
            "PIO_STORAGE_SOURCES_NET_URL": f"http://127.0.0.1:{srv.port}",
            "PIO_STORAGE_SOURCES_NET_SECRET": "s3cret",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NET",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NET",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NET",
        }

    @staticmethod
    def _events(n, seed=0):
        return [ev("rate", f"u{(seed + k) % 9}", T0 + k * HOUR,
                   target_entity_type="item",
                   target_entity_id=f"i{k % 5}",
                   properties=DataMap({"rating": float(k % 5 + 1)}))
                for k in range(n)]

    def test_env_scheme_end_to_end(self, served):
        from predictionio_tpu.data.storage import App, Storage
        s = Storage(env=self._env(served))
        s.verify_all_data_objects()
        app_id = s.apps().insert(App(0, "netapp"))
        s.events().init(app_id)
        s.events().insert_batch(self._events(40, seed=4), app_id)
        got = list(s.events().find(app_id))
        assert len(got) == 40
        b = s.events().find_columnar(app_id, ordered=False,
                                     with_props=False)
        assert b.n == 40

    def test_columnar_etag_cache(self, served):
        from predictionio_tpu.data.storage import App, Storage
        s = Storage(env=self._env(served))
        app_id = s.apps().insert(App(0, "netapp2"))
        s.events().init(app_id)
        s.events().insert_batch(self._events(30, seed=5), app_id)
        es = s.events()
        b1 = es.find_columnar(app_id, ordered=False, with_props=False)
        # second read: server must answer 304 and the client reuse its
        # cached batch object
        cached = es.c.columnar_cache
        key = next(iter(cached))
        etag_before, batch_before = cached[key]
        b2 = es.find_columnar(app_id, ordered=False, with_props=False)
        assert cached[key][1] is batch_before
        # a write invalidates: new etag, more rows
        s.events().insert_batch(self._events(5, seed=6), app_id)
        b3 = es.find_columnar(app_id, ordered=False, with_props=False)
        assert b3.n == 35
        assert cached[key][0] != etag_before

    def test_float_prop_names_escaped_on_wire(self, served):
        """Prop names ride the URL query; '&' must not rewrite the
        query string (advisor r3) and ',' — unrepresentable in the
        comma-joined wire format — is rejected loudly."""
        from predictionio_tpu.data.storage import App, Storage
        s = Storage(env=self._env(served))
        app_id = s.apps().insert(App(0, "netesc"))
        s.events().init(app_id)
        s.events().insert_batch(self._events(10, seed=7), app_id)
        # 'a&b' is quoted on the wire: the request still carries BOTH
        # names (sqlite's alnum gate then drops the unsafe one), so
        # 'rating' survives — unescaped it would truncate the list
        b = s.events().find_columnar(
            app_id, ordered=False, float_props=("a&b", "rating"))
        assert "rating" in b.float_props
        with pytest.raises(ValueError):
            s.events().find_columnar(app_id, float_props=("a,b",))

    def test_shard_request_against_preshard_server_fails_loudly(
            self, served):
        """A pre-shard server ignores shard_i/shard_n and returns the
        FULL log; the client must raise (treating it as a shard would
        feed every rating N times across a pod), not proceed."""
        from predictionio_tpu.data.storage import App, Storage
        from predictionio_tpu.data.storage.base import StorageError
        s = Storage(env=self._env(served))
        app_id = s.apps().insert(App(0, "netold"))
        s.events().init(app_id)
        s.events().insert_batch(self._events(12), app_id)
        es = s.events()
        real = es.c.request

        def old_server(method, path, body=None, **kw):
            # strip the shard params the way an old server ignores them
            path = path.split("&shard_i=")[0]
            st, hd, bd = real(method, path, body, **kw)
            return st, {k: v for k, v in hd.items()
                        if not k.lower().startswith("x-shard")}, bd
        es.c.request = old_server
        with pytest.raises(StorageError, match="shard"):
            es.find_columnar(app_id, ordered=False, shard=(0, 4))

    def test_etag_full_content_hash(self):
        """Two same-length, same-sum batches differing only at
        positions a strided sample misses must get DIFFERENT ETags
        (advisor r3: compensated edits served stale 304s forever)."""
        import numpy as np

        from predictionio_tpu.server.storageserver import _batch_version

        def mk(rating):
            class B:
                pass
            b = B()
            n = len(rating)
            b.n = n
            z = np.zeros(n, np.int32)
            b.event = b.entity_type = b.entity_id = z
            b.target_type = b.target_id = z
            b.event_time = np.zeros(n, np.int64)
            b.props_offsets = np.zeros(n + 1, np.int64)
            b.props_blob = np.zeros(0, np.uint8)
            b.float_props = {"rating": rating}
            return b

        n = 200_000
        a = np.zeros(n, np.float64)
        c = a.copy()
        c[100_001] += 1.0  # not on the stride-3 sample grid
        c[100_003] -= 1.0  # sum unchanged
        va, vc = _batch_version(mk(a)), _batch_version(mk(c))
        assert va != vc
        # memoized per request identity, anchored on the event column:
        # a select-style view sharing the parent's event array hits the
        # memo; a re-encoded batch (new arrays) recomputes
        ba, bc = mk(a), mk(c)
        k = ("t", None, False, ("rating",))
        v1 = _batch_version(ba, memo_key=k)
        view = mk(a)
        view.event = ba.event  # zero-copy select shares the anchor
        view.float_props = {"rating": c}  # memo must NOT re-hash
        assert _batch_version(view, memo_key=k) == v1
        assert _batch_version(bc, memo_key=k) == vc  # new anchor

    def test_shard_pushdown_transfers_fraction_of_bytes(self, served):
        """The point of shard pushdown (VERDICT r3 missing #1): an
        N-host pod transfers the log ~once in aggregate. Four clients
        each fetch their shard; each must receive ≤ ~1/4 of the full
        npz bytes (+ the shared dictionary overhead), shard ETags must
        differ per shard, and a repeat poll must 304."""
        from predictionio_tpu.data.storage import App, Storage
        s0 = Storage(env=self._env(served))
        app_id = s0.apps().insert(App(0, "netshard"))
        s0.events().init(app_id)
        s0.events().insert_batch(self._events(4000), app_id)

        def counting_storage():
            s = Storage(env=self._env(served))
            es = s.events()
            real = es.c.request
            stat = {"bytes": 0, "status": []}

            def wrapped(method, path, body=None, **kw):
                st, hd, bd = real(method, path, body, **kw)
                stat["bytes"] += len(bd or b"")
                stat["status"].append(st)
                return st, hd, bd
            es.c.request = wrapped
            return es, stat

        es_full, stat_full = counting_storage()
        full = es_full.find_columnar(app_id, ordered=False,
                                     with_props=False)
        full_bytes = stat_full["bytes"]
        assert full.n == 4000 and full_bytes > 0

        etags = set()
        for i in range(4):
            es_i, stat_i = counting_storage()
            b = es_i.find_columnar(app_id, ordered=False,
                                   with_props=False, shard=(i, 4))
            assert b.n == 1000
            assert stat_i["bytes"] <= 0.35 * full_bytes, \
                (i, stat_i["bytes"], full_bytes)
            # repeat poll: per-shard ETag 304, ~no bytes
            before = stat_i["bytes"]
            b2 = es_i.find_columnar(app_id, ordered=False,
                                    with_props=False, shard=(i, 4))
            assert b2.n == 1000
            assert stat_i["status"][-1] == 304
            assert stat_i["bytes"] == before
            etags.add(es_i.c.columnar_cache[
                next(iter(es_i.c.columnar_cache))][0])
        assert len(etags) == 4  # one distinct ETag per shard

    def test_bad_secret_rejected(self, served):
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.data.storage.base import StorageError
        env = self._env(served)
        env["PIO_STORAGE_SOURCES_NET_SECRET"] = "wrong"
        s = Storage(env=env)
        with pytest.raises(StorageError):
            s.events().init(1)

    def test_model_blob_roundtrip(self, served):
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.data.storage.base import Model
        s = Storage(env=self._env(served))
        blob = bytes(range(256)) * 10
        s.models().insert(Model(id="m1", models=blob))
        assert s.models().get("m1").models == blob
        s.models().delete("m1")
        assert s.models().get("m1") is None

    def test_concurrent_clients(self, served):
        """8 threads × mixed insert/read traffic against one storage
        server: exactly the expected rows land, reads stay consistent
        (the SQLite-behind-HTTP locking story under real concurrency)."""
        import threading

        from predictionio_tpu.data.storage import Storage
        s = Storage(env=self._env(served))
        app_id = 31
        s.events().init(app_id)
        errors: list = []

        def writer(t):
            try:
                st = Storage(env=self._env(served))
                st.events().insert_batch(
                    self._events(50, seed=t), app_id)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                st = Storage(env=self._env(served))
                for _ in range(5):
                    list(st.events().find(app_id))
                    st.events().find_columnar(app_id, ordered=False,
                                              with_props=False)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        threads += [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:2]
        assert len(list(s.events().find(app_id))) == 400
        assert s.events().find_columnar(app_id, ordered=False).n == 400
