"""Zero-copy columnar ingest + block cursor tests (ISSUE 19).

The write half of the columnar data plane: block inserts landing
bitwise-equivalent rows to the per-event path across backends, the
two HTTP ingest routes (event server firehose, storage server block
lane), the chained content stamp that makes ETag revalidation
O(delta), block-granularity exactly-once consumption, and the
multi-segment contiguous read path (docs/streaming.md).
"""

import json
import urllib.error
import urllib.request
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.columnar import (
    batch_digest,
    columnar_from_events,
)
from predictionio_tpu.data.storage import App, EventFilter, Storage
from predictionio_tpu.data.storage.base import AccessKey
from predictionio_tpu.data.storage.sqlite import SQLiteEventStore
from predictionio_tpu.data.storage.wire import batch_from_npz, batch_to_npz
from predictionio_tpu.streaming.cursor import EventCursor

T0 = datetime(2026, 3, 1, tzinfo=timezone.utc)


def make_events(n=20, seed=0, start=T0):
    rng = np.random.default_rng(seed)
    out, t = [], start
    for k in range(n):
        out.append(Event(
            event="rate" if k % 3 else "buy", entity_type="user",
            entity_id=f"u{rng.integers(0, 8)}",
            target_entity_type="item",
            target_entity_id=f"i{rng.integers(0, 6)}",
            properties=DataMap({"rating": float(rng.integers(1, 6))}),
            event_time=t))
        t += timedelta(seconds=7)
    return out


def proj(e: Event):
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, json.dumps(dict(e.properties),
                                           sort_keys=True),
            e.event_time_millis)


@pytest.fixture
def sq(tmp_path):
    storage = Storage(env={
        "PIO_STORAGE_SOURCES_SQ_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQ_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQ",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQ",
    })
    app_id = storage.apps().insert(App(0, "ingapp"))
    storage.events().init(app_id)
    return storage, app_id


class TestInsertColumnar:
    def test_sqlite_block_matches_event_path(self, sq):
        storage, app_id = sq
        events = make_events(25, seed=1)
        block = batch_from_npz(batch_to_npz(columnar_from_events(events)))
        n = storage.events().insert_columnar(block, app_id)
        assert n == 25
        got = sorted(proj(e) for e in storage.events().find(app_id))
        want = sorted(proj(e) for e in events)
        assert got == want
        # block rows get server-assigned ids, all distinct
        ids = [e.event_id for e in storage.events().find(app_id)]
        assert len(set(ids)) == 25

    def test_memory_backend_default_fallback(self):
        # backends without a block lane inherit the base to_events
        # fallback — same rows, same count
        st = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
        app_id = st.apps().insert(App(0, "memapp"))
        st.events().init(app_id)
        events = make_events(10, seed=2)
        n = st.events().insert_columnar(
            columnar_from_events(events), app_id)
        assert n == 10
        got = sorted(proj(e) for e in st.events().find(app_id))
        assert got == sorted(proj(e) for e in events)

    def test_empty_block_is_a_noop(self, sq):
        storage, app_id = sq
        assert storage.events().insert_columnar(
            columnar_from_events([]), app_id) == 0
        assert list(storage.events().find(app_id)) == []


class TestContentStamp:
    def test_stamp_present_stable_and_moving(self, sq):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch(make_events(12, seed=3), app_id)
        b1 = es.find_columnar(app_id, ordered=False)
        s1 = getattr(b1, "content_stamp", None)
        assert s1  # sqlite maintains the chained stamp at append
        # stable across re-reads and across projections
        b2 = es.find_columnar(app_id, ordered=False, with_props=False)
        assert getattr(b2, "content_stamp", None) == s1
        # append → the chain moves
        es.insert_batch(make_events(3, seed=4,
                                    start=T0 + timedelta(days=1)), app_id)
        b3 = es.find_columnar(app_id, ordered=False)
        assert getattr(b3, "content_stamp", None) != s1

    def test_batch_version_fast_path(self):
        from predictionio_tpu.server.storageserver import _batch_version
        b = columnar_from_events(make_events(5, seed=1))
        b.content_stamp = "a" * 32
        # bare stamp without a request identity; folded with one
        assert _batch_version(b) == "a" * 32
        v_full = _batch_version(b, memo_key=(1, None, True, (), None))
        v_shard = _batch_version(b, memo_key=(1, None, True, (), (0, 2)))
        assert v_full != v_shard  # distinct ETag per projection
        assert v_full == _batch_version(
            b, memo_key=(1, None, True, (), None))  # deterministic
        b.content_stamp = "b" * 32  # log moved → every view's moves
        assert _batch_version(
            b, memo_key=(1, None, True, (), None)) != v_full

    def test_batch_digest_sensitivity(self):
        a = columnar_from_events(make_events(8, seed=5))
        b = columnar_from_events(make_events(8, seed=5))
        c = columnar_from_events(make_events(8, seed=6))
        assert batch_digest(a) == batch_digest(b)
        assert batch_digest(a) != batch_digest(c)


class TestStorageServerBlockLane:
    @pytest.fixture
    def served(self, tmp_path):
        from conftest import start_sqlite_backed_storage_server
        srv, backing = start_sqlite_backed_storage_server(
            tmp_path, secret="s3cret")
        app_id = backing.apps().insert(App(0, "blkapp"))
        backing.events().init(app_id)
        yield srv, backing, app_id
        srv.shutdown()

    @staticmethod
    def raw(srv, method, path, body=None, headers=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}", data=body,
            method=method,
            headers={"X-PIO-Storage-Secret": "s3cret", **(headers or {})})
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), e.read()

    def test_post_block_then_read_back(self, served):
        srv, backing, app_id = served
        events = make_events(30, seed=7)
        payload = batch_to_npz(columnar_from_events(events))
        status, _, body = self.raw(
            srv, "POST", f"/v1/events/{app_id}/columnar", payload,
            {"Content-Type": "application/octet-stream"})
        assert status == 200
        assert json.loads(body)["accepted"] == 30
        status, hdrs, body = self.raw(
            srv, "GET", f"/v1/events/{app_id}/columnar")
        assert status == 200
        got = batch_from_npz(body)
        assert sorted(proj(e) for e in got.to_events()) == \
            sorted(proj(e) for e in events)
        assert hdrs.get("ETag")

    def test_etag_304_via_chained_stamp(self, served):
        srv, backing, app_id = served
        self.raw(srv, "POST", f"/v1/events/{app_id}/columnar",
                 batch_to_npz(columnar_from_events(make_events(9, seed=8))),
                 {"Content-Type": "application/octet-stream"})
        _, hdrs, _ = self.raw(srv, "GET",
                              f"/v1/events/{app_id}/columnar")
        etag = hdrs["ETag"]
        # the served ETag derives from the sidecar's chained stamp (no
        # serve-time re-hash) and is stable across identical reads...
        _, hdrs2, _ = self.raw(srv, "GET",
                               f"/v1/events/{app_id}/columnar")
        assert hdrs2["ETag"] == etag
        # ...but distinct per projection: a shard view must never
        # alias the full read's ETag through a client cache
        _, hdrs_shard, _ = self.raw(
            srv, "GET",
            f"/v1/events/{app_id}/columnar?shard_i=0&shard_n=2")
        assert hdrs_shard["ETag"] != etag
        status, _, body = self.raw(
            srv, "GET", f"/v1/events/{app_id}/columnar", None,
            {"If-None-Match": etag})
        assert status == 304 and body == b""
        # another block moves the stamp → revalidation misses
        self.raw(srv, "POST", f"/v1/events/{app_id}/columnar",
                 batch_to_npz(columnar_from_events(
                     make_events(2, seed=9,
                                 start=T0 + timedelta(days=2)))),
                 {"Content-Type": "application/octet-stream"})
        status, hdrs, _ = self.raw(
            srv, "GET", f"/v1/events/{app_id}/columnar", None,
            {"If-None-Match": etag})
        assert status == 200 and hdrs["ETag"] != etag

    def test_bad_block_is_400(self, served):
        srv, _, app_id = served
        status, _, _ = self.raw(
            srv, "POST", f"/v1/events/{app_id}/columnar", b"not an npz",
            {"Content-Type": "application/octet-stream"})
        assert status == 400

    def test_remote_store_block_ingest(self, served):
        srv, backing, app_id = served
        from predictionio_tpu.data.storage.remote import (
            RemoteClient,
            RemoteEventStore,
        )
        client = RemoteClient(f"http://127.0.0.1:{srv.port}",
                              secret="s3cret")
        es = RemoteEventStore(client)
        events = make_events(14, seed=10)
        assert es.insert_columnar(
            columnar_from_events(events), app_id) == 14
        got = sorted(proj(e) for e in backing.events().find(app_id))
        assert got == sorted(proj(e) for e in events)


class TestEventServerColumnarRoute:
    @pytest.fixture
    def server(self):
        from predictionio_tpu.server.eventserver import create_event_server
        st = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY"})
        app_id = st.apps().insert(App(id=0, name="fireapp",
                                      description=None))
        st.access_keys().insert(AccessKey(key="KEY1", app_id=app_id,
                                          events=[]))
        st.access_keys().insert(AccessKey(key="KEYLIMITED", app_id=app_id,
                                          events=["rate"]))
        srv = create_event_server(st, host="127.0.0.1", port=0, stats=True)
        srv.start_background()
        yield srv, st, app_id
        srv.shutdown()

    @staticmethod
    def post(srv, path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}", data=payload,
            method="POST",
            headers={"Content-Type": "application/octet-stream"})
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"null")

    def test_requires_auth(self, server):
        srv, _, _ = server
        payload = batch_to_npz(columnar_from_events(make_events(3)))
        assert self.post(srv, "/columnar/events.npz", payload)[0] == 401
        assert self.post(srv, "/columnar/events.npz?accessKey=WRONG",
                         payload)[0] == 401

    def test_limited_key_rejects_whole_block(self, server):
        srv, st, app_id = server
        # the block mixes "rate" and "buy"; KEYLIMITED allows only rate
        payload = batch_to_npz(columnar_from_events(make_events(6, seed=1)))
        status, body = self.post(
            srv, "/columnar/events.npz?accessKey=KEYLIMITED", payload)
        assert status == 403 and "not allowed" in body["message"]
        # all-or-nothing: nothing landed
        assert list(st.events().find(app_id)) == []

    def test_accepts_block_and_counts_stats(self, server):
        srv, st, app_id = server
        events = make_events(12, seed=2)
        status, body = self.post(
            srv, "/columnar/events.npz?accessKey=KEY1",
            batch_to_npz(columnar_from_events(events)))
        assert status == 201 and body["accepted"] == 12
        got = sorted(proj(e) for e in st.events().find(app_id))
        assert got == sorted(proj(e) for e in events)
        # bulk stats bookkeeping counted every row
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stats.json?accessKey=KEY1"
        ) as resp:
            stats = json.loads(resp.read())
        assert stats["statusCode"][0] == {"key": 201, "value": 12}


class TestBlockCursor:
    def test_exactly_once_with_restart(self, sq):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch(make_events(20, seed=3), app_id)
        cur = EventCursor(storage, app_id, "fold")
        b = cur.pending_block()
        assert b.n == 20
        cur.advance_block(b.n)
        cur.save()
        assert cur.pending_block().n == 0
        # append → only the delta; the saved cursor record (an event
        # row in the same log) must NOT surface as pending
        es.insert_batch(make_events(5, seed=4,
                                    start=T0 + timedelta(days=1)), app_id)
        b2 = cur.pending_block()
        assert b2.n == 5
        names = {b2.dicts.entity_types.values[int(c)]
                 for c in np.unique(b2.entity_type)}
        assert names == {"user"}
        cur.advance_block(b2.n)
        cur.save()
        # process restart: a fresh cursor resumes the row watermark
        cur2 = EventCursor(storage, app_id, "fold")
        assert cur2.block_rows == cur.block_rows
        assert cur2.pending_block().n == 0

    def test_block_and_save_churn_do_not_interact(self, sq):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch(make_events(8, seed=5), app_id)
        cur = EventCursor(storage, app_id, "fold")
        # repeated saves churn the cursor's own upserted row; the
        # watermark over non-cursor rows must not move
        for _ in range(4):
            cur.save()
        assert cur.pending_block().n == 8

    def test_block_rows_clamped_when_log_shrinks(self, sq):
        storage, app_id = sq
        es = storage.events()
        es.insert_batch(make_events(6, seed=6), app_id)
        cur = EventCursor(storage, app_id, "fold")
        cur.advance_block(cur.pending_block().n)
        cur.block_rows += 100  # simulate a truncated/rebuilt log
        assert cur.pending_block().n == 0
        assert cur.block_rows == 6


class TestMultiSegmentContiguousLoad:
    def test_parity_and_contiguity_across_segments(self, sq, monkeypatch):
        # force several sidecar segments: chunk bounds derive from
        # ENCODE_SUBCHUNK, segment fill from COLUMNAR_CHUNK — both small
        monkeypatch.setattr(SQLiteEventStore, "ENCODE_SUBCHUNK", 7)
        monkeypatch.setattr(SQLiteEventStore, "COLUMNAR_CHUNK", 7)
        storage, app_id = sq
        es = storage.events()
        events = make_events(25, seed=7)
        es.insert_batch(events, app_id)
        full = es.find_columnar(app_id, ordered=False)
        assert full.n == 25
        # host read-path discipline: one contiguous buffer per column
        for col in (full.event, full.entity_id, full.event_time,
                    full.props_offsets, full.props_blob):
            assert col.flags["C_CONTIGUOUS"]
        assert sorted(proj(e) for e in full.to_events()) == \
            sorted(proj(e) for e in events)
        # float-prop projection decoded across segment boundaries
        r = full.float_prop("rating")
        assert r.dtype == np.float64 and len(r) == 25
        # props-free projection stays valid on the multi-segment path
        slim = es.find_columnar(app_id, ordered=False, with_props=False)
        assert slim.n == 25
