"""AOT compile-artifact tests (ISSUE 19, docs/cold-start.md).

Build captures the serving warm ladder's executables into a versioned
artifact store; deploy warms by load-and-verify with a compile
fallback. These tests hold the contract on CPU: bitwise result parity
between artifact-loaded and freshly-compiled executables, a stale
store key falling back to compile (never wrong results), and corrupt
artifact files degrading to compile — never a crash.

CPU caveat: tiny models serve from host numpy (``HOST_SERVE_WORK``
budget) and never touch device executables, so every test forces the
device path — exactly what ``benchmarks/coldstart_smoke.py`` does.
"""

import os
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

import predictionio_tpu.models.als as als
from predictionio_tpu import aot
from predictionio_tpu.controller import Context
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.server.engineserver import (
    QueryServer,
    ServerConfig,
    build_artifacts,
)
from predictionio_tpu.templates.recommendation import (
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import core as wf
from predictionio_tpu.workflow import run_train

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(autouse=True)
def _force_device_serving():
    """Device-path serving + clean process-global AOT state per test."""
    prev = als.HOST_SERVE_WORK
    als.HOST_SERVE_WORK = 0
    aot.deactivate()
    aot.reset_stats()
    try:
        yield
    finally:
        als.HOST_SERVE_WORK = prev
        aot.deactivate()


@pytest.fixture(scope="module")
def trained_ctx():
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "aotapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(11)
    events, t = [], T0
    for u in range(24):
        for i in rng.choice(18, size=6, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
                event_time=t))
            t += timedelta(seconds=30)
    es.insert_batch(events, app_id)
    ctx = Context(app_name="aotapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("aotapp", rank=4, num_iterations=4, seed=5)
    run_train(ctx, engine, ep, engine_id="aot", engine_version="1")
    return ctx, engine, ep


def _config(**kw) -> ServerConfig:
    base = dict(warm_start=False, streaming=False, feedback=False,
                tracing=False, slo_interval_ms=0.0, hot_keys_k=0)
    base.update(kw)
    return ServerConfig(**base)


def _server(trained_ctx, **cfg) -> QueryServer:
    ctx, engine, ep = trained_ctx
    instance = ctx.storage.engine_instances().get_latest_completed(
        "aot", "1", "engine.json")
    models = wf.load_models_for_deploy(ctx, engine, instance, ep)
    return QueryServer(ctx, engine, ep, models, instance, _config(**cfg))


def _warm(server: QueryServer) -> dict:
    try:
        server._warm_serving(server._warm_gen)
    finally:
        server.stop_slo()
    assert server.warm_done.is_set()
    return server._warm_report


def _recs(trained_ctx, k: int = 5):
    """Serving results straight through the dispatch seam."""
    ctx, engine, ep = trained_ctx
    instance = ctx.storage.engine_instances().get_latest_completed(
        "aot", "1", "engine.json")
    model = wf.load_models_for_deploy(ctx, engine, instance, ep)[0]
    single = als.recommend_products(model, 0, k)
    batch = als.recommend_batch(model, np.arange(4), k)
    return [np.asarray(x) for x in (*single, *batch)]


def _same(a, b) -> bool:
    return len(a) == len(b) and all(
        np.array_equal(x, y) for x, y in zip(a, b))


class TestArtifactRoundTrip:
    def test_build_captures_entries(self, trained_ctx, tmp_path):
        ctx, engine, ep = trained_ctx
        out = build_artifacts(ctx, engine, ep, str(tmp_path / "art"),
                              engine_id="aot", config=_config())
        assert out["entries"] > 0
        assert os.path.isfile(os.path.join(out["path"], "manifest.json"))
        # capture is loss-free: every captured entry made it to disk
        assert aot.stats()["captured_entries"] == out["entries"]
        assert aot.stats()["capture_errors"] == 0

    def test_artifact_warm_bitwise_parity(self, trained_ctx, tmp_path):
        ctx, engine, ep = trained_ctx
        root = str(tmp_path / "art")
        build_artifacts(ctx, engine, ep, root, engine_id="aot",
                        config=_config())
        aot.deactivate()
        aot.reset_stats()

        server = _server(trained_ctx, artifact_dir=root)
        report = _warm(server)
        assert report["artifact"] is True
        assert report["loadedEntries"] > 0
        assert report["compiledFallbacks"] == 0
        assert report["corruptEntries"] == 0
        # results with the loaded executables...
        art = _recs(trained_ctx)
        # ...bitwise equal to a freshly compiled run
        aot.deactivate()
        cold = _recs(trained_ctx)
        assert _same(art, cold)
        # phase decomposition: an artifact warm reports load time and
        # all four phases are present on the report
        assert set(report["seconds"]) == {"load", "compile",
                                          "replicate", "probe"}

    def test_status_flag_reflects_artifact_warm(self, trained_ctx,
                                                tmp_path):
        ctx, engine, ep = trained_ctx
        root = str(tmp_path / "art")
        build_artifacts(ctx, engine, ep, root, engine_id="aot",
                        config=_config())
        aot.deactivate()
        server = _server(trained_ctx, artifact_dir=root)
        report = _warm(server)
        assert bool(report.get("artifact")) is True
        # the /status.json route renders exactly this flag
        assert bool(server._warm_report.get("artifact")) is True


class TestFallbacks:
    def test_stale_key_compiles_and_serves(self, trained_ctx, tmp_path):
        ctx, engine, ep = trained_ctx
        root = str(tmp_path / "art")
        build_artifacts(ctx, engine, ep, root, engine_id="aot",
                        config=_config(max_batch=16))
        aot.deactivate()
        aot.reset_stats()
        # deploy under a DIFFERENT key (max_batch changes the store key)
        server = _server(trained_ctx, artifact_dir=root, max_batch=32)
        report = _warm(server)
        assert report["artifact"] is False
        assert report["staleStores"] >= 1
        assert report["loadedEntries"] == 0
        # ...but warm-up completed and serving works
        got = _recs(trained_ctx)
        assert got[0].shape[-1] == 5

    def test_missing_store_is_a_cold_warm(self, trained_ctx, tmp_path):
        server = _server(trained_ctx,
                         artifact_dir=str(tmp_path / "nothing-here"))
        report = _warm(server)
        assert report["artifact"] is False
        assert report["staleStores"] >= 1

    def test_corrupt_artifact_falls_back_bitwise_safe(self, trained_ctx,
                                                      tmp_path):
        ctx, engine, ep = trained_ctx
        root = str(tmp_path / "art")
        out = build_artifacts(ctx, engine, ep, root, engine_id="aot",
                              config=_config())
        aot.deactivate()
        # flip bytes inside one serialized executable
        execs = [f for f in os.listdir(out["path"])
                 if f.endswith(".exec")]
        victim = os.path.join(out["path"], sorted(execs)[0])
        blob = bytearray(open(victim, "rb").read())
        blob[10] ^= 0xFF
        with open(victim, "wb") as f:
            f.write(bytes(blob))

        aot.reset_stats()
        server = _server(trained_ctx, artifact_dir=root)
        report = _warm(server)
        assert report["corruptEntries"] >= 1
        assert report["compiledFallbacks"] >= 1
        assert report["artifact"] is False  # not a pure artifact warm
        got = _recs(trained_ctx)
        aot.deactivate()
        cold = _recs(trained_ctx)
        assert _same(got, cold)


class TestAotUnit:
    def test_dispatch_passthrough_without_stores(self):
        calls = []

        def fn(x, *, k):
            calls.append((x, k))
            return x * k

        assert aot.dispatch("t", fn, (3,), {"k": 2}) == 6
        assert calls == [(3, 2)]
        assert aot.stats()["loaded_calls"] == 0

    def test_store_key_is_deterministic_and_sensitive(self):
        a = aot.store_key(serving_mode="auto", rank=(4,))
        b = aot.store_key(serving_mode="auto", rank=(4,))
        c = aot.store_key(serving_mode="auto", rank=(8,))
        assert aot.key_digest(a) == aot.key_digest(b)
        assert aot.key_digest(a) != aot.key_digest(c)
        # environment facts ride in every key
        assert "jax" in a and "backend" in a

    def test_entry_key_separates_statics_and_shapes(self):
        x4 = np.zeros(4, np.float32)
        x8 = np.zeros(8, np.float32)
        k1 = aot.entry_key("serve", (x4,), {"k": 5})
        k2 = aot.entry_key("serve", (x4,), {"k": 10})
        k3 = aot.entry_key("serve", (x8,), {"k": 5})
        k4 = aot.entry_key("serve", (x4,), {"k": 5}, key_extra=("m",))
        assert len({k1, k2, k3, k4}) == 4
        assert k1 == aot.entry_key("serve", (x4,), {"k": 5})
