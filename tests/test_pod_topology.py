"""The pod topology end-to-end (VERDICT r3 task 4): one storage server
+ N=4 ``ptpu train`` processes driven through the REAL CLI
(``PIO_COORDINATOR``/``PIO_NUM_PROCESSES`` envs, gloo collectives, 2
virtual CPU devices per process), REMOTE backend with shard pushdown.

Asserts the whole ``docs/deployment.md`` story at once:
- every worker exits 0; factors match the single-process CLI run;
- each worker transferred ~1/4 of the log's columnar bytes (the shard
  pushdown actually engaged over the wire);
- engine-instance metadata transitioned INIT→COMPLETED exactly once
  (single-writer workflow), one model blob.

The reference never had a test like this — its multi-node story needed
a real Spark cluster (SURVEY §4 "Multi-node without a cluster: they
don't").
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

WORKER = textwrap.dedent("""
    import json, os, sys

    pid = int(sys.argv[1])
    outdir = sys.argv[2]
    engine_json = sys.argv[3]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    # count the bulk-read bytes this worker pulls off the wire
    from predictionio_tpu.data.storage import remote
    real = remote.RemoteClient.request
    stats = {"columnar_bytes": 0}
    def wrapped(self, method, path, body=None, **kw):
        st, hd, bd = real(self, method, path, body, **kw)
        if "/columnar" in path:
            stats["columnar_bytes"] += len(bd or b"")
        return st, hd, bd
    remote.RemoteClient.request = wrapped

    from predictionio_tpu.cli import main
    rc = main(["train", "--engine-json", engine_json])
    json.dump({"rc": rc, "pid": pid, **stats},
              open(os.path.join(outdir, f"worker{pid}.json"), "w"))
    sys.exit(rc)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _remote_env(port: int) -> dict:
    return {
        "PIO_STORAGE_SOURCES_NET_TYPE": "remote",
        "PIO_STORAGE_SOURCES_NET_URL": f"http://127.0.0.1:{port}",
        "PIO_STORAGE_SOURCES_NET_SECRET": "podsecret",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NET",
    }


def test_four_process_cli_train_over_storage_server(tmp_path):
    from conftest import start_sqlite_backed_storage_server
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import App, Storage

    srv, _ = start_sqlite_backed_storage_server(tmp_path,
                                                secret="podsecret")
    try:
        env_remote = _remote_env(srv.port)
        s = Storage(env=env_remote)
        app_id = s.apps().insert(App(0, "PodApp"))
        s.events().init(app_id)
        rng = np.random.default_rng(11)
        n = 1500
        s.events().insert_batch(
            [Event(event="rate", entity_type="user",
                   entity_id=f"u{int(u)}", target_entity_type="item",
                   target_entity_id=f"i{int(i)}",
                   properties=DataMap({"rating": float(r)}))
             for u, i, r in zip(rng.integers(0, 60, n),
                                rng.integers(0, 30, n),
                                rng.integers(1, 6, n))], app_id)

        engine_json = tmp_path / "engine.json"
        engine_json.write_text(json.dumps({
            "id": "podrec", "version": "1",
            "engineFactory": "predictionio_tpu.templates."
                             "recommendation:recommendation_engine",
            "datasource": {"params": {"app_name": "PodApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 8, "num_iterations": 2, "reg": 0.05,
                "seed": 5}}],
        }))
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER)

        coord_port = _free_port()
        base_env = {k: v for k, v in os.environ.items()
                    if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        base_env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + base_env.get("PYTHONPATH", "").split(os.pathsep))
        base_env.update(env_remote)
        base_env.update({
            "PIO_COORDINATOR": f"127.0.0.1:{coord_port}",
            "PIO_NUM_PROCESSES": "4",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        })
        procs = []
        for pid in range(4):
            env = dict(base_env)
            env["PIO_PROCESS_ID"] = str(pid)
            procs.append(subprocess.Popen(
                [sys.executable, str(worker), str(pid), str(tmp_path),
                 str(engine_json)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        outs = [p.communicate(timeout=420)[0].decode() for p in procs]
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"

        # metadata: INIT→COMPLETED exactly once, one model blob
        instances = [i for i in s.engine_instances().get_all()]
        assert len(instances) == 1, [
            (i.id, i.status) for i in instances]
        inst = instances[0]
        assert inst.status == "COMPLETED"
        blob = s.models().get(inst.id)
        assert blob is not None

        from predictionio_tpu.workflow import persistence
        model_multi = persistence.loads_models(blob.models)[0]

        # single-process reference through the SAME CLI against the
        # same storage (its own instance id; remove multihost envs)
        env1 = dict(base_env)
        for k in ("PIO_COORDINATOR", "PIO_NUM_PROCESSES",
                  "PIO_PROCESS_ID"):
            env1.pop(k, None)
        p1 = subprocess.run(
            [sys.executable, str(worker), "9", str(tmp_path),
             str(engine_json)],
            env=env1, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=420)
        assert p1.returncode == 0, p1.stdout.decode()[-4000:]
        instances2 = [i for i in s.engine_instances().get_all()]
        assert len(instances2) == 2
        single_id = next(i.id for i in instances2 if i.id != inst.id)
        model_single = persistence.loads_models(
            s.models().get(single_id).models)[0]

        # factors match after aligning rows by entity-id string (both
        # runs index by ascending dictionary code — same sidecar, same
        # codes — so this should be the identity permutation, but align
        # anyway to keep the assertion about MATH, not layout)
        for side, attr in (("user_ids", "user_factors"),
                           ("item_ids", "item_factors")):
            ids_m = getattr(model_multi, side)
            ids_s = getattr(model_single, side)
            assert set(ids_m) == set(ids_s)
            fm = np.asarray(getattr(model_multi, attr))
            fs = np.asarray(getattr(model_single, attr))
            perm_m = [ids_m[k] for k in sorted(ids_m)]
            perm_s = [ids_s[k] for k in sorted(ids_s)]
            np.testing.assert_allclose(fm[perm_m], fs[perm_s],
                                       rtol=2e-3, atol=2e-4)

        # shard pushdown engaged: each worker pulled ~1/4 of the bytes
        # the single-process run pulled
        single_bytes = json.load(
            open(tmp_path / "worker9.json"))["columnar_bytes"]
        for pid in range(4):
            wb = json.load(
                open(tmp_path / f"worker{pid}.json"))["columnar_bytes"]
            assert wb <= 0.4 * single_bytes, \
                (pid, wb, single_bytes)
    finally:
        srv.shutdown()
