"""S3-contract object-store backend: client, DAOs, registry wiring.

The event-store contract is covered by the cross-backend fuzzer
(test_storage_fuzz) and the kill fuzzer (test_crash_fuzz); this file
covers the rest of the backend: the S3 REST subset itself (list
pagination, etags), metadata DAOs, the Models role
(``storage/s3/.../S3Models.scala``), and a full Storage environment
over the bucket.
"""

import pytest

from predictionio_tpu.data.storage import Storage
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    Model,
)
from predictionio_tpu.data.storage.objectstore import (
    FakeObjectStoreServer,
    ObjectStoreClient,
)


@pytest.fixture
def bucket(tmp_path):
    srv = FakeObjectStoreServer(str(tmp_path / "bucket"))
    srv.start_background()
    yield ObjectStoreClient(f"http://127.0.0.1:{srv.port}/bucket")
    srv.shutdown()


class TestClient:
    def test_put_get_delete_roundtrip(self, bucket):
        assert bucket.get("a/b") is None
        etag = bucket.put("a/b", b"hello")
        assert etag
        assert bucket.get("a/b") == b"hello"
        bucket.delete("a/b")
        assert bucket.get("a/b") is None

    def test_list_prefix_order_and_pagination(self, bucket):
        for i in range(7):
            bucket.put(f"p/{i:03d}", bytes([i]))
        bucket.put("q/x", b"z")
        keys = list(bucket.list("p/"))
        assert keys == [f"p/{i:03d}" for i in range(7)]
        # marker pagination: force tiny pages through the raw API
        status, body, _ = bucket._request(
            "GET", f"{bucket.bucket_path}?prefix=p/&max-keys=3")
        assert status == 200 and b"true" in body.lower()

    def test_binary_and_unicode_keys(self, bucket):
        data = bytes(range(256))
        bucket.put("models/étag id", data)
        assert bucket.get("models/étag id") == data


class TestStorageEnvironment:
    def test_full_backend_verifies_and_roundtrips(self, bucket):
        s = Storage(env={
            "PIO_STORAGE_SOURCES_OBJ_TYPE": "s3",
            "PIO_STORAGE_SOURCES_OBJ_ENDPOINT": bucket.endpoint,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "OBJ",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "OBJ",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ",
        })
        s.verify_all_data_objects()
        aid = s.apps().insert(App(0, "bucketapp"))
        assert aid and s.apps().get_by_name("bucketapp").id == aid
        key = s.access_keys().insert(AccessKey("", aid, ["rate"]))
        assert s.access_keys().get(key).app_id == aid
        cid = s.channels().insert(Channel(0, "live", aid))
        assert cid in [c.id for c in s.channels().get_by_app_id(aid)]
        s.models().insert(Model(id="m1", models=b"\x00\x01blob"))
        assert s.models().get("m1").models == b"\x00\x01blob"
        s.models().delete("m1")
        assert s.models().get("m1") is None

    def test_engine_instances_latest_completed(self, bucket):
        from datetime import datetime, timedelta, timezone

        from predictionio_tpu.data.storage.base import (
            STATUS_COMPLETED,
            EngineInstance,
        )

        s = Storage(env={
            "PIO_STORAGE_SOURCES_OBJ_TYPE": "s3",
            "PIO_STORAGE_SOURCES_OBJ_ENDPOINT": bucket.endpoint,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "OBJ",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "OBJ",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ",
        })
        t = datetime(2026, 5, 1, tzinfo=timezone.utc)
        dao = s.engine_instances()
        ids = []
        for j in range(3):
            ids.append(dao.insert(EngineInstance(
                id="", status=STATUS_COMPLETED,
                start_time=t + timedelta(hours=j),
                end_time=t + timedelta(hours=j, minutes=5),
                engine_id="e", engine_version="1",
                engine_variant="v.json", engine_factory="f")))
        latest = dao.get_latest_completed("e", "1", "v.json")
        assert latest.id == ids[-1]
        got = dao.get(ids[0])
        dao.update(got.copy(status="INIT"))
        assert len(dao.get_completed("e", "1", "v.json")) == 2


class TestDurability:
    def test_reopen_fresh_client_sees_state(self, tmp_path):
        root = str(tmp_path / "bucket")
        srv = FakeObjectStoreServer(root)
        srv.start_background()
        url = f"http://127.0.0.1:{srv.port}/bucket"
        c1 = ObjectStoreClient(url)
        c1.put("models/m", b"abc")
        c1.write_doc("apps", [{"id": 1, "name": "a",
                               "description": None}])
        c1.close()
        srv.shutdown()
        # a NEW server over the same directory (host restart)
        srv2 = FakeObjectStoreServer(root)
        srv2.start_background()
        c2 = ObjectStoreClient(f"http://127.0.0.1:{srv2.port}/bucket")
        assert c2.get("models/m") == b"abc"
        assert c2.read_doc("apps", [])[0]["name"] == "a"
        c2.close()
        srv2.shutdown()
