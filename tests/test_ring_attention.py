"""Ring attention (sequence-parallel) vs dense attention — exercised on
the 8-device virtual CPU mesh like every other sharded component."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.ops.ring_attention import (
    ring_attention,
    sequence_shard,
)


def dense_reference(q, k, v, causal, scale=None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float64) * scale
    if causal:
        S = q.shape[1]
        mask = np.arange(S)[:, None] >= np.arange(S)[None, :]
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v.astype(np.float64))


def _qkv(B=2, S=64, H=3, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((B, S, H, D)).astype(np.float32)
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_on_mesh(self, mesh8, causal):
        q, k, v = _qkv()
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh=mesh8, causal=causal)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_single_device_path(self, causal):
        q, k, v = _qkv(S=24, seed=3)
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh=None, causal=causal)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=2e-5, atol=2e-5)

    def test_output_keeps_sequence_sharding(self, mesh8):
        q, k, v = _qkv(S=32, seed=5)
        qs = sequence_shard(jnp.asarray(q), mesh8)
        ks = sequence_shard(jnp.asarray(k), mesh8)
        vs = sequence_shard(jnp.asarray(v), mesh8)
        out = ring_attention(qs, ks, vs, mesh=mesh8)
        # the sequence axis stays sharded — no device gathered the
        # whole sequence
        shard_shapes = {s.data.shape for s in out.addressable_shards}
        n_seq_axis = mesh8.shape["data"]
        assert all(sh[1] == 32 // n_seq_axis for sh in shard_shapes)

    def test_bf16_inputs(self, mesh8):
        q, k, v = _qkv(S=32, seed=7)
        out = ring_attention(
            jnp.asarray(q, jnp.bfloat16), jnp.asarray(k, jnp.bfloat16),
            jnp.asarray(v, jnp.bfloat16), mesh=mesh8, causal=True)
        assert out.dtype == jnp.bfloat16
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float64), ref, rtol=0.05,
            atol=0.05)

    def test_key_valid_mask_matches_dense_on_mesh(self, mesh8):
        """Padding-key masking through the PUBLIC API: the mask rotates
        around the ring with its KV block and must equal dense
        attention over only the valid keys."""
        q, k, v = _qkv(B=2, S=64, H=2, D=8, seed=11)
        rng = np.random.default_rng(12)
        key_valid = rng.random((2, 64)) > 0.3
        out = ring_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), mesh=mesh8, causal=True,
                             key_valid=jnp.asarray(key_valid))
        # dense reference with the same key mask
        scale = q.shape[-1] ** -0.5
        s = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float64) * scale
        S = q.shape[1]
        cmask = np.arange(S)[:, None] >= np.arange(S)[None, :]
        s = np.where(cmask[None, None], s, -np.inf)
        s = np.where(key_valid[:, None, None, :], s, -np.inf)
        m = s.max(axis=-1, keepdims=True)
        m = np.where(np.isinf(m), 0.0, m)
        p = np.exp(s - m)
        denom = p.sum(-1, keepdims=True)
        p = np.where(denom > 0, p / np.maximum(denom, 1e-30), 0.0)
        ref = np.einsum("bhqk,bkhd->bqhd", p, v.astype(np.float64))
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=3e-5, atol=3e-5)
