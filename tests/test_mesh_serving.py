"""Mesh-wide serving & training (ISSUE 6) on the 8-device virtual CPU
mesh: replicated fan-out (per-device lanes through the MicroBatcher)
must answer identically on every lane, row-sharded factor tables
(``shard_model`` over the ``(batch, model)`` serving mesh) must answer
identically to the single-device baseline, and ALS must train to the
same factors over the serving mesh as meshless. ``tests/conftest.py``
forces ``XLA_FLAGS=--xla_force_host_platform_device_count=8``; CI also
runs this module as its own forced-8-device step.
"""

import json
from datetime import datetime, timezone

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from predictionio_tpu.controller import Context
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.base import (
    STATUS_COMPLETED,
    EngineInstance,
)
from predictionio_tpu.models.als import (
    ALSModel,
    ALSParams,
    RatingsCOO,
    _serve_topk,
    pin_user_rows,
    pin_user_rows_lanes,
    recommend_batch,
    recommend_pinned,
    recommend_products,
    replicate_model,
    shard_model,
    train_als,
)
from predictionio_tpu.parallel import (
    BATCH_AXIS,
    MODEL_AXIS,
    make_serving_mesh,
    resolve_serving_mode,
    rows_spec,
)
from predictionio_tpu.server.engineserver import QueryServer, ServerConfig
from predictionio_tpu.templates.recommendation import (
    default_engine_params,
    recommendation_engine,
)

GiB = 1 << 30


class TestMeshPlumbing:
    def test_serving_mesh_axes_and_shape(self):
        mesh = make_serving_mesh()
        assert mesh.axis_names == (BATCH_AXIS, MODEL_AXIS)
        assert mesh.devices.size == len(jax.devices())
        mesh2 = make_serving_mesh(batch=4, model=2)
        assert dict(zip(mesh2.axis_names, mesh2.devices.shape)) == {
            "batch": 4, "model": 2}

    def test_rows_spec_covers_every_axis(self):
        mesh = make_serving_mesh(batch=4, model=2)
        assert rows_spec(mesh) == P(("batch", "model"))
        from predictionio_tpu.parallel import make_mesh

        assert rows_spec(make_mesh(data=2, model=4)) \
            == P(("data", "model"))
        assert rows_spec(None) == P()

    def test_resolve_serving_mode(self):
        # explicit modes pass through; auto sizes against one HBM
        assert resolve_serving_mode("replicated", None, 8) == "replicated"
        assert resolve_serving_mode("sharded", None, 8) == "sharded"
        assert resolve_serving_mode("auto", None, 1) == "single"
        # model fits comfortably → a full copy per device
        assert resolve_serving_mode(
            "auto", 1 * GiB, 8, hbm_limit=16 * GiB) == "replicated"
        # 10M users × rank 256 × f32 ≈ 10.2 GB > 0.6 × 16 GiB → sharded
        big = (10_000_000 + 100_000) * 256 * 4
        assert resolve_serving_mode(
            "auto", big, 8, hbm_limit=16 * GiB) == "sharded"
        with pytest.raises(ValueError):
            resolve_serving_mode("bogus", None, 8)


def _ratings(nu=96, ni=40, nnz=2000, seed=0):
    rng = np.random.default_rng(seed)
    return RatingsCOO(rng.integers(0, nu, nnz).astype(np.int32),
                      rng.integers(0, ni, nnz).astype(np.int32),
                      (rng.random(nnz) * 4 + 1).astype(np.float32),
                      nu, ni)


class TestTrainOverServingMesh:
    """The SAME training code runs over the ``(batch, model)`` serving
    mesh: rows_spec derives the row sharding from the mesh's own axis
    names, and the Gramian all-reduce rides the same mesh."""

    def test_explicit_matches_meshless(self):
        r = _ratings()
        p = ALSParams(rank=8, num_iterations=3, seed=3)
        U0, V0 = train_als(r, p)
        mesh = make_serving_mesh(batch=4, model=2)
        U1, V1 = train_als(r, p, mesh=mesh)
        np.testing.assert_allclose(np.asarray(U0)[:r.n_users],
                                   np.asarray(U1)[:r.n_users],
                                   atol=5e-4)
        np.testing.assert_allclose(np.asarray(V0)[:r.n_items],
                                   np.asarray(V1)[:r.n_items],
                                   atol=5e-4)

    def test_implicit_matches_meshless(self):
        r = _ratings(seed=1)
        p = ALSParams(rank=8, num_iterations=2, implicit_prefs=True,
                      alpha=4.0, seed=3)
        U0, V0 = train_als(r, p)
        U1, V1 = train_als(r, p, mesh=make_serving_mesh())
        np.testing.assert_allclose(np.asarray(U0)[:r.n_users],
                                   np.asarray(U1)[:r.n_users],
                                   atol=5e-4)


def _model(nu=200, ni=101, rank=16, seed=0):
    rng = np.random.default_rng(seed)
    return ALSModel(
        user_factors=rng.standard_normal((nu, rank)).astype(np.float32),
        item_factors=rng.standard_normal((ni, rank)).astype(np.float32),
        n_users=nu, n_items=ni,
        user_ids=BiMap({f"u{i}": i for i in range(nu)}),
        item_ids=BiMap({f"i{i}": i for i in range(ni)}),
        params=ALSParams(rank=rank))


class TestShardedServing:
    def test_shard_model_places_rows_on_every_device(self):
        mesh = make_serving_mesh()
        ms = shard_model(_model(), mesh)
        assert ms.mesh is mesh
        assert len(ms.user_factors.sharding.device_set) == 8
        # rows padded to a device multiple, real counts preserved
        assert ms.item_factors.shape[0] % 8 == 0
        assert ms.n_items == 101

    def test_sharded_predictions_match_single_device(self):
        m = _model()
        mesh = make_serving_mesh(batch=4, model=2)
        ms = shard_model(m, mesh)
        rng = np.random.default_rng(2)
        idx = rng.integers(0, m.n_users, 7)
        want_s, want_i = _serve_topk(
            jnp.asarray(m.user_factors), jnp.asarray(m.item_factors),
            idx, k=10, n_items=m.n_items)
        ids, scores = recommend_batch(ms, idx, 10)
        np.testing.assert_array_equal(ids, np.asarray(want_i))
        np.testing.assert_allclose(scores, np.asarray(want_s),
                                   rtol=1e-5)
        i1, s1 = recommend_products(ms, int(idx[0]), 10)
        np.testing.assert_array_equal(i1, ids[0])

    def test_sharded_k_exceeding_local_shard(self):
        # 104 padded items over 8 devices = 13 per shard; ask for 20
        m = _model(ni=101)
        ms = shard_model(m, make_serving_mesh())
        want_s, want_i = _serve_topk(
            jnp.asarray(m.user_factors), jnp.asarray(m.item_factors),
            np.asarray([3]), k=20, n_items=m.n_items)
        ids, scores = recommend_batch(ms, np.asarray([3]), 20)
        np.testing.assert_array_equal(ids[0], np.asarray(want_i)[0][:20])

    def test_sharded_concurrent_dispatch_is_safe(self):
        # the mesh program's candidate all-gather deadlocks if two host
        # threads interleave their per-device launches — the dispatch
        # lock serializes them; this must finish, and identically
        import threading

        m = _model()
        ms = shard_model(m, make_serving_mesh())
        want, _ = recommend_batch(ms, np.asarray([1, 2, 3]), 5)
        results = [None] * 8
        def fire(i):
            results[i] = recommend_batch(ms, np.asarray([1, 2, 3]), 5)[0]
        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for got in results:
            np.testing.assert_array_equal(got, want)

    def test_sharded_pinned_hot_rows(self):
        m = _model()
        ms = shard_model(m, make_serving_mesh())
        pinned, nbytes = pin_user_rows(ms, [5, 9], 4)
        assert pinned is not None and nbytes > 0
        want_i, _ = recommend_products(ms, 9, 10)
        ids, _ = recommend_pinned(ms, pinned, 1, 10)
        np.testing.assert_array_equal(ids, want_i)


class TestReplicatedLanes:
    def test_replicate_model_commits_to_device(self):
        m = _model()
        dev = jax.devices()[3]
        mr = replicate_model(m, dev)
        assert list(mr.user_factors.devices()) == [dev]
        assert mr.mesh is None

    def test_lane_pinned_tables_follow_lane_model_device(self):
        # per-device pinned shards: whichever lane's model serves the
        # hot query, the pinned copy on ITS device is used — fully
        # lane-local, and identical answers on every lane
        m0 = _model()
        devs = jax.devices()[:4]
        lane_models = [replicate_model(m0, d) for d in devs]
        tables, nbytes = pin_user_rows_lanes(lane_models[0], [5, 9], 4,
                                             devs)
        assert tables is not None and len(tables) == 4
        assert nbytes > 0
        want_i, _ = recommend_products(lane_models[0], 5, 10)
        for lm, dev, table in zip(lane_models, devs, tables):
            ids, _ = recommend_pinned(lm, tables, 0, 10)
            np.testing.assert_array_equal(ids, want_i)
            assert list(table.devices()) == [dev]


def _mk_server(cfg: ServerConfig, model: ALSModel) -> QueryServer:
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "meshtest"))
    ctx = Context(app_name="meshtest", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("meshtest", rank=model.params.rank)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="mesh-inst", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="meshtest", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    return QueryServer(ctx, engine, ep, [model], inst, cfg)


class TestQueryServerMeshModes:
    """The engine-server integration: mode resolution at bind,
    per-device lane fan-out through the MicroBatcher, the sharded
    binding serving /queries.json-shaped queries, and the status
    surfaces."""

    def test_replicated_lanes_answer_identically(self):
        model = _model(nu=300, ni=150)
        want = _mk_server(ServerConfig(warm_start=False),
                          model).query({"user": "u7", "num": 5})
        qs = _mk_server(
            ServerConfig(warm_start=False, serving_mode="replicated",
                         batching=True, max_batch=8), model)
        assert qs.serving_mode_resolved == "replicated"
        assert len(qs.lane_models) == 8
        assert qs.batcher is not None and qs.batcher.lanes == 8
        outs = [qs.query_batch([{"user": "u7", "num": 5}], lane=lane)[0]
                for lane in range(8)]
        assert all(o == outs[0] for o in outs)
        assert [s["item"] for s in outs[0]["itemScores"]] \
            == [s["item"] for s in want["itemScores"]]
        # the serve() entry (what /queries.json calls) rides the lanes
        r = qs.serve({"user": "u7", "num": 5})
        assert [s["item"] for s in r["itemScores"]] \
            == [s["item"] for s in want["itemScores"]]

    def test_replicated_mesh_status_and_metrics(self):
        qs = _mk_server(
            ServerConfig(warm_start=False, serving_mode="replicated",
                         batching=True, max_batch=8),
            _model(nu=300, ni=150))
        for lane in range(3):
            qs.query_batch([{"user": "u1", "num": 3}], lane=lane)
        mesh = qs.mesh_status()
        assert mesh["mode"] == "replicated"
        assert mesh["devices"] == 8
        assert len(mesh["lanes"]) == 8
        assert mesh["lanes"][0]["dispatches"] >= 1
        assert {lane["deviceId"] for lane in mesh["lanes"]} \
            == {d.id for d in jax.devices()}
        # the per-lane series land in the exposition
        text = qs.metrics.render()
        assert "pio_lane_dispatches_total" in text
        assert "pio_serving_lanes" in text

    def test_sharded_server_matches_single(self):
        model = _model(nu=300, ni=150)
        want = _mk_server(ServerConfig(warm_start=False),
                          model).query({"user": "u7", "num": 5})
        qs = _mk_server(
            ServerConfig(warm_start=False, serving_mode="sharded"),
            model)
        assert qs.serving_mode_resolved == "sharded"
        assert qs.serving_mesh is not None
        got = qs.query({"user": "u7", "num": 5})
        assert [s["item"] for s in got["itemScores"]] \
            == [s["item"] for s in want["itemScores"]]
        mesh = qs.mesh_status()
        assert mesh["mode"] == "sharded"
        assert mesh["meshShape"] == {"batch": 8, "model": 1}

    def test_auto_resolves_replicated_on_unsized_backend(self):
        # CPU reports no HBM limit: auto must stay conservative —
        # fan-out, never auto-shard on unknown sizing
        qs = _mk_server(
            ServerConfig(warm_start=False, serving_mode="auto"),
            _model())
        assert qs.serving_mode_resolved == "replicated"

    def test_single_mode_is_unchanged(self):
        qs = _mk_server(ServerConfig(warm_start=False), _model())
        assert qs.serving_mode_resolved == "single"
        assert qs.lane_models == [] and qs.batcher is None
        assert qs.mesh_status() == {"mode": "single"}

    def test_sharded_end_to_end_train_deploy_query(self):
        """The acceptance path at test scale: ALS trains row-sharded
        over the serving mesh, the model deploys sharded, and
        /queries.json-shaped queries answer identically to a
        single-device deployment of the same factors."""
        r = _ratings(nu=120, ni=60, nnz=3000, seed=5)
        p = ALSParams(rank=8, num_iterations=2, seed=3)
        mesh = make_serving_mesh()
        U, V = train_als(r, p, mesh=mesh)
        model = ALSModel(
            user_factors=np.asarray(U)[:r.n_users],
            item_factors=np.asarray(V)[:r.n_items],
            n_users=r.n_users, n_items=r.n_items,
            user_ids=BiMap({f"u{i}": i for i in range(r.n_users)}),
            item_ids=BiMap({f"i{i}": i for i in range(r.n_items)}),
            params=p)
        want = _mk_server(ServerConfig(warm_start=False),
                          model).query({"user": "u11", "num": 4})
        qs = _mk_server(
            ServerConfig(warm_start=False, serving_mode="sharded"),
            model)
        got = qs.query({"user": "u11", "num": 4})
        assert [s["item"] for s in got["itemScores"]] \
            == [s["item"] for s in want["itemScores"]]
        status_mesh = json.loads(json.dumps(qs.mesh_status()))
        assert status_mesh["mode"] == "sharded"
