"""Progressive-delivery tests (ISSUE 3): release registry, traffic
splitter, health policy, the end-to-end canary lifecycle (erroring
candidate auto-rolls-back; healthy candidate ramps to 100% and becomes
the pinned stable), shadow mode, the release CLI, concurrent
per-algorithm dispatch, and the /reload warm-race stress test."""

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.cli import main as cli_main
from predictionio_tpu.controller import Context
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.data.storage.base import (
    STATUS_COMPLETED,
    EngineInstance,
)
from predictionio_tpu.rollout import (
    ArmWindow,
    HealthPolicy,
    ReleaseRegistry,
    TrafficSplitter,
    window_quantile,
)
from predictionio_tpu.server.engineserver import (
    QueryServer,
    ServerConfig,
    create_engine_server,
)
from predictionio_tpu.templates.recommendation import (
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow.core import load_models_for_deploy

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return resp.status, (json.loads(raw) if "json" in ctype
                                 else raw.decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


# ---------------------------------------------------------------------------
# unit: registry
# ---------------------------------------------------------------------------

def _mem_storage_with_instance(iid="i1"):
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    now = datetime.now(timezone.utc)
    storage.engine_instances().insert(EngineInstance(
        id=iid, status=STATUS_COMPLETED, start_time=now, end_time=now,
        engine_id="e", engine_version="1", engine_variant="v",
        engine_factory="f"))
    return storage


class TestReleaseRegistry:
    def test_deploy_pin_promote_rollback_history(self):
        storage = _mem_storage_with_instance("i1")
        now = datetime.now(timezone.utc)
        storage.engine_instances().insert(EngineInstance(
            id="i2", status=STATUS_COMPLETED, start_time=now,
            end_time=now, engine_id="e", engine_version="1",
            engine_variant="v", engine_factory="f"))
        reg = ReleaseRegistry(storage, "e", "1", "v")
        reg.record_deploy("i1", actor="test", reason="first")
        assert reg.state()["stable"] == "i1"
        reg.pin("i1", actor="test")
        assert reg.pinned_instance() == "i1"
        reg.start_candidate("i2", 0.05, mode="canary", actor="gate")
        st = reg.state()
        assert st["candidate"] == "i2" and st["fraction"] == 0.05
        reg.set_fraction(0.25, actor="gate")
        assert reg.state()["fraction"] == 0.25
        reg.promote("i2", actor="gate", reason="healthy")
        st = reg.state()
        assert st["stable"] == "i2" and st["pinned"] == "i2"
        assert st["candidate"] == "" and st["previousStable"] == "i1"
        # stable rollback (no candidate): reverts to previous stable
        reg.rollback(actor="op", reason="bad promote")
        st = reg.state()
        assert st["stable"] == "i1" and st["pinned"] == "i1"
        actions = [e.action for e in reg.history()]
        assert actions == ["deploy", "pin", "canary", "ramp",
                           "promote", "rollback"]
        # persisted: a fresh registry over the same storage reads it all
        again = ReleaseRegistry(storage, "e", "1", "v")
        assert [e.action for e in again.history()] == actions
        assert ("e", "1", "v") in ReleaseRegistry.list_tracked(storage)

    def test_candidate_rollback_and_guards(self):
        storage = _mem_storage_with_instance("i1")
        reg = ReleaseRegistry(storage, "e", "1", "v")
        with pytest.raises(ValueError):
            reg.pin("nope")  # unknown instance
        with pytest.raises(ValueError):
            reg.rollback()  # nothing to roll back
        reg.start_candidate("i1", 0.01, actor="t")
        ev = reg.rollback(actor="gate", reason="error rate")
        assert ev.extra["kind"] == "candidate"
        assert reg.state()["candidate"] == ""

    def test_unpin(self):
        storage = _mem_storage_with_instance("i1")
        reg = ReleaseRegistry(storage, "e", "1", "v")
        reg.pin("i1")
        reg.unpin(actor="t")
        assert reg.pinned_instance() is None


# ---------------------------------------------------------------------------
# unit: splitter + policy
# ---------------------------------------------------------------------------

class TestSplitter:
    def test_deterministic_and_monotone(self):
        lo = TrafficSplitter(0.1)
        hi = TrafficSplitter(0.5)
        queries = [{"user": f"u{i}"} for i in range(2000)]
        picks = [lo.routes_candidate(q) for q in queries]
        assert picks == [lo.routes_candidate(q) for q in queries]
        share = sum(picks) / len(picks)
        assert 0.06 < share < 0.14  # ~10% of cohort space
        # ramping only ADDS cohort, never churns users between arms
        assert all(hi.routes_candidate(q)
                   for q, p in zip(queries, picks) if p)

    def test_edges_and_fallback_key(self):
        s = TrafficSplitter(0.0)
        assert not s.routes_candidate({"user": "u1"})
        s.set_fraction(1.0)
        assert s.routes_candidate({"user": "u1"})
        # entity-less queries still split deterministically
        assert (s.cohort_key({"num": 3})
                == s.cohort_key({"num": 3}))
        assert s.route({"user": "u1"}) == "candidate"
        s.shadow = True
        assert s.route({"user": "u1"}) == "stable"


class TestPolicy:
    def test_verdicts(self):
        p = HealthPolicy(min_queries=10, max_error_rate=0.1,
                         error_rate_slack=0.05, p99_regression=2.0)
        ok = ArmWindow(queries=100, errors=1, p99=0.010)
        assert p.evaluate(ok, ArmWindow(3, 0, None)).action == "hold"
        assert p.evaluate(
            ok, ArmWindow(50, 20, 0.01)).action == "rollback"
        # relative gate: stable erroring too, candidate within slack
        noisy = ArmWindow(queries=100, errors=8, p99=0.010)
        assert p.evaluate(
            noisy, ArmWindow(50, 4, 0.01)).action == "advance"
        # p99 regression
        assert p.evaluate(
            ok, ArmWindow(50, 0, 0.05)).action == "rollback"
        assert p.evaluate(
            ok, ArmWindow(50, 0, 0.012)).action == "advance"

    def test_ramp_schedule(self):
        p = HealthPolicy()
        assert p.next_fraction(0.01) == 0.05
        assert p.next_fraction(0.25) == 1.0
        assert p.next_fraction(1.0) is None

    def test_window_quantile(self):
        from predictionio_tpu.obs import StreamingHistogram

        h = StreamingHistogram(bounds=[0.01, 0.1, 1.0])
        for _ in range(100):
            h.observe(0.005)  # old traffic: fast
        start = h.bucket_counts()
        for _ in range(50):
            h.observe(0.5)    # window traffic: slow
        q = window_quantile(start, h.bucket_counts(), 0.99)
        assert 0.1 < q <= 1.0  # sees ONLY the window's slow samples
        assert window_quantile(start, start, 0.99) is None


# ---------------------------------------------------------------------------
# E2E: the full canary lifecycle over a real trained engine
# ---------------------------------------------------------------------------

def _synth_als_model(seed: int, n_users: int = 24, n_items: int = 24,
                     rank: int = 4):
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.als import ALSModel, ALSParams

    rng = np.random.default_rng(seed)
    return ALSModel(
        user_factors=rng.standard_normal(
            (n_users, rank)).astype(np.float32),
        item_factors=rng.standard_normal(
            (n_items, rank)).astype(np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))


@pytest.fixture(scope="module")
def two_releases():
    """Two COMPLETED instances of the same engine triple with
    persisted model blobs — the post-train state `deploy`/`reload`/
    `start_canary` load from, synthesized without the training path."""
    from predictionio_tpu.data.storage.base import Model
    from predictionio_tpu.workflow import persistence

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "relapp"))
    ctx = Context(app_name="relapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("relapp", rank=4)
    ids = []
    for n, seed in (("rl1", 1), ("rl2", 2)):
        start = T0 + timedelta(minutes=len(ids))
        storage.engine_instances().insert(EngineInstance(
            id=n, status=STATUS_COMPLETED, start_time=start,
            end_time=start, engine_id="rel", engine_version="1",
            engine_variant="engine.json", engine_factory="synthetic"))
        storage.models().insert(Model(
            id=n,
            models=persistence.dumps_models([_synth_als_model(seed)])))
        ids.append(n)
    return ctx, engine, ep, ids[0], ids[1]


def _serve(two_releases, iid, config=None):
    ctx, engine, ep, _, _ = two_releases
    inst = ctx.storage.engine_instances().get(iid)
    models = load_models_for_deploy(ctx, engine, inst, ep)
    qs = QueryServer(ctx, engine, ep, models, inst,
                     config or ServerConfig(warm_start=False))
    srv = create_engine_server(qs, "127.0.0.1", 0).start_background()
    return qs, srv


class PoisonServing:
    """Candidate serving that always fails — the 'bad retrain'."""

    def supplement(self, q):
        raise RuntimeError("candidate poison")

    def serve(self, q, ps):  # pragma: no cover — supplement raises
        raise RuntimeError("candidate poison")


def _drive_until(port, qs, pred, timeout=30.0, n_users=20):
    """Fire query traffic until ``pred()`` or timeout; returns the
    collected (status, body) pairs."""
    results = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and not pred():
        for u in range(n_users):
            results.append(call(port, "POST", "/queries.json",
                                {"user": f"u{u}", "num": 2}))
        time.sleep(0.02)
    return results


class TestCanaryLifecycle:
    def test_erroring_candidate_auto_rolls_back(self, two_releases):
        ctx, engine, ep, iid1, iid2 = two_releases
        qs, srv = _serve(two_releases, iid1)
        try:
            policy = HealthPolicy(window_sec=0.2, min_queries=5,
                                  ramp=(0.5, 1.0),
                                  max_error_rate=0.2)
            ctl = qs.start_canary(iid2, fraction=0.5, policy=policy,
                                  actor="test", reason="bad retrain")
            assert qs._candidate is not None
            qs._candidate.serving = PoisonServing()  # the bad model

            results = _drive_until(
                srv.port, qs, lambda: not ctl.active)
            assert not ctl.active, \
                "controller did not conclude within the timeout"
            assert ctl.outcome == "rolled_back"
            assert qs._candidate is None
            assert qs.instance.id == iid1  # stable untouched

            # canary blast radius: SOME queries saw candidate 500s
            # while it was live, but stable answers stayed correct
            # and post-rollback everything is 200 again
            assert any(status == 500 for status, _ in results)
            ok = [b for status, b in results if status == 200]
            assert ok and all(b.get("itemScores") for b in ok)
            status, body = call(srv.port, "POST", "/queries.json",
                                {"user": "u1", "num": 2})
            assert status == 200 and body["itemScores"]

            # the registry recorded the full story
            status, rel = call(srv.port, "GET", "/release.json")
            assert status == 200
            actions = [e["action"] for e in rel["history"]]
            assert "canary" in actions and "rollback" in actions
            assert rel["rollout"]["outcome"] == "rolled_back"
            assert rel["serving"]["stableInstanceId"] == iid1
            assert rel["arms"]["candidate"]["errors"] > 0
        finally:
            srv.shutdown()

    def test_healthy_candidate_ramps_to_pinned_stable(
            self, two_releases):
        ctx, engine, ep, iid1, iid2 = two_releases
        qs, srv = _serve(two_releases, iid1)
        try:
            # p99_regression is effectively disabled: with a 3-query
            # minimum sample, one scheduler hiccup on a candidate
            # query under full-suite load flips the 2x default and
            # rolls back a healthy canary (observed flake). This test
            # exercises the ramp/promote mechanics; the latency gate
            # has its own coverage in TestPolicy.
            policy = HealthPolicy(window_sec=0.15, min_queries=3,
                                  ramp=(0.25, 1.0),
                                  p99_regression=1000.0)
            ctl = qs.start_canary(iid2, policy=policy, actor="test",
                                  reason="healthy retrain")
            assert ctl.splitter.fraction == 0.25  # first ramp step

            results = _drive_until(
                srv.port, qs, lambda: not ctl.active)
            assert not ctl.active, \
                "controller did not conclude within the timeout"
            assert ctl.outcome == "promoted"
            # zero failed queries across the entire ramp + promote swap
            assert all(status == 200 for status, _ in results)
            assert all(b.get("itemScores") for _, b in results)

            # the candidate IS the serving stable now, and pinned
            assert qs.instance.id == iid2
            st = qs.releases.state()
            assert st["stable"] == iid2 and st["pinned"] == iid2
            actions = [e.action for e in qs.releases.history()]
            assert "ramp" in actions and "promote" in actions
            status, body = call(srv.port, "GET", "/status.json")
            assert body["release"]["stable"] == iid2
            # reload now binds the pinned (promoted) release
            status, body = call(srv.port, "POST", "/reload")
            assert status == 200 and body["engineInstanceId"] == iid2
        finally:
            srv.shutdown()

    def test_shadow_mirrors_without_affecting_answers(
            self, two_releases):
        ctx, engine, ep, iid1, iid2 = two_releases
        qs, srv = _serve(two_releases, iid1)
        try:
            policy = HealthPolicy(window_sec=0.2, min_queries=3)
            ctl = qs.start_canary(iid2, shadow=True, policy=policy,
                                  actor="test")
            # even a POISONED shadow candidate never surfaces to users
            qs._candidate.serving = PoisonServing()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and ctl.windows < 2:
                for u in range(10):
                    status, body = call(
                        srv.port, "POST", "/queries.json",
                        {"user": f"u{u}", "num": 2})
                    assert status == 200 and body["itemScores"]
                time.sleep(0.02)
            assert ctl.windows >= 2, "gate windows did not evaluate"
            # shadow never auto-promotes or auto-rolls-back
            assert ctl.active and qs.instance.id == iid1
            # the mirrored candidate errors were counted
            q, e, _ = qs.release_arm_snapshot("candidate")
            assert e > 0
            # operator rollback ends it
            status, body = call(srv.port, "POST", "/release/rollback")
            assert status == 200
            assert not ctl.active and qs._candidate is None
        finally:
            srv.shutdown()

    def test_canary_http_route_and_guards(self, two_releases):
        ctx, engine, ep, iid1, iid2 = two_releases
        qs, srv = _serve(two_releases, iid1)
        try:
            # guards: unknown instance, stable-as-candidate
            status, _ = call(srv.port, "POST", "/release/canary",
                             {"instanceId": "nope"})
            assert status == 404
            status, _ = call(srv.port, "POST", "/release/canary",
                             {"instanceId": iid1})
            assert status == 400
            status, _ = call(srv.port, "POST", "/release/canary", {})
            assert status == 400
            # promote with nothing bound
            status, _ = call(srv.port, "POST", "/release/promote")
            assert status == 409
            # start over HTTP with an explicit fraction
            status, body = call(srv.port, "POST", "/release/canary",
                                {"instanceId": iid2, "fraction": 0.5,
                                 "reason": "via http"})
            assert status == 200
            assert body["rollout"]["fraction"] == 0.5
            # double-start is rejected while one is live
            status, _ = call(srv.port, "POST", "/release/canary",
                             {"instanceId": iid2})
            assert status == 409
            # operator promote skips the rest of the ramp
            status, body = call(srv.port, "POST", "/release/promote")
            assert status == 200 and body["engineInstanceId"] == iid2
            assert qs.instance.id == iid2
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# CLI: ptpu release / status / undeploy
# ---------------------------------------------------------------------------

class TestReleaseCLI:
    def test_list_show_pin(self, capsys):
        storage = _mem_storage_with_instance("i1")
        assert cli_main(["release", "list"], storage=storage) == 0
        assert "No releases" in capsys.readouterr().out
        rc = cli_main(["release", "pin", "i1", "--engine-id", "e",
                       "--engine-json", "v", "--reason", "known good"],
                      storage=storage)
        assert rc == 0
        assert cli_main(["release", "list"], storage=storage) == 0
        out = capsys.readouterr().out
        assert "e v1" in out and "pinned=i1" in out
        assert cli_main(["release", "show", "--engine-id", "e",
                         "--engine-json", "v"], storage=storage) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["state"]["pinned"] == "i1"
        assert payload["history"][-1]["reason"] == "known good"
        # pin guards: unknown instance
        rc = cli_main(["release", "pin", "nope", "--engine-id", "e",
                       "--engine-json", "v"], storage=storage)
        assert rc == 1
        # unpin
        rc = cli_main(["release", "pin", "--clear", "--engine-id", "e",
                       "--engine-json", "v"], storage=storage)
        assert rc == 0
        assert ReleaseRegistry(storage, "e", "1",
                               "v").pinned_instance() is None

    def test_status_reports_releases(self, capsys):
        storage = _mem_storage_with_instance("i1")
        ReleaseRegistry(storage, "e", "1", "v").record_deploy(
            "i1", actor="test")
        assert cli_main(["status"], storage=storage) == 0
        out = capsys.readouterr().out
        assert "Release [e v1]: stable=i1" in out

    def test_undeploy_records_history(self, two_releases, capsys):
        ctx, engine, ep, iid1, _ = two_releases
        qs, srv = _serve(two_releases, iid1)
        rc = cli_main(["undeploy", "--ip", "127.0.0.1",
                       "--port", str(srv.port)],
                      storage=ctx.storage)
        assert rc == 0
        out = capsys.readouterr().out
        assert iid1 in out
        events = ReleaseRegistry(
            ctx.storage, "rel", "1", "engine.json").history()
        undeploys = [e for e in events if e.action == "undeploy"]
        assert undeploys and undeploys[-1].instance_id == iid1

    def test_release_status_falls_back_to_storage(self, capsys):
        storage = _mem_storage_with_instance("i1")
        ReleaseRegistry(storage, "default", "1",
                        "engine.json").record_deploy("i1")
        rc = cli_main(["release", "status", "--port", "1"],
                      storage=storage)
        assert rc == 0
        captured = capsys.readouterr()
        assert "unreachable" in captured.err
        assert json.loads(captured.out)["state"]["stable"] == "i1"


# ---------------------------------------------------------------------------
# fake-engine scaffolding: parallel dispatch + reload warm race
# ---------------------------------------------------------------------------

@dataclass
class FQ:
    user: str = ""
    num: int = 1


class FakeModel:
    def __init__(self, tag):
        self.tag = tag
        self.algo_gen = None


class FakeAlgo:
    query_class = FQ

    def __init__(self, gen, predict_delay=0.0, warm_gate=None):
        self.gen = gen
        self.predict_delay = predict_delay
        self.warm_gate = warm_gate  # Event the test releases
        self.warm_runs = 0

    def bind_serving(self, ctx):
        pass

    def prepare_serving_model(self, model, max_batch):
        # stamp the pairing: a torn binding (this algo generation
        # serving another bind's model) is detected at predict time
        model.algo_gen = self.gen
        return model

    def warm_serving(self, model, max_batch):
        if self.warm_gate is not None:
            assert self.warm_gate.wait(timeout=30)
        self.warm_runs += 1

    def predict(self, model, query):
        if self.predict_delay:
            time.sleep(self.predict_delay)
        assert model.algo_gen == self.gen, \
            f"TORN BINDING: algo gen {self.gen} got model of gen " \
            f"{model.algo_gen}"
        return model.tag

    def batch_predict(self, model, queries):
        if self.predict_delay:
            time.sleep(self.predict_delay)
        return [model.tag] * len(queries)


class FakeServing:
    def supplement(self, q):
        return q

    def serve(self, q, predictions):
        return {"tags": list(predictions)}


class FakeEngine:
    def __init__(self, n_algos=1, predict_delay=0.0, gated_warm=False):
        self.n_algos = n_algos
        self.predict_delay = predict_delay
        self.gated_warm = gated_warm
        self.gen = 0
        self.gates = []  # one Event per bind generation
        self.made = []   # the algorithm list of each generation

    def make_algorithms(self, ep):
        self.gen += 1
        gate = threading.Event() if self.gated_warm else None
        self.gates.append(gate)
        algos = [FakeAlgo(self.gen, self.predict_delay, gate)
                 for _ in range(self.n_algos)]
        self.made.append(algos)
        return algos

    def make_serving(self, ep):
        return FakeServing()


def _fake_instance(storage, iid, engine_id="fk"):
    # start_time ordering makes the LAST-created instance the
    # "latest COMPLETED" reload target
    start = (datetime.now(timezone.utc)
             + timedelta(seconds=int(iid[-1])))
    inst = EngineInstance(
        id=iid, status=STATUS_COMPLETED, start_time=start,
        end_time=start, engine_id=engine_id, engine_version="1",
        engine_variant="engine.json", engine_factory="fake")
    storage.engine_instances().insert(inst)
    return inst


def _fake_ctx():
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "fkapp"))
    return Context(app_name="fkapp", _storage=storage)


class TestParallelAlgoDispatch:
    def test_independent_algorithms_dispatch_concurrently(self):
        """Satellite: the per-algorithm predict loop (the reference's
        CreateServer.scala 'TODO: Parallelize') runs concurrently —
        wall time of a 3-algorithm query is ~one delay, not three."""
        ctx = _fake_ctx()
        inst = _fake_instance(ctx.storage, "p1")
        engine = FakeEngine(n_algos=3, predict_delay=0.2)
        qs = QueryServer(ctx, engine, object(),
                         [FakeModel("a"), FakeModel("b"),
                          FakeModel("c")],
                         inst, ServerConfig(warm_start=False))
        t0 = time.monotonic()
        result = qs.query({"user": "u1"})
        wall = time.monotonic() - t0
        # order preserved (serving sees params order), and concurrent:
        # serial would be >= 0.6s
        assert result == {"tags": ["a", "b", "c"]}
        assert wall < 0.45, f"predictions look serial: {wall:.2f}s"

    def test_batched_dispatch_also_concurrent(self):
        """The micro-batcher / batch-predict lane shares the fix: one
        concurrent batch_predict dispatch per algorithm."""
        ctx = _fake_ctx()
        inst = _fake_instance(ctx.storage, "p2")
        engine = FakeEngine(n_algos=3, predict_delay=0.2)
        qs = QueryServer(ctx, engine, object(),
                         [FakeModel("a"), FakeModel("b"),
                          FakeModel("c")],
                         inst, ServerConfig(warm_start=False))
        t0 = time.monotonic()
        out = qs.query_batch([{"user": "u1"}, {"user": "u2"}])
        wall = time.monotonic() - t0
        assert [o["tags"] for o in out] == [["a", "b", "c"]] * 2
        assert wall < 0.45, f"batch dispatch looks serial: {wall:.2f}s"


class TestReloadWarmRace:
    """The documented warm race (engineserver.py ~:188-216): a stale
    deploy-time warm thread must never flip ``warm_done`` while a
    post-reload re-warm is still compiling, and concurrent queries
    during a reload must never observe a torn model binding."""

    def _boot(self, monkeypatch):
        ctx = _fake_ctx()
        inst1 = _fake_instance(ctx.storage, "w1")
        _fake_instance(ctx.storage, "w2")  # later start_time → latest
        engine = FakeEngine(gated_warm=True)

        def fake_load(ctx_, engine_, instance, ep):
            return [FakeModel(instance.id)]

        import predictionio_tpu.workflow.core as wfcore
        monkeypatch.setattr(wfcore, "load_models_for_deploy", fake_load)
        qs = QueryServer(ctx, engine, object(), [FakeModel("w1")],
                         inst1, ServerConfig(warm_start=True))
        return ctx, engine, qs

    def test_stale_warm_thread_never_reports_warm(self, monkeypatch):
        ctx, engine, qs = self._boot(monkeypatch)
        gate1 = engine.gates[0]  # deploy-time warm, still blocked
        assert not qs.warm_done.is_set()
        qs.reload()  # rebinds to w2, starts gen-2 re-warm
        gate2 = engine.gates[1]
        assert not qs.warm_done.is_set()
        # release the STALE deploy-time warm thread; it must NOT set
        # warm_done — the re-warm (gen 2) is still compiling
        gate1.set()
        stale_algo = engine.made[0][0]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and stale_algo.warm_runs == 0:
            time.sleep(0.01)
        assert stale_algo.warm_runs == 1  # the stale thread finished
        time.sleep(0.1)  # give a buggy stale thread time to misfire
        assert not qs.warm_done.is_set(), \
            "stale warm thread flipped warm_done during re-warm"
        # releasing the re-warm completes the warmup for real
        gate2.set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and not qs.warm_done.is_set():
            time.sleep(0.01)
        assert qs.warm_done.is_set()

    def test_concurrent_queries_never_see_torn_binding(
            self, monkeypatch):
        ctx, engine, qs = self._boot(monkeypatch)
        for gate in engine.gates:
            gate.set()
        stop = threading.Event()
        failures = []
        tags = set()

        def hammer():
            while not stop.is_set():
                try:
                    out = qs.query({"user": "u1"})
                    tags.add(out["tags"][0])
                except Exception as e:  # noqa: BLE001 — recorded
                    failures.append(repr(e))

        threads = [threading.Thread(target=hammer) for _ in range(6)]
        for t in threads:
            t.start()
        try:
            for _ in range(10):  # reload under fire, repeatedly
                qs.reload()
                engine.gates[-1].set()  # release each re-warm
                time.sleep(0.03)       # let queries land mid-swap
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not failures, f"torn binding observed: {failures[:3]}"
        # queries saw only whole bindings: the models of w1 and w2
        assert tags <= {"w1", "w2"} and "w2" in tags
        # after the final reload every new query is the new release
        assert qs.query({"user": "u1"})["tags"] == ["w2"]
