"""Crash-consistency fuzz (VERDICT r3 task 5): SIGKILL a writer
mid-workload, restart, and check the acknowledged-batch oracle.

Contract (the transactional guarantee ``JDBCLEvents.scala`` bought from
the database, re-earned per backend):

- every ACKNOWLEDGED batch (insert_batch returned) is fully present;
- the at-most-one in-flight batch is fully present or fully absent —
  never a torn prefix of fresh ids;
- no duplicates;
- the store still passes reads/writes after restart (no poisoned log).

The writer subprocess appends one fsync'd ack line per completed batch;
the parent kills it at a random moment, then replays the oracle against
a FRESH client over the same on-disk state. For the storage server the
KILL hits the server between a client's insert and its response (the
client sees a connection error → batch unacked; the backing sqlite
transaction decides atomically).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

#: events per batch — small enough to keep runtime down, large enough
#: that a mid-batch kill window exists
BATCH = 40
ROUNDS = 6  # kill/restart cycles per backend

WRITER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"

    backend = sys.argv[1]
    root = sys.argv[2]
    ack_path = sys.argv[3]
    start_batch = int(sys.argv[4])
    BATCH = int(sys.argv[5])

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import Storage

    def env_for(backend, root):
        if backend == "sqlite":
            return {"PIO_HOME": root}
        if backend == "localfs":
            return {"PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
                    "PIO_STORAGE_SOURCES_FS_PATH": root,
                    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
                    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
                    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS"}
        if backend == "segmentfs":
            return {"PIO_STORAGE_SOURCES_SEG_TYPE": "segmentfs",
                    "PIO_STORAGE_SOURCES_SEG_PATH": root,
                    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SEG",
                    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SEG",
                    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SEG"}
        if backend == "remote":
            return {"PIO_STORAGE_SOURCES_NET_TYPE": "remote",
                    "PIO_STORAGE_SOURCES_NET_URL": root,  # url here
                    "PIO_STORAGE_SOURCES_NET_SECRET": "crash",
                    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NET",
                    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NET",
                    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NET"}
        if backend == "s3":
            return {"PIO_STORAGE_SOURCES_OBJ_TYPE": "s3",
                    "PIO_STORAGE_SOURCES_OBJ_ENDPOINT": root,  # url
                    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "OBJ",
                    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "OBJ",
                    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ"}
        raise SystemExit(f"unknown backend {backend}")

    es = Storage(env=env_for(backend, root)).events()
    es.init(1)
    ack = open(ack_path, "a")
    k = start_batch
    print("READY", flush=True)
    while True:
        evs = [Event(event="rate", entity_type="user",
                     entity_id=f"b{k}e{j}",
                     target_entity_type="item", target_entity_id=f"i{j}",
                     properties=DataMap({"rating": float(j % 5 + 1)}))
               for j in range(BATCH)]
        try:
            es.insert_batch(evs, 1)
        except Exception as e:  # server killed mid-request: unacked
            print(f"UNACKED {k}: {type(e).__name__}", flush=True)
            sys.exit(7)
        ack.write(f"{k}\\n")
        ack.flush()
        os.fsync(ack.fileno())
        k += 1
""")


def _oracle_check(events, acked: set, max_batch: int):
    """Assert the acknowledged-batch contract over a fresh read."""
    per_batch: dict = {}
    seen = set()
    for e in events:
        assert e.entity_id not in seen, f"duplicate {e.entity_id}"
        seen.add(e.entity_id)
        b, j = e.entity_id[1:].split("e")
        per_batch.setdefault(int(b), set()).add(int(j))
    for k in acked:
        got = per_batch.get(k, set())
        assert len(got) == BATCH, \
            f"acked batch {k} torn: {len(got)}/{BATCH} rows"
    for k, got in per_batch.items():
        assert len(got) in (0, BATCH), \
            f"unacked batch {k} torn: {len(got)}/{BATCH} rows"
        assert k <= max_batch, f"ghost batch {k}"


def _storage_for(backend, root):
    from predictionio_tpu.data.storage import Storage
    env = {
        "sqlite": {"PIO_HOME": root},
        "localfs": {"PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
                    "PIO_STORAGE_SOURCES_FS_PATH": root,
                    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
                    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
                    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS"},
        "segmentfs": {"PIO_STORAGE_SOURCES_SEG_TYPE": "segmentfs",
                      "PIO_STORAGE_SOURCES_SEG_PATH": root,
                      "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SEG",
                      "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SEG",
                      "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SEG"},
        "s3": {"PIO_STORAGE_SOURCES_OBJ_TYPE": "s3",
               "PIO_STORAGE_SOURCES_OBJ_ENDPOINT": root,
               "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "OBJ",
               "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "OBJ",
               "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ"},
    }[backend]
    return Storage(env=env)


def _run_killer_rounds(backend: str, root: str, tmp_path, seed: int):
    """Spawn writer → SIGKILL at a random point → fresh-client oracle,
    ROUNDS times over the same store."""
    rng = np.random.default_rng(seed)
    ack_path = tmp_path / f"acks_{backend}.log"
    ack_path.touch()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    writer_py = tmp_path / "writer.py"
    writer_py.write_text(WRITER)

    next_batch = 0
    for rnd in range(ROUNDS):
        p = subprocess.Popen(
            [sys.executable, str(writer_py), backend, root,
             str(ack_path), str(next_batch), str(BATCH)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        assert p.stdout.readline().strip() == "READY"
        # let it write for a random slice, then kill WITHOUT warning
        time.sleep(float(rng.uniform(0.02, 0.4)))
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)

        acked = {int(x) for x in
                 ack_path.read_text().split() if x.strip()}
        # fresh client over the same on-disk state
        s = _storage_for(backend, root)
        events = list(s.events().find(1))
        _oracle_check(events, acked, 20_000_000)
        # the store must still accept writes after recovery. Probe ids
        # live in a DISJOINT space (>=10M) so a later writer round can
        # never walk into them
        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event
        probe_k = 10_000_000 + rnd
        s.events().insert_batch(
            [Event(event="rate", entity_type="user",
                   entity_id=f"b{probe_k}e{j}",
                   target_entity_type="item",
                   target_entity_id=f"i{j}",
                   properties=DataMap({"rating": 1.0}))
             for j in range(BATCH)], 1)
        with open(ack_path, "a") as f:
            f.write(f"{probe_k}\n")
        next_batch = (max((int(b) for b in acked if b < 10_000_000),
                          default=0) + 1000)  # fresh id space per round
        s.events().close()


@pytest.mark.parametrize("backend", ["sqlite", "localfs", "segmentfs"])
def test_kill_writer_midbatch(backend, tmp_path):
    import zlib

    # crc32, not hash(): str hashing is per-process randomized, and a
    # failing kill-timing window must be reproducible from the seed
    _run_killer_rounds(backend, str(tmp_path / "store"), tmp_path,
                       seed=zlib.crc32(backend.encode()))


def test_kill_writer_midbatch_objectstore(tmp_path):
    """The S3-contract backend joins the kill fuzzer: the fake object
    store runs in THIS process (it survives; the killed party is the
    writer/client, as when a pod host dies mid-upload), and one batch =
    one immutable object PUT = per-object atomicity carries the
    all-or-nothing contract."""
    import zlib

    from predictionio_tpu.data.storage.objectstore import (
        FakeObjectStoreServer,
    )

    srv = FakeObjectStoreServer(str(tmp_path / "bucket"))
    srv.start_background()
    try:
        _run_killer_rounds(
            "s3", f"http://127.0.0.1:{srv.port}/bucket", tmp_path,
            seed=zlib.crc32(b"s3"))
    finally:
        srv.shutdown()


def test_kill_storage_server_between_insert_and_response(tmp_path):
    """The server-side crash window: SIGKILL the storage SERVER while a
    client's insert_batch is in flight. The client sees an error (batch
    unacked); after restart on the same volume the backing sqlite
    transaction must have decided atomically — fully present or fully
    absent."""
    from conftest import start_sqlite_backed_storage_server

    rng = np.random.default_rng(77)
    ack_path = tmp_path / "acks_remote.log"
    ack_path.touch()
    writer_py = tmp_path / "writer.py"
    writer_py.write_text(WRITER)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))

    next_batch = 0
    for _ in range(4):
        # a fresh server process each round, same volume
        srv = subprocess.Popen(
            [sys.executable, "-c", textwrap.dedent(f"""
                import os
                os.environ["JAX_PLATFORMS"] = "cpu"
                from predictionio_tpu.data.storage import Storage
                from predictionio_tpu.server.storageserver import (
                    create_storage_server)
                backing = Storage(env={{"PIO_HOME": {str(tmp_path / 'vol')!r}}})
                srv = create_storage_server(backing, host="127.0.0.1",
                                            port=0, secret="crash")
                print(srv.port, flush=True)
                srv.serve_forever()
            """)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        port = int(srv.stdout.readline())

        w = subprocess.Popen(
            [sys.executable, str(writer_py), "remote",
             f"http://127.0.0.1:{port}", str(ack_path),
             str(next_batch), str(BATCH)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        assert w.stdout.readline().strip() == "READY"
        time.sleep(float(rng.uniform(0.05, 0.5)))
        srv.send_signal(signal.SIGKILL)  # kill the SERVER, not writer
        srv.wait(timeout=30)
        w.wait(timeout=60)  # writer exits 7 on the failed request

        acked = {int(x) for x in
                 ack_path.read_text().split() if x.strip()}
        s = _storage_for("sqlite", str(tmp_path / "vol"))
        events = list(s.events().find(1))
        _oracle_check(events, acked, max(acked, default=0) + 10_000)
        next_batch = (max((int(b) for b in acked), default=0) + 1000)
        s.events().close()


def test_storage_server_restart_clients_retry_and_resync(tmp_path):
    """The HA drill (VERDICT r3 task 7): kill the storage server
    mid-service, restart it on the same volume ON THE SAME PORT; a
    long-lived client — with retries and a warm ETag cache — keeps
    working: reads resync (304 against the reborn server, fresh
    download after new writes), writes land exactly once."""
    import threading
    import urllib.error

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.server.storageserver import (
        create_storage_server,
    )

    vol = str(tmp_path / "vol")

    def start(port=0):
        backing = Storage(env={"PIO_HOME": vol})
        srv = create_storage_server(backing, host="127.0.0.1",
                                    port=port, secret="ha")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    srv = start()
    port = srv.port
    env = {
        "PIO_STORAGE_SOURCES_NET_TYPE": "remote",
        "PIO_STORAGE_SOURCES_NET_URL": f"http://127.0.0.1:{port}",
        "PIO_STORAGE_SOURCES_NET_SECRET": "ha",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NET",
    }
    s = Storage(env=env)
    app_id = s.apps().insert(App(0, "haapp"))
    es = s.events()
    es.init(app_id)
    es.insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{j}",
               target_entity_type="item", target_entity_id=f"i{j}",
               properties=DataMap({"rating": 1.0}))
         for j in range(50)], app_id)
    b1 = es.find_columnar(app_id, ordered=False, with_props=False)
    assert b1.n == 50  # warm ETag cache

    # hard-stop the server (client keeps its connection-less HTTP
    # model + warm cache), restart on the SAME port and volume
    srv.shutdown()
    from predictionio_tpu.data.storage.base import StorageError
    with pytest.raises(StorageError):
        # while down, a read fails after retries — never hangs
        es.find_columnar(app_id, ordered=False, with_props=False)
    srv2 = start(port=port)
    try:
        # resync: the reborn server recomputes the same content ETag,
        # so the warm client gets a 304 and reuses its CACHED batch
        key = next(iter(es.c.columnar_cache))
        etag_before, batch_before = es.c.columnar_cache[key]
        b2 = es.find_columnar(app_id, ordered=False, with_props=False)
        assert b2.n == 50
        assert es.c.columnar_cache[key][0] == etag_before
        assert es.c.columnar_cache[key][1] is batch_before  # 304 path
        # writes land exactly once post-restart; reads see them
        es.insert_batch(
            [Event(event="rate", entity_type="user", entity_id="uX",
                   target_entity_type="item", target_entity_id="iX",
                   properties=DataMap({"rating": 5.0}))], app_id)
        b3 = es.find_columnar(app_id, ordered=False, with_props=False)
        assert b3.n == 51
        assert len(list(es.find(app_id))) == 51
    finally:
        srv2.shutdown()


def test_localfs_torn_tail_recovers_and_next_append_is_clean(tmp_path):
    """Direct torn-tail regression: a partial trailing line (killed
    writer residue) must be dropped AND truncated so later appends
    don't concatenate onto it."""
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event

    root = str(tmp_path / "store")
    s = _storage_for("localfs", root)
    es = s.events()
    es.init(1)
    es.insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"b0e{j}",
               target_entity_type="item", target_entity_id=f"i{j}",
               properties=DataMap({"rating": 1.0}))
         for j in range(5)], 1)
    # simulate the killed writer: append half a record, no newline —
    # torn INSIDE a multi-byte UTF-8 character (the é of "café"), which
    # surfaces as UnicodeDecodeError rather than JSONDecodeError
    torn = '{"op": "putb", "events": [{"event": "café'.encode()[:-1]
    assert torn[-1] == 0xC3  # ends on a lead byte: mid-character tear
    log = None
    for dirpath, _, files in os.walk(root):
        for fn in files:
            if fn.endswith(".jsonl"):  # NOT the .jsonl.lock sidecar
                log = os.path.join(dirpath, fn)
                break
    assert log, os.listdir(root)
    with open(log, "ab") as f:
        f.write(torn)
    # a FRESH client must read the 5 good rows, drop the torn tail...
    s2 = _storage_for("localfs", root)
    assert len(list(s2.events().find(1))) == 5
    # ...but a NEWLINE-TERMINATED corrupt final line is committed-data
    # corruption (bit-rot), not torn-writer residue — it must RAISE,
    # never silently truncate an acknowledged batch away
    corrupt = str(tmp_path / "corrupt")
    sc = _storage_for("localfs", corrupt)
    sc.events().init(1)
    sc.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"c{j}",
               target_entity_type="item", target_entity_id=f"i{j}",
               properties=DataMap({"rating": 1.0}))
         for j in range(3)], 1)
    clog = os.path.join(corrupt, "events_1.jsonl")
    raw = open(clog, "rb").read()
    assert raw.endswith(b"\n")
    open(clog, "wb").write(raw[:len(raw) // 2] + b"garbage\n")
    sc2 = _storage_for("localfs", corrupt)
    with pytest.raises(json.JSONDecodeError):
        list(sc2.events().find(1))
    assert os.path.getsize(clog) > 0  # nothing was destroyed
    # ...and a subsequent append must land on a clean line
    s2.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"b1e{j}",
               target_entity_type="item", target_entity_id=f"i{j}",
               properties=DataMap({"rating": 2.0}))
         for j in range(5)], 1)
    s3 = _storage_for("localfs", root)
    got = sorted(e.entity_id for e in s3.events().find(1))
    assert got == sorted([f"b0e{j}" for j in range(5)]
                         + [f"b1e{j}" for j in range(5)])


# -- native bulk-import lane under SIGKILL (round 4) -------------------

IMPORT_WRITER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"

    root = sys.argv[1]
    files_dir = sys.argv[2]
    ack_path = sys.argv[3]
    start_file = int(sys.argv[4])

    from predictionio_tpu.data.storage import Storage

    es = Storage(env={
        "PIO_STORAGE_SOURCES_SEG_TYPE": "segmentfs",
        "PIO_STORAGE_SOURCES_SEG_PATH": root,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SEG",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SEG",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SEG",
    }).events()
    es.init(1)
    ack = open(ack_path, "a")
    k = start_file
    print("READY", flush=True)
    while True:
        path = os.path.join(files_dir, f"f{k}.jsonl")
        if not os.path.exists(path):
            break
        es.import_jsonl(path, 1)
        ack.write(f"{k}\\n")
        ack.flush()
        os.fsync(ack.fileno())
        k += 1
""")


def test_kill_native_import_midblock(tmp_path):
    """SIGKILL a process running the native segmentfs bulk-import lane
    mid-file. Contract: acked files fully present; the in-flight file's
    events form a clean BLOCK PREFIX (blocks are the atomic publish
    unit — count divisible by the per-block line count, never a torn
    segment); the log stays readable and writable afterwards."""
    from predictionio_tpu.native import codec

    if codec() is None:  # Python lane ignores PIO_IMPORT_BLOCK — the
        pytest.skip("no native toolchain")  # contract under test is gone

    rng = np.random.default_rng(0xC0DEC)
    root = str(tmp_path / "store")
    files_dir = tmp_path / "files"
    files_dir.mkdir()
    # small blocks: each import commits in several atomic steps, so a
    # kill lands inside a file with near-certainty
    line = ('{"event": "rate", "entityType": "user", '
            '"entityId": "F%DE%", "targetEntityType": "item", '
            '"targetEntityId": "i", "properties": {"rating": 1.0}, '
            '"eventTime": "2015-03-01T00:00:00.000Z"}')
    per_line = len(line) + 2
    lines_per_block = 8
    n_files, lines_per_file = 40, 64
    for k in range(n_files):
        with open(files_dir / f"f{k}.jsonl", "w") as f:
            for j in range(lines_per_file):
                f.write(line.replace("%DE%", f"{k}_{j}") + "\n")

    ack_path = tmp_path / "acks.log"
    ack_path.touch()
    env = {k2: v for k2, v in os.environ.items()
           if k2 not in ("XLA_FLAGS",)}
    env["JAX_PLATFORMS"] = "cpu"
    env["PIO_IMPORT_BLOCK"] = str(per_line * lines_per_block)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    writer_py = tmp_path / "import_writer.py"
    writer_py.write_text(IMPORT_WRITER)

    from predictionio_tpu.data.storage import Storage

    def store():
        return Storage(env={
            "PIO_STORAGE_SOURCES_SEG_TYPE": "segmentfs",
            "PIO_STORAGE_SOURCES_SEG_PATH": root,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SEG",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SEG",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SEG",
        })

    next_file = 0
    for rnd in range(5):
        p = subprocess.Popen(
            [sys.executable, str(writer_py), root, str(files_dir),
             str(ack_path), str(next_file)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        assert p.stdout.readline().strip() == "READY"
        time.sleep(float(rng.uniform(0.01, 0.25)))
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)

        acked = {int(x) for x in
                 ack_path.read_text().split() if x.strip()}
        s = store()
        events = list(s.events().find(1))  # readable: no torn segment
        by_file: dict = {}
        for e in events:
            fk = int(e.entity_id[1:].split("_")[0])
            by_file.setdefault(fk, set()).add(e.entity_id)
        for fk in acked:
            assert len(by_file.get(fk, ())) == lines_per_file, \
                f"acked file {fk} incomplete"
        for fk, ids in by_file.items():
            if fk in acked:
                continue
            # in-flight file: a clean block prefix, and exactly the
            # FIRST lines (publish order = file order)
            assert len(ids) % lines_per_block == 0, \
                (fk, len(ids), "torn block")
            assert ids == {f"F{fk}_{j}" for j in range(len(ids))}
        s.events().close()
        next_file = max(acked, default=-1) + 1
        if next_file >= n_files:
            break
