"""Engine server + batch predict tests: train → deploy → HTTP queries →
feedback/reload/stop, and the JSON-lines batch-predict flow."""

import json
import urllib.error
import urllib.request
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import Context
from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.server.engineserver import ServerConfig, deploy
from predictionio_tpu.templates.recommendation import (
    default_engine_params,
    recommendation_engine,
)
from predictionio_tpu.workflow import run_train
from predictionio_tpu.workflow.batch_predict import run_batch_predict

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(scope="module")
def trained_ctx():
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    app_id = storage.apps().insert(App(0, "srvapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(7)
    events = []
    t = T0
    for u in range(20):
        items = rng.choice(20, size=6, replace=False)
        for i in items:
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
                event_time=t))
            t += timedelta(seconds=30)
    es.insert_batch(events, app_id)
    ctx = Context(app_name="srvapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("srvapp", rank=4, num_iterations=4, seed=3)
    run_train(ctx, engine, ep, engine_id="srv", engine_version="1")
    return ctx, engine, ep


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return resp.status, (json.loads(raw) if "json" in ctype
                                 else raw.decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


@pytest.fixture()
def served(trained_ctx):
    ctx, engine, ep = trained_ctx
    srv = deploy(ctx, engine, ep, engine_id="srv", engine_version="1",
                 config=ServerConfig(feedback=True, feedback_app_name="srvapp"),
                 host="127.0.0.1", port=0)
    srv.start_background()
    yield ctx, srv
    srv.shutdown()


class TestEngineServer:
    def test_queries(self, served):
        ctx, srv = served
        status, body = call(srv.port, "POST", "/queries.json",
                            {"user": "u1", "num": 5})
        assert status == 200
        assert len(body["itemScores"]) == 5
        scores = [s["score"] for s in body["itemScores"]]
        assert scores == sorted(scores, reverse=True)

    def test_bad_query_400(self, served):
        ctx, srv = served
        status, _ = call(srv.port, "POST", "/queries.json",
                         {"nonsense": True})
        assert status == 400

    def test_status_page_and_json(self, served):
        ctx, srv = served
        call(srv.port, "POST", "/queries.json", {"user": "u1", "num": 3})
        status, html = call(srv.port, "GET", "/")
        assert status == 200 and "requests served" in html
        status, body = call(srv.port, "GET", "/status.json")
        assert status == 200 and body["requestCount"] >= 1
        assert body["engineId"] == "srv"

    def test_feedback_event_written(self, served):
        ctx, srv = served
        before = len(list(ctx.event_store.find("srvapp",
                                               event_names=["predict"])))
        status, body = call(srv.port, "POST", "/queries.json",
                            {"user": "u2", "num": 2})
        assert status == 200
        assert "prId" in body  # injected by feedback loop
        predicts = list(ctx.event_store.find("srvapp",
                                             event_names=["predict"]))
        assert len(predicts) == before + 1
        ev = predicts[-1]
        assert ev.entity_type == "pio_pr"
        assert ev.properties["query"] == {"user": "u2", "num": 2}
        assert ev.properties["prediction"]["itemScores"]

    def test_reload(self, served):
        ctx, srv = served
        status, body = call(srv.port, "POST", "/reload")
        assert status == 200
        assert body["engineInstanceId"]

    def test_stop(self, trained_ctx):
        ctx, engine, ep = trained_ctx
        srv = deploy(ctx, engine, ep, engine_id="srv", engine_version="1",
                     host="127.0.0.1", port=0)
        srv.start_background()
        status, body = call(srv.port, "POST", "/stop")
        assert status == 200
        import time
        stopped = False
        for _ in range(50):
            try:
                call(srv.port, "GET", "/status.json")
                time.sleep(0.05)
            except (ConnectionError, OSError):
                stopped = True
                break
        assert stopped, "server still answering after /stop"

    def test_accesskey_guard(self, trained_ctx):
        ctx, engine, ep = trained_ctx
        srv = deploy(ctx, engine, ep, engine_id="srv", engine_version="1",
                     config=ServerConfig(accesskey="SECRET"),
                     host="127.0.0.1", port=0)
        srv.start_background()
        try:
            assert call(srv.port, "POST", "/reload")[0] == 401
            assert call(srv.port, "POST",
                        "/reload?accessKey=SECRET")[0] == 200
            # queries are not key-guarded (parity with reference default)
            assert call(srv.port, "POST", "/queries.json",
                        {"user": "u1", "num": 1})[0] == 200
        finally:
            srv.shutdown()


class TestBatchPredict:
    def test_jsonl_roundtrip(self, trained_ctx, tmp_path):
        ctx, engine, ep = trained_ctx
        inp = tmp_path / "queries.jsonl"
        out = tmp_path / "predictions.jsonl"
        queries = [{"user": f"u{i}", "num": 3} for i in range(5)]
        inp.write_text("\n".join(json.dumps(q) for q in queries) + "\n\n")
        n = run_batch_predict(ctx, engine, ep, str(inp), str(out),
                              engine_id="srv", engine_version="1")
        assert n == 5
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(lines) == 5
        for q, line in zip(queries, lines):
            assert line["query"] == q
            assert len(line["prediction"]["itemScores"]) == 3


class TestMicroBatching:
    def test_concurrent_queries_batched(self, trained_ctx):
        import threading

        ctx, engine, ep = trained_ctx
        srv = deploy(ctx, engine, ep, engine_id="srv", engine_version="1",
                     config=ServerConfig(batching=True, batch_window_ms=20,
                                         max_batch=16),
                     host="127.0.0.1", port=0)
        srv.start_background()
        try:
            # reference result without batching
            _, want = call(srv.port, "POST", "/queries.json",
                           {"user": "u1", "num": 3})

            results = [None] * 8
            def fire(i):
                _, results[i] = call(srv.port, "POST", "/queries.json",
                                     {"user": "u1", "num": 3})
            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # same ranking; scores to float32 tolerance — the batched
            # dispatch compiles a different [B, n] shape whose reduction
            # order may differ from the B=1 kernel's by an ulp
            for r in results:
                assert [s["item"] for s in r["itemScores"]] == \
                    [s["item"] for s in want["itemScores"]]
                for got, exp in zip(r["itemScores"], want["itemScores"]):
                    assert got["score"] == pytest.approx(exp["score"],
                                                         rel=1e-5)
        finally:
            srv.shutdown()

    def test_bad_query_isolated_in_batch(self, trained_ctx):
        ctx, engine, ep = trained_ctx
        srv = deploy(ctx, engine, ep, engine_id="srv", engine_version="1",
                     config=ServerConfig(batching=True, batch_window_ms=5),
                     host="127.0.0.1", port=0)
        srv.start_background()
        try:
            status, body = call(srv.port, "POST", "/queries.json",
                                {"bogus": 1})
            assert status == 400
            status, body = call(srv.port, "POST", "/queries.json",
                                {"user": "u1", "num": 2})
            assert status == 200 and len(body["itemScores"]) == 2
        finally:
            srv.shutdown()


class TestShardingFindingsGauge:
    def test_census_recorded_and_on_status(self, trained_ctx):
        """The pio_sharding_findings info gauge (ISSUE 14): the server
        records the per-rule count of pragma-suppressed sharding
        findings baked into the deployed build, and /status.json
        carries the same census as `shardingFindings`."""
        from predictionio_tpu.analysis import count_sharding_pragmas
        from predictionio_tpu.server.engineserver import (
            QueryServer,
            build_app,
        )
        from predictionio_tpu.workflow import (
            get_latest_completed,
            load_models_for_deploy,
        )

        ctx, engine, ep = trained_ctx
        inst = get_latest_completed(ctx, engine_id="srv")
        models = load_models_for_deploy(ctx, engine, inst, ep)
        server = QueryServer(ctx, engine, ep, models, inst)
        expect = count_sharding_pragmas()
        sf = server.sharding_findings_status()
        assert sf["byRule"] == dict(sorted(expect.items()))
        assert sf["suppressed"] == sum(expect.values())
        rendered = server.metrics.render()
        for rule, n in expect.items():
            assert (f'pio_sharding_findings{{rule="{rule}"}} {n}'
                    in rendered)
        app = build_app(server)
        route = next(h for m, _, _, h in app._routes
                     if getattr(h, "__name__", "") == "status")
        doc = route(None).body
        assert doc["shardingFindings"] == sf


class TestGramModeGauge:
    def test_bind_records_resolved_gram_mode(self, trained_ctx):
        """The pio_gram_mode info gauge (ISSUE 7): binding an ALS
        engine sets 1 on the resolved realization's label; a rebind
        zeroes the stale label."""
        from predictionio_tpu.models.als import resolved_gram_mode
        from predictionio_tpu.server.engineserver import QueryServer
        from predictionio_tpu.workflow import (
            get_latest_completed,
            load_models_for_deploy,
        )

        ctx, engine, ep = trained_ctx
        inst = get_latest_completed(ctx, engine_id="srv")
        models = load_models_for_deploy(ctx, engine, inst, ep)
        server = QueryServer(ctx, engine, ep, models, inst)
        expect = resolved_gram_mode(server.algorithms[0].params)
        children = dict(server._gram_mode_gauge.children())
        active = {labels: child.value
                  for labels, child in children.items()}
        assert active[(("mode", expect),)] == 1.0
        assert f'pio_gram_mode{{mode="{expect}"}} 1' \
            in server.metrics.render()
    def test_serve_error_isolated_in_mixed_batch(self, trained_ctx):
        """A serve-time exception for one query must not poison its
        batch-mates (exercises query_batch directly with a genuinely
        mixed batch)."""
        from predictionio_tpu.server.engineserver import (
            HTTPError,
            QueryServer,
        )
        from predictionio_tpu.workflow import (
            get_latest_completed,
            load_models_for_deploy,
        )

        ctx, engine, ep = trained_ctx
        inst = get_latest_completed(ctx, engine_id="srv")
        models = load_models_for_deploy(ctx, engine, inst, ep)
        server = QueryServer(ctx, engine, ep, models, inst)

        class PoisonServing:
            def __init__(self, inner):
                self.inner = inner

            def supplement(self, q):
                return self.inner.supplement(q)

            def serve(self, q, ps):
                if q.user == "u3":
                    raise RuntimeError("poison")
                return self.inner.serve(q, ps)

        server.serving = PoisonServing(server.serving)
        out = server.query_batch([
            {"user": "u1", "num": 2},
            {"user": "u3", "num": 2},   # serve raises
            {"bogus": 1},               # parse error
            {"user": "u5", "num": 2},
        ])
        assert len(out[0]["itemScores"]) == 2
        assert isinstance(out[1], HTTPError) and out[1].status == 500
        assert isinstance(out[2], HTTPError) and out[2].status == 400
        assert len(out[3]["itemScores"]) == 2


class TestRemoteLog:
    def test_remote_log_ships_and_swallows(self, trained_ctx):
        """remote_log POSTs {engineInstance, message} with the prefix
        (CreateServer.scala remoteLog :435-446) and swallows collector
        outages; 400s do not remote-log over HTTP."""
        import json as _json

        from predictionio_tpu.server.engineserver import QueryServer
        from predictionio_tpu.server.http import (
            AppServer,
            HTTPApp,
            Request,
            json_response,
        )
        from predictionio_tpu.workflow import (
            get_latest_completed,
            load_models_for_deploy,
        )

        received = []
        collector_app = HTTPApp("collector")

        @collector_app.route("POST", "/log")
        def log_sink(req: Request):
            received.append(req.body.decode())
            return json_response({"ok": True})

        collector = AppServer(collector_app, "127.0.0.1", 0)
        collector.start_background()
        try:
            ctx, engine, ep = trained_ctx
            cfg = ServerConfig(
                log_url=f"http://127.0.0.1:{collector.port}/log",
                log_prefix="PIO: ")

            # client errors do not remote-log
            srv = deploy(ctx, engine, ep, engine_id="srv",
                         engine_version="1", config=cfg,
                         host="127.0.0.1", port=0)
            srv.start_background()
            try:
                status, _ = call(srv.port, "POST", "/queries.json",
                                 {"bogus": 1})
                assert status == 400
                assert not received
            finally:
                srv.shutdown()

            inst = get_latest_completed(ctx, engine_id="srv")
            models = load_models_for_deploy(ctx, engine, inst, ep)
            qs = QueryServer(ctx, engine, ep, models, inst, cfg)
            qs.remote_log("boom", wait=True)
            assert received and received[-1].startswith("PIO: ")
            body = _json.loads(received[-1][len("PIO: "):])
            assert body["message"] == "boom"
            assert body["engineInstance"] == inst.id
        finally:
            collector.shutdown()
        qs.remote_log("after-shutdown", wait=True)  # down: must not raise


class TestPluginREST:
    def test_plugin_rest_route(self, trained_ctx):
        """/plugins/<type>/<name>/<args…> dispatches to handle_rest
        (CreateServer.scala:684-689)."""
        from predictionio_tpu.server.engineserver import QueryServer
        from predictionio_tpu.server.plugins import (
            EngineServerPlugin,
            EngineServerPlugins,
        )
        from predictionio_tpu.workflow import (
            get_latest_completed,
            load_models_for_deploy,
        )
        from predictionio_tpu.server.engineserver import (
            create_engine_server,
        )

        class EchoPlugin(EngineServerPlugin):
            plugin_name = "echo"
            plugin_description = "echoes its REST args"

            def process(self, query, prediction):
                return prediction

            def handle_rest(self, args):
                return {"args": args}

        ctx, engine, ep = trained_ctx
        inst = get_latest_completed(ctx, engine_id="srv")
        models = load_models_for_deploy(ctx, engine, inst, ep)
        plugins = EngineServerPlugins()
        plugins.register(EchoPlugin(), blocker=True)
        qs = QueryServer(ctx, engine, ep, models, inst, plugins=plugins)
        srv = create_engine_server(qs, "127.0.0.1", 0).start_background()
        try:
            status, body = call(srv.port, "GET",
                                "/plugins/outputblockers/echo/a/b")
            assert status == 200 and body == {"args": ["a", "b"]}
            status, body = call(srv.port, "GET", "/plugins.json")
            assert "echo" in body["plugins"]["outputblockers"]
            assert call(srv.port, "GET",
                        "/plugins/outputblockers/nope")[0] == 404
            assert call(srv.port, "GET",
                        "/plugins/badtype/echo")[0] == 404
        finally:
            srv.shutdown()


class TestServingWarmup:
    def test_warm_serving_flag_and_hook(self, trained_ctx):
        """ServerConfig.warm_start pre-compiles the serving shapes via
        the algorithm's warm_serving hook and flips /status.json's
        servingWarm (round-4: each cold batch shape cost a 6-20s XLA
        compile through the device tunnel DURING serving)."""
        from predictionio_tpu.server.engineserver import (
            QueryServer,
            ServerConfig,
        )
        from predictionio_tpu.workflow.core import (
            get_latest_completed,
            load_models_for_deploy,
        )

        ctx, engine, ep = trained_ctx
        inst = get_latest_completed(ctx, engine_id="srv")
        models = load_models_for_deploy(ctx, engine, inst, ep)

        # the hook exists on the shipped template and runs clean
        assert hasattr(engine.make_algorithms(ep)[0], "warm_serving")

        qs = QueryServer(ctx, engine, ep, models, inst,
                         ServerConfig(batching=True, max_batch=8))
        assert qs.warm_done.wait(timeout=60)

        # warm_start=False: no thread, immediately "warm"
        qs2 = QueryServer(ctx, engine, ep, models, inst,
                          ServerConfig(warm_start=False))
        assert qs2.warm_done.is_set()


def _make_server(models, cfg):
    """Minimal real QueryServer over a synthetic COMPLETED instance."""
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
    )
    from predictionio_tpu.server.engineserver import QueryServer
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "resid"))
    ctx = Context(app_name="resid", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="r", status=STATUS_COMPLETED, start_time=now, end_time=now,
        engine_id="r", engine_version="1", engine_variant="e.json",
        engine_factory="f")
    return QueryServer(ctx, recommendation_engine(),
                       default_engine_params("resid", rank=8), models,
                       inst, cfg)


def test_bind_makes_large_model_device_resident(monkeypatch):
    """A re-materialized (numpy) model past HOST_SERVE_WORK must move
    to the device ONCE at bind — through the REAL QueryServer._bind ->
    prepare_serving_model wiring, not just the helper. Budget is
    monkeypatched tiny so the test model stays a few KB."""
    import numpy as np

    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models import als as als_mod
    from predictionio_tpu.models.als import ALSModel, ALSParams

    monkeypatch.setattr(als_mod, "HOST_SERVE_WORK", 1024)

    rank = 8
    def mk(n_items):
        return ALSModel(
            user_factors=np.zeros((4, rank), np.float32),
            item_factors=np.zeros((n_items, rank), np.float32),
            n_users=4, n_items=n_items,
            user_ids=BiMap({f"u{i}": i for i in range(4)}),
            item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
            params=ALSParams(rank=rank))

    big = mk(1024 // rank + 8)     # past the (patched) batch-1 budget
    qs = _make_server([big], ServerConfig(warm_start=False))
    assert not isinstance(qs.models[0].item_factors, np.ndarray)

    small = mk(8)                  # host fast path stays host-resident
    qs2 = _make_server([small], ServerConfig(warm_start=False))
    assert isinstance(qs2.models[0].item_factors, np.ndarray)

    # batched binds use the BATCHED budget: the same small model past
    # max_batch * size must go to the device
    qs3 = _make_server([small], ServerConfig(warm_start=False,
                                             batching=True,
                                             max_batch=64))
    assert not isinstance(qs3.models[0].item_factors, np.ndarray)
