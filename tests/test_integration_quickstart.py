"""Quickstart integration test — the reference's
``tests/pio_tests/scenarios/quickstart_test.py:50`` flow driven through
REAL subprocesses and HTTP: app new → event ingestion via the Event
Server REST API → train → deploy → live queries → undeploy.

Where the reference needed dockerized HBase/ES/postgres, the default
SQLite backend under a temp PIO_HOME covers durability across the CLI
process boundaries.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def cli_env(pio_home: Path) -> dict:
    env = dict(os.environ)
    env.update({
        "PIO_HOME": str(pio_home),
        "PYTHONPATH": str(REPO),
        "JAX_PLATFORMS": "cpu",
    })
    # a TPU plugin may override JAX_PLATFORMS; tests must not grab the chip
    env.pop("PJRT_DEVICE", None)
    return env


def run_cli(pio_home: Path, *args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.cli", *args],
        env=cli_env(pio_home), capture_output=True, text=True,
        timeout=timeout, cwd=str(REPO))


def http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type":
                                          "application/json"})
    def parse(raw):
        try:
            return json.loads(raw or b"null")
        except json.JSONDecodeError:
            return raw.decode(errors="replace")  # e.g. HTML status pages

    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, parse(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, parse(e.read())


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_port(port, timeout=30, any_status=False):
    """Poll until the port answers HTTP — with 200 on GET / by default,
    or ANY status with ``any_status`` (servers without a root route)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            status, _ = http("GET", f"http://127.0.0.1:{port}/")
            if any_status or status == 200:
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"nothing listening on {port}")


@pytest.mark.integration
def test_quickstart_end_to_end(tmp_path):
    pio_home = tmp_path / "pio_home"
    pio_home.mkdir()

    # -- app new (CLI process #1) -----------------------------------------
    out = run_cli(pio_home, "app", "new", "qsapp")
    assert out.returncode == 0, out.stderr
    access_key = next(l.split(":", 1)[1].strip()
                      for l in out.stdout.splitlines()
                      if l.startswith("Access Key:"))

    # -- event server (long-lived process) + REST ingestion ----------------
    es_port = free_port()
    es = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli", "eventserver",
         "--ip", "127.0.0.1", "--port", str(es_port)],
        env=cli_env(pio_home), cwd=str(REPO),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        wait_port(es_port)
        rng = np.random.default_rng(6)
        base = f"http://127.0.0.1:{es_port}"
        # single-event endpoint
        for u in range(16):
            pool = range(0, 8) if u % 2 == 0 else range(8, 16)
            for i in rng.choice(list(pool), size=4, replace=False):
                status, body = http(
                    "POST", f"{base}/events.json?accessKey={access_key}",
                    {"event": "rate", "entityType": "user",
                     "entityId": f"u{u}", "targetEntityType": "item",
                     "targetEntityId": f"i{i}",
                     "properties": {"rating": 5.0}})
                assert status == 201, body
        # batch endpoint (≤50 semantics)
        batch = [{"event": "buy", "entityType": "user",
                  "entityId": f"u{u}", "targetEntityType": "item",
                  "targetEntityId": "i1"} for u in range(4)]
        status, body = http(
            "POST", f"{base}/batch/events.json?accessKey={access_key}",
            batch)
        assert status == 200 and len(body) == 4
    finally:
        es.terminate()
        es.wait(timeout=10)

    # -- build + train (CLI processes) -------------------------------------
    variant = {
        "id": "qs", "version": "1",
        "engineFactory": "predictionio_tpu.templates.recommendation:"
                         "recommendation_engine",
        "datasource": {"params": {"app_name": "qsapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 8, "num_iterations": 5,
                                   "seed": 2}}],
    }
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps(variant))
    assert run_cli(pio_home, "build", "--engine-json",
                   str(ej)).returncode == 0
    out = run_cli(pio_home, "train", "--engine-json", str(ej))
    assert out.returncode == 0, out.stderr
    assert "Training completed" in out.stdout

    # -- deploy (long-lived process) + live queries -------------------------
    q_port = free_port()
    srv = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli", "deploy",
         "--engine-json", str(ej), "--ip", "127.0.0.1",
         "--port", str(q_port)],
        env=cli_env(pio_home), cwd=str(REPO),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        wait_port(q_port, timeout=90)  # model load + first compile
        status, body = http("POST",
                            f"http://127.0.0.1:{q_port}/queries.json",
                            {"user": "u0", "num": 4})
        assert status == 200 and len(body["itemScores"]) == 4
        scores = [s["score"] for s in body["itemScores"]]
        assert scores == sorted(scores, reverse=True)
        status, _ = http("POST",
                         f"http://127.0.0.1:{q_port}/queries.json",
                         {"bogus": 1})
        assert status == 400

        # -- undeploy via CLI ----------------------------------------------
        out = run_cli(pio_home, "undeploy", "--ip", "127.0.0.1",
                      "--port", str(q_port))
        assert out.returncode == 0, out.stderr
        deadline = time.monotonic() + 15
        stopped = False
        while time.monotonic() < deadline:
            try:
                http("GET", f"http://127.0.0.1:{q_port}/status.json",
                     timeout=2)
                time.sleep(0.3)
            except OSError:
                stopped = True
                break
        assert stopped, "engine server still up after undeploy"
    finally:
        srv.terminate()
        srv.wait(timeout=10)


def remote_env(pio_home: Path, storage_port: int) -> dict:
    env = cli_env(pio_home)
    env.update({
        "PIO_STORAGE_SOURCES_NET_TYPE": "remote",
        "PIO_STORAGE_SOURCES_NET_URL": f"http://127.0.0.1:{storage_port}",
        "PIO_STORAGE_SOURCES_NET_SECRET": "qs-secret",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NET",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "NET",
    })
    return env


@pytest.mark.integration
def test_quickstart_over_remote_storage(tmp_path):
    """The pod topology end-to-end in real processes: ONE storage server
    owns the store; the CLI, event server, trainer, and engine server
    all reach it over HTTP (no shared PIO_HOME state between them)."""
    storage_home = tmp_path / "storage_home"
    storage_home.mkdir()
    client_home = tmp_path / "client_home"  # deliberately EMPTY
    client_home.mkdir()

    st_port = free_port()
    st = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli", "storageserver",
         "--ip", "127.0.0.1", "--port", str(st_port),
         "--secret", "qs-secret"],
        env=cli_env(storage_home), cwd=str(REPO),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    env = remote_env(client_home, st_port)

    def run(*args, timeout=240):
        return subprocess.run(
            [sys.executable, "-m", "predictionio_tpu.cli", *args],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=str(REPO))

    es = srv = None
    try:
        wait_port(st_port, any_status=True)
        out = run("app", "new", "netqs")
        assert out.returncode == 0, out.stderr
        access_key = next(l.split(":", 1)[1].strip()
                          for l in out.stdout.splitlines()
                          if l.startswith("Access Key:"))
        assert run("status").returncode == 0

        es_port = free_port()
        es = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli",
             "eventserver", "--ip", "127.0.0.1", "--port", str(es_port)],
            env=env, cwd=str(REPO), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        wait_port(es_port)
        rng = np.random.default_rng(9)
        batch = [{"event": "rate", "entityType": "user",
                  "entityId": f"u{int(u)}", "targetEntityType": "item",
                  "targetEntityId": f"i{int(i)}",
                  "properties": {"rating": float(r)}}
                 for u, i, r in zip(rng.integers(0, 12, 48),
                                    rng.integers(0, 10, 48),
                                    rng.integers(1, 6, 48))]
        status, body = http(
            "POST",
            f"http://127.0.0.1:{es_port}/batch/events.json"
            f"?accessKey={access_key}", batch)
        assert status == 200 and all(r["status"] == 201 for r in body)

        variant = {
            "id": "netqs", "version": "1",
            "engineFactory": "predictionio_tpu.templates."
                             "recommendation:recommendation_engine",
            "datasource": {"params": {"app_name": "netqs"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 4, "num_iterations": 3,
                                       "seed": 2}}],
        }
        ej = tmp_path / "engine.json"
        ej.write_text(json.dumps(variant))
        out = run("train", "--engine-json", str(ej))
        assert out.returncode == 0, out.stderr + out.stdout

        q_port = free_port()
        srv = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli", "deploy",
             "--engine-json", str(ej), "--ip", "127.0.0.1",
             "--port", str(q_port)],
            env=env, cwd=str(REPO), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        wait_port(q_port, timeout=90)
        status, body = http(
            "POST", f"http://127.0.0.1:{q_port}/queries.json",
            {"user": "u0", "num": 3})
        assert status == 200 and body["itemScores"], body
    finally:
        for p in (es, srv, st):
            if p is not None:
                p.terminate()
                p.wait(timeout=10)
