"""Unified telemetry tests (ISSUE 2): histogram math, Prometheus
exposition validity, per-phase spans through a real in-process engine
server (batched and unbatched), transfer-guard counter wiring, and
memory-boundedness of the span registry under 100k records."""

import json
import logging
import re
import sys
import threading
import urllib.error
import urllib.request
from datetime import datetime, timezone

import numpy as np
import pytest

from predictionio_tpu.obs import (
    DEFAULT_LATENCY_BOUNDS,
    MetricsRegistry,
    StreamingHistogram,
    TransferGuardCounter,
    exponential_bounds,
    linear_bounds,
)
from predictionio_tpu.utils.tracing import SpanRegistry, timed


# ---------------------------------------------------------------------------
# histogram bucket / percentile math
# ---------------------------------------------------------------------------

class TestStreamingHistogram:
    def test_bounds_validation(self):
        with pytest.raises(ValueError):
            StreamingHistogram([])
        with pytest.raises(ValueError):
            StreamingHistogram([1.0, 1.0])
        with pytest.raises(ValueError):
            StreamingHistogram([2.0, 1.0])
        with pytest.raises(ValueError):
            exponential_bounds(0, 2, 3)
        with pytest.raises(ValueError):
            linear_bounds(0, -1, 3)

    def test_bucket_assignment_le_semantics(self):
        h = StreamingHistogram([1.0, 2.0, 4.0])
        for v in (0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 100.0):
            h.record(v)
        # cumulative: le=1 → {0.5, 1.0}; le=2 → +{1.5, 2.0};
        # le=4 → +{3.0, 4.0}; +Inf → +{100.0}
        assert h.bucket_counts() == [
            (1.0, 2), (2.0, 4), (4.0, 6), (float("inf"), 7)]
        assert h.count == 7
        assert h.max == 100.0
        assert h.min == 0.5
        assert h.sum == pytest.approx(112.0)

    def test_percentiles_uniform_distribution(self):
        # 1..1000 into fine linear buckets: interpolation error is
        # bounded by one bucket width (10)
        h = StreamingHistogram(linear_bounds(10.0, 10.0, 100))
        for v in range(1, 1001):
            h.record(float(v))
        assert h.quantile(0.5) == pytest.approx(500, abs=10)
        assert h.quantile(0.9) == pytest.approx(900, abs=10)
        assert h.quantile(0.99) == pytest.approx(990, abs=10)
        assert h.quantile(1.0) == pytest.approx(1000, abs=10)

    def test_percentiles_skewed_distribution(self):
        # 99 fast + 1 slow: p50 stays in the fast bucket, p99+ sees the
        # tail — the exact signal raw-mean bookkeeping hides
        h = StreamingHistogram(exponential_bounds(0.001, 2.0, 20))
        for _ in range(99):
            h.record(0.002)
        h.record(10.0)
        assert h.quantile(0.5) < 0.01
        # p99 of 99 fast + 1 slow is still fast — the tail shows at
        # p99.9 and max (exactly why max is part of the snapshot)
        assert h.quantile(0.999) > 1.0
        s = h.snapshot()
        assert s["count"] == 100
        assert s["p99"] >= s["p50"]
        assert s["max"] == 10.0

    def test_quantile_clamped_to_observed_range(self):
        h = StreamingHistogram([1.0, 100.0])
        h.record(5.0)
        h.record(6.0)
        for q in (0.0, 0.5, 1.0):
            assert 5.0 <= h.quantile(q) <= 6.0

    def test_empty_histogram(self):
        h = StreamingHistogram()
        assert h.quantile(0.5) is None
        assert h.snapshot() == {"count": 0}
        assert h.count == 0 and h.max == 0.0

    def test_o1_memory_under_100k_records(self):
        h = StreamingHistogram(DEFAULT_LATENCY_BOUNDS)
        baseline_cells = len(h._counts)
        baseline_size = sys.getsizeof(h._counts)
        rng = np.random.default_rng(0)
        for v in rng.lognormal(-5, 2, size=100_000):
            h.record(float(v))
        assert h.count == 100_000
        # the whole state is still the same fixed bucket array
        assert len(h._counts) == baseline_cells
        assert sys.getsizeof(h._counts) == baseline_size
        assert h.quantile(0.99) is not None

    def test_thread_safety_no_lost_updates(self):
        h = StreamingHistogram([1.0])
        n, threads = 10_000, 8

        def hammer():
            for _ in range(n):
                h.record(0.5)

        ts = [threading.Thread(target=hammer) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == n * threads
        assert h.bucket_counts()[0][1] == n * threads


# ---------------------------------------------------------------------------
# span registry: bounded memory + backward-compatible summary
# ---------------------------------------------------------------------------

class TestSpanRegistry:
    def test_summary_keys_backward_compatible_plus_percentiles(self):
        reg = SpanRegistry()
        with timed("op", registry=reg):
            pass
        reg.record("op", 0.5)
        s = reg.summary()["op"]
        for key in ("count", "total_sec", "mean_sec", "max_sec",
                    "p50", "p90", "p99"):
            assert key in s
        assert s["count"] == 2
        assert s["max_sec"] == pytest.approx(0.5, abs=0.01)

    def test_memory_bounded_under_100k_records(self):
        reg = SpanRegistry()
        for i in range(100_000):
            reg.record("hot", 0.001 * (i % 100))
        hist = reg.histograms()["hot"]
        # bounded: fixed bucket array, no raw list of 100k floats
        assert len(hist._counts) == len(hist.bounds) + 1
        assert reg.summary()["hot"]["count"] == 100_000

    def test_span_name_cardinality_capped(self):
        reg = SpanRegistry()
        for i in range(SpanRegistry.MAX_SPAN_NAMES + 50):
            reg.record(f"span-{i}", 0.001)
        hists = reg.histograms()
        assert len(hists) <= SpanRegistry.MAX_SPAN_NAMES + 1
        assert SpanRegistry._OVERFLOW in hists
        assert hists[SpanRegistry._OVERFLOW].count == 50

    def test_reset(self):
        reg = SpanRegistry()
        reg.record("x", 1.0)
        reg.reset()
        assert reg.summary() == {}


# ---------------------------------------------------------------------------
# Prometheus exposition format
# ---------------------------------------------------------------------------

_METRIC_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?[0-9.eE+-]+|[+-]Inf|NaN)$')


def validate_exposition(text: str):
    """Grammar + histogram-consistency validation; returns the parsed
    (name → type) map."""
    assert text.endswith("\n")
    types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram")
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        assert _METRIC_LINE.match(line), f"bad line: {line!r}"
    return types


class TestPrometheusExposition:
    def test_render_all_kinds(self):
        reg = MetricsRegistry()
        reg.counter("t_requests_total", "requests").labels(
            method="GET", status="200").inc(3)
        reg.gauge("t_temperature", "a gauge").set(36.6)
        h = reg.histogram("t_latency_seconds", "latency",
                          bounds=[0.1, 1.0])
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = reg.render()
        types = validate_exposition(text)
        assert types["t_requests_total"] == "counter"
        assert types["t_temperature"] == "gauge"
        assert types["t_latency_seconds"] == "histogram"
        assert 't_requests_total{method="GET",status="200"} 3' in text
        assert 't_latency_seconds_bucket{le="0.1"} 1' in text
        assert 't_latency_seconds_bucket{le="1"} 2' in text
        assert 't_latency_seconds_bucket{le="+Inf"} 3' in text
        assert "t_latency_seconds_count 3" in text
        assert "t_latency_seconds_sum 5.55" in text

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("t_esc_total", "escaping").labels(
            path='we"ird\\path\nline').inc()
        text = reg.render()
        validate_exposition(text)
        assert r'path="we\"ird\\path\nline"' in text

    def test_help_escaping_and_type_lines(self):
        reg = MetricsRegistry()
        reg.gauge("t_g", "multi\nline \\ help").set(1)
        text = reg.render()
        assert "# HELP t_g multi\\nline \\\\ help" in text
        assert "# TYPE t_g gauge" in text

    def test_invalid_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("bad-name", "nope")
        with pytest.raises(ValueError):
            reg.counter("t_ok_total", "ok").labels(**{"0bad": "v"})

    def test_kind_conflict_rejected(self):
        reg = MetricsRegistry()
        reg.counter("t_same", "x")
        with pytest.raises(ValueError):
            reg.gauge("t_same", "x")

    def test_counter_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("t_c_total", "x")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_fn_failure_reads_zero(self):
        reg = MetricsRegistry()
        reg.gauge("t_broken", "x", fn=lambda: 1 / 0)
        assert "t_broken 0" in reg.render()

    def test_snapshot_shapes(self):
        reg = MetricsRegistry()
        reg.counter("t_plain_total", "x").inc(2)
        reg.histogram("t_h_seconds", "x",
                      bounds=[1.0]).labels(phase="a").observe(0.5)
        snap = reg.snapshot()
        assert snap["t_plain_total"] == 2
        assert snap["t_h_seconds"]["phase=a"]["count"] == 1
        assert "p99" in snap["t_h_seconds"]["phase=a"]

    def test_collector_errors_isolated(self):
        reg = MetricsRegistry()

        def boom():
            raise RuntimeError("collector down")

        reg.register_collector(boom)
        reg.gauge("t_alive", "x").set(1)
        assert "t_alive 1" in reg.render()


# ---------------------------------------------------------------------------
# transfer-guard counter wiring
# ---------------------------------------------------------------------------

class TestTransferGuardCounter:
    def test_counts_guard_log_records(self):
        TransferGuardCounter.install()
        before = TransferGuardCounter.total()
        logging.getLogger("jax").warning(
            "Disallowed host-to-device transfer: aval=ShapedArray(...)")
        assert TransferGuardCounter.total() == before + 1
        # unrelated records do not count
        logging.getLogger("jax").warning("compiling module jit_step")
        assert TransferGuardCounter.total() == before + 1

    def test_direct_count_and_registry_gauge(self):
        from predictionio_tpu.obs import register_runtime_metrics

        reg = MetricsRegistry()
        register_runtime_metrics(reg, server="test")
        before = TransferGuardCounter.total()
        TransferGuardCounter.count(2)
        assert TransferGuardCounter.total() == before + 2
        text = reg.render()
        m = re.search(
            r"^pio_transfer_guard_violations_total (\d+)$", text,
            re.MULTILINE)
        assert m and int(m.group(1)) == TransferGuardCounter.total()

    def test_install_idempotent(self):
        h1 = TransferGuardCounter.install()
        h2 = TransferGuardCounter.install()
        assert h1 is h2
        root_handlers = [h for h in logging.getLogger().handlers
                         if isinstance(h, TransferGuardCounter)]
        assert len(root_handlers) == 1


# ---------------------------------------------------------------------------
# per-phase spans through a REAL in-process engine server
# ---------------------------------------------------------------------------

def _deploy_synthetic(batching: bool):
    from predictionio_tpu.controller import Context
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.data.storage import App, Storage
    from predictionio_tpu.data.storage.base import (
        STATUS_COMPLETED,
        EngineInstance,
    )
    from predictionio_tpu.models.als import ALSModel, ALSParams
    from predictionio_tpu.server.engineserver import (
        QueryServer,
        ServerConfig,
        create_engine_server,
    )
    from predictionio_tpu.templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )

    rank, n_users, n_items = 4, 16, 32
    rng = np.random.default_rng(0)
    model = ALSModel(
        user_factors=rng.standard_normal((n_users, rank)).astype(
            np.float32),
        item_factors=rng.standard_normal((n_items, rank)).astype(
            np.float32),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))
    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "obsapp"))
    ctx = Context(app_name="obsapp", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="obs", status=STATUS_COMPLETED, start_time=now, end_time=now,
        engine_id="obs", engine_version="1", engine_variant="e.json",
        engine_factory="synthetic")
    qs = QueryServer(ctx, recommendation_engine(),
                     default_engine_params("obsapp", rank=rank),
                     [model], inst,
                     ServerConfig(warm_start=False, batching=batching,
                                  max_batch=8, batch_window_ms=5.0))
    srv = create_engine_server(qs, host="127.0.0.1", port=0)
    srv.start_background()
    return qs, srv


def _call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return (resp.status,
                    json.loads(raw) if "json" in ctype else raw.decode(),
                    dict(resp.headers))
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), dict(e.headers)


class TestEngineServerPhases:
    def test_unbatched_phases_recorded(self):
        qs, srv = _deploy_synthetic(batching=False)
        try:
            for i in range(5):
                status, body, headers = _call(
                    srv.port, "POST", "/queries.json",
                    {"user": f"u{i}", "num": 3})
                assert status == 200
                assert headers.get("X-Request-ID")
            status, st, _ = _call(srv.port, "GET", "/status.json")
            assert status == 200
            phases = st["phases"]
            for phase in ("phase=assemble", "phase=supplement",
                          "phase=dispatch", "phase=serve",
                          "phase=readback"):
                assert phases[phase]["count"] >= 5, phases.keys()
                assert phases[phase]["p99"] is not None
            assert st["latency"]["count"] >= 5
            assert st["transferGuardViolations"] >= 0
            assert isinstance(st["hbm"], list)  # empty on CPU: graceful
            status, text, _ = _call(srv.port, "GET", "/metrics")
            assert status == 200
            validate_exposition(text)
            assert 'pio_query_phase_seconds_bucket{phase="dispatch"' \
                in text
            assert "pio_query_latency_seconds_count 5" in text
            assert "pio_compiles_since_warm" in text
            # the global timed(name) span registry bridges into the
            # same exposition once a span exists
            with timed("obs-bridge-span"):
                pass
            status, text, _ = _call(srv.port, "GET", "/metrics")
            assert 'pio_span_seconds_bucket{span="obs-bridge-span"' \
                in text
        finally:
            srv.shutdown()

    def test_batched_phases_queue_and_occupancy(self):
        qs, srv = _deploy_synthetic(batching=True)
        try:
            results = [None] * 8

            def fire(i):
                results[i] = _call(srv.port, "POST", "/queries.json",
                                   {"user": f"u{i}", "num": 3})

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(r[0] == 200 for r in results)
            status, st, _ = _call(srv.port, "GET", "/status.json")
            assert st["phases"]["phase=queue_wait"]["count"] >= 8
            assert st["batchOccupancy"]["count"] >= 1
            assert st["queueDepth"]["count"] >= 1
            # 8 concurrent queries over max_batch=8: every query was
            # coalesced, so total occupancy-weighted count is 8
            status, text, _ = _call(srv.port, "GET", "/metrics")
            validate_exposition(text)
            assert "pio_batch_occupancy_count" in text
            assert "pio_queue_depth_count" in text
            assert 'pio_query_phase_seconds_bucket{phase="queue_wait"' \
                in text
        finally:
            srv.shutdown()

    def test_direct_query_records_without_http(self):
        qs, srv = _deploy_synthetic(batching=False)
        try:
            obs = {}
            qs.query({"user": "u1", "num": 2}, obs=obs)
            assert "dispatchMs" in obs and "serveMs" in obs
            assert qs.spans_summary()["query (end-to-end)"]["count"] == 1
        finally:
            srv.shutdown()

    def test_query_errors_counted(self):
        qs, srv = _deploy_synthetic(batching=False)
        try:
            status, _, _ = _call(srv.port, "POST", "/queries.json",
                                 {"bogus": 1})
            assert status == 400
            snap = qs.metrics.snapshot()
            assert snap["pio_query_errors_total"]["status=400"] == 1
        finally:
            srv.shutdown()

    def test_access_log_line_carries_request_id_and_phases(self):
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        access = logging.getLogger("predictionio_tpu.access")
        handler = Capture()
        old_level = access.level
        access.addHandler(handler)
        access.setLevel(logging.INFO)
        qs, srv = _deploy_synthetic(batching=False)
        try:
            status, _, headers = _call(srv.port, "POST", "/queries.json",
                                       {"user": "u1", "num": 2})
            assert status == 200
            lines = [json.loads(r) for r in records]
            mine = [ln for ln in lines
                    if ln.get("path") == "/queries.json"]
            assert mine, "no access-log line for the query"
            line = mine[-1]
            assert line["requestId"] == headers["X-Request-ID"]
            assert line["status"] == 200
            assert "dispatchMs" in line and "durationMs" in line
        finally:
            srv.shutdown()
            access.removeHandler(handler)
            access.setLevel(old_level)


# ---------------------------------------------------------------------------
# process self-telemetry + scrape self-cost (ISSUE 17 satellites)
# ---------------------------------------------------------------------------

class TestProcessMetrics:
    def test_process_stats_sane(self):
        from predictionio_tpu.obs import process_stats

        st = process_stats()
        if not st:
            pytest.skip("/proc not readable on this platform")
        assert st["rss_bytes"] > (1 << 20)
        assert st["cpu_seconds_total"] > 0.0
        assert st["open_fds"] >= 3
        assert st["threads"] >= 1

    def test_process_gauges_render(self):
        from predictionio_tpu.obs import (
            process_stats,
            register_process_metrics,
        )

        reg = MetricsRegistry()
        register_process_metrics(reg)
        if not process_stats():
            return  # no-op registration off Linux: nothing to assert
        text = reg.render()
        validate_exposition(text)
        for name in ("pio_process_rss_bytes",
                     "pio_process_cpu_seconds_total",
                     "pio_process_open_fds", "pio_process_threads"):
            assert re.search(rf"^{name} [0-9.e+]+$", text,
                             re.MULTILINE), name


class TestScrapeSelfCost:
    def test_10k_series_render_under_budget(self):
        # the scrape self-cost guard (ISSUE 17): a registry an order
        # of magnitude wider than the engine server's must still
        # render in a small fraction of the fleet scrape interval —
        # rendering itself must never be the serving regression
        import time as _time

        reg = MetricsRegistry()
        wide = reg.gauge("t_wide_series", "one child per shard")
        for i in range(10_000):
            wide.labels(shard=str(i)).set(float(i))
        t0 = _time.perf_counter()
        text = reg.render()
        elapsed = _time.perf_counter() - t0
        assert text.count("\n") >= 10_000
        assert elapsed < 2.0, f"10k-series render took {elapsed:.2f}s"
        t0 = _time.perf_counter()
        reg.export()
        assert _time.perf_counter() - t0 < 2.0

    def test_render_seconds_histogram_on_metrics_routes(self):
        # every /metrics(.json) render observes its own wall time, by
        # format — the self-cost series the fleet plane watches
        qs, srv = _deploy_synthetic(batching=False)
        try:
            status, text, _ = _call(srv.port, "GET", "/metrics")
            assert status == 200
            # a render observes itself AFTER snapshotting, so the
            # first JSON scrape can't contain its own timing — read
            # the second
            _call(srv.port, "GET", "/metrics.json")
            status, export, _ = _call(srv.port, "GET", "/metrics.json")
            assert status == 200
            fam = export["pio_metrics_render_seconds"]
            assert fam["kind"] == "histogram"
            by_format = {c["labels"]["format"]: c["count"]
                         for c in fam["children"]}
            assert by_format.get("text", 0) >= 1
            assert by_format.get("json", 0) >= 1
        finally:
            srv.shutdown()

    def test_metrics_json_export_matches_text_exposition(self):
        qs, srv = _deploy_synthetic(batching=False)
        try:
            _call(srv.port, "POST", "/queries.json",
                  {"user": "u1", "num": 2})
            status, export, _ = _call(srv.port, "GET", "/metrics.json")
            assert status == 200
            lat = export["pio_query_latency_seconds"]["children"][0]
            assert lat["count"] == 1
            assert lat["buckets"][-1][0] == "+Inf"
            assert lat["buckets"][-1][1] == 1
            # counters carry plain values
            total = export["pio_http_requests_total"]["children"]
            assert any(c["labels"].get("route") == "/queries.json"
                       and c["value"] >= 1 for c in total)
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# event + storage server exposition
# ---------------------------------------------------------------------------

class TestEventServerMetrics:
    @pytest.fixture()
    def served(self):
        from predictionio_tpu.data.storage import AccessKey, App, Storage
        from predictionio_tpu.server.eventserver import (
            create_event_server,
        )

        storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
        app_id = storage.apps().insert(App(0, "obsev"))
        storage.access_keys().insert(
            AccessKey(key="KEY", app_id=app_id, events=()))
        storage.events().init(app_id)
        srv = create_event_server(storage, host="127.0.0.1", port=0)
        srv.start_background()
        yield srv
        srv.shutdown()

    def test_metrics_and_status(self, served):
        ev = {"event": "rate", "entityType": "user", "entityId": "u1",
              "targetEntityType": "item", "targetEntityId": "i1",
              "properties": {"rating": 5}}
        status, body, _ = _call(served.port, "POST",
                                "/events.json?accessKey=KEY", ev)
        assert status == 201
        status, st, _ = _call(served.port, "GET", "/status.json")
        assert status == 200
        assert st["statsEnabled"] is False
        assert st["metrics"]["pio_stats_enabled"] == 0
        assert st["metrics"]["pio_events_ingested_total"][
            "route=events"] == 1
        status, text, _ = _call(served.port, "GET", "/metrics")
        assert status == 200
        validate_exposition(text)
        assert 'pio_events_ingested_total{route="events"} 1' in text
        assert "pio_stats_enabled 0" in text
        # event-ingest latency histogram (the acceptance criterion's
        # "event latency" series) exists for the /events.json route
        assert 'pio_http_request_duration_seconds_bucket' in text
        assert 'route="/events.json"' in text

    def test_stats_404_explains_flag(self, served):
        status, body, _ = _call(served.port, "GET",
                                "/stats.json?accessKey=KEY")
        assert status == 404
        assert "--stats" in body["message"]
        assert body["statsEnabled"] is False
        assert "hint" in body


class TestStorageServerMetrics:
    def test_columnar_hit_miss_counters(self, tmp_path):
        from tests.conftest import start_sqlite_backed_storage_server

        srv, backing = start_sqlite_backed_storage_server(tmp_path)
        try:
            from predictionio_tpu.data.event import Event
            from predictionio_tpu.data.storage import App

            app_id = backing.apps().insert(App(0, "obsst"))
            backing.events().init(app_id)
            backing.events().insert(
                Event(event="rate", entity_type="user", entity_id="u1",
                      target_entity_type="item", target_entity_id="i1",
                      properties={"rating": 4.0}), app_id)
            url = (f"http://127.0.0.1:{srv.port}"
                   f"/v1/events/{app_id}/columnar")
            with urllib.request.urlopen(url, timeout=30) as resp:
                etag = resp.headers["ETag"]
                assert resp.status == 200
            req = urllib.request.Request(
                url, headers={"If-None-Match": etag})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    assert resp.status == 304
            except urllib.error.HTTPError as e:
                assert e.code == 304
            status, text, _ = _call(srv.port, "GET", "/metrics")
            assert status == 200
            validate_exposition(text)
            assert 'pio_columnar_requests_total{outcome="miss"} 1' \
                in text
            assert 'pio_columnar_requests_total{outcome="hit"} 1' \
                in text
            m = re.search(r"^pio_columnar_bytes_total (\d+)$", text,
                          re.MULTILINE)
            assert m and int(m.group(1)) > 0
            status, st, _ = _call(srv.port, "GET", "/status.json")
            assert st["status"] == "alive"
        finally:
            srv.shutdown()


class TestDashboardMetrics:
    def test_dashboard_mounts_metrics_and_table(self):
        from predictionio_tpu.data.storage import Storage
        from predictionio_tpu.server.dashboard import create_dashboard

        storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
        srv = create_dashboard(storage, host="127.0.0.1", port=0)
        srv.start_background()
        try:
            status, html, _ = _call(srv.port, "GET", "/")
            assert status == 200
            # second hit: the first request is now in the registry, so
            # the index renders its percentile table
            status, html, _ = _call(srv.port, "GET", "/")
            assert "Request latency percentiles" in html
            status, text, _ = _call(srv.port, "GET", "/metrics")
            assert status == 200
            validate_exposition(text)
            assert "pio_http_request_duration_seconds_bucket" in text
        finally:
            srv.shutdown()
