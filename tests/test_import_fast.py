"""Native bulk-import lane (``EventStore.import_jsonl``).

The reference's ``pio import`` (``tools/imprt/FileToEvents.scala``)
parsed JSON lines into events on the driver; here segmentfs gets a
one-pass C++ lane (``native/_codec.cpp:import_jsonl``) and every other
backend a streaming base implementation. These tests pin the contract:
the fast lane is INVISIBLE — same stored events, same validation
errors, same durable-prefix reporting as the pure-Python path.
"""

import json
import os

import pytest

from predictionio_tpu.data import Event
from predictionio_tpu.data.storage.base import JsonlImportError
from predictionio_tpu.data.storage.memory import MemoryEventStore
from predictionio_tpu.data.storage.segmentfs import (
    SegmentFSClient,
    SegmentFSEventStore,
)
from predictionio_tpu.native import codec


def _seg_store(td):
    return SegmentFSEventStore(SegmentFSClient(str(td)))


def _lines():
    rows = [
        {"event": "rate", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 3.5},
         "eventTime": "2015-03-01T12:34:56.789Z"},
        # offset timezone -> must normalize to the same UTC instant
        {"event": "rate", "entityType": "user", "entityId": "u2",
         "targetEntityType": "item", "targetEntityId": "i2",
         "properties": {"rating": 1.0},
         "eventTime": "2015-03-01T18:00:00+05:30"},
        # $set with nested/unicode properties, tags, prId
        {"event": "$set", "entityType": "user", "entityId": "ué",
         "properties": {"città": "naïve", "n": [1, 2.5, {"k": None}]},
         "eventTime": "2015-06-01T00:00:00Z", "tags": ["a", "b"],
         "prId": "p-1"},
        # no eventTime / no properties -> defaults
        {"event": "buy", "entityType": "user", "entityId": "u3"},
        # explicit eventId is preserved
        {"event": "buy", "entityType": "user", "entityId": "u4",
         "eventId": "feedbeef" * 4,
         "eventTime": "2015-03-02T00:00:00.000Z"},
        # date-only eventTime (fromisoformat accepts it; so must we)
        {"event": "view", "entityType": "user", "entityId": "u5",
         "eventTime": "2015-07-04"},
    ]
    return "\n".join(json.dumps(r) for r in rows) + "\n\n"


def _key(e: Event):
    # event_time excluded: rows without an explicit eventTime default
    # to "now", which differs between the two import moments
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, e.properties.to_dict(), tuple(e.tags),
            e.pr_id)


@pytest.mark.skipif(codec() is None, reason="no native toolchain")
class TestNativeLane:
    def test_parity_with_python_path(self, tmp_path):
        p = tmp_path / "in.jsonl"
        p.write_text(_lines(), encoding="utf-8")
        seg = _seg_store(tmp_path / "seg")
        mem = MemoryEventStore()
        n1 = seg.import_jsonl(str(p), 1)
        n2 = mem.import_jsonl(str(p), 1)
        assert n1 == n2 == 6
        got = sorted(seg.find(1), key=lambda e: e.entity_id)
        want = sorted(mem.find(1), key=lambda e: e.entity_id)
        assert [_key(e) for e in got] == [_key(e) for e in want]
        # the same instants survive the offset normalization (only
        # rows that specified an eventTime are comparable)
        timed = {"u1", "u2", "u4", "u5", "ué"}
        for g, w in zip(got, want):
            if g.entity_id in timed:
                assert g.event_time_millis == w.event_time_millis
        # explicit eventId preserved; generated ids are 32-hex uuid4s
        by_ent = {e.entity_id: e for e in got}
        assert by_ent["u4"].event_id == "feedbeef" * 4
        assert len(by_ent["u1"].event_id) == 32
        assert by_ent["u1"].event_id != by_ent["u2"].event_id

    def test_columnar_read_after_native_import(self, tmp_path):
        p = tmp_path / "in.jsonl"
        p.write_text(_lines(), encoding="utf-8")
        seg = _seg_store(tmp_path / "seg")
        seg.import_jsonl(str(p), 1)
        batch = seg.find_columnar(1, float_props=("rating",))
        assert batch.n == 6
        ratings = sorted(x for x in batch.float_props["rating"].tolist()
                         if x == x)
        assert ratings == [1.0, 3.5]

    def test_fallback_block_matches_python_semantics(self, tmp_path):
        # tags-as-string is legal to the Python lane (tuple("ab")) but
        # outside the strict native subset -> the block must fall back
        # and store what the Python path stores
        p = tmp_path / "in.jsonl"
        p.write_text(json.dumps(
            {"event": "buy", "entityType": "u", "entityId": "x",
             "tags": "ab"}) + "\n", encoding="utf-8")
        seg = _seg_store(tmp_path / "seg")
        assert seg.import_jsonl(str(p), 1) == 1
        (e,) = list(seg.find(1))
        assert e.tags == ("a", "b")

    def test_validation_error_reports_durable_prefix(self, tmp_path,
                                                     monkeypatch):
        # two small blocks; the bad line sits in block 2 -> block 1 is
        # durable, block 2 commits nothing (all-or-nothing per block)
        rows = [json.dumps({"event": "buy", "entityType": "u",
                            "entityId": f"e{i}"}) for i in range(8)]
        rows.append(json.dumps({"event": "$bogus", "entityType": "u",
                                "entityId": "bad"}))
        text = "\n".join(rows) + "\n"
        # block size that splits after ~4 lines
        monkeypatch.setenv("PIO_IMPORT_BLOCK",
                           str(len(rows[0]) * 4 + 4))
        p = tmp_path / "in.jsonl"
        p.write_text(text, encoding="utf-8")
        seg = _seg_store(tmp_path / "seg")
        with pytest.raises(JsonlImportError) as ei:
            seg.import_jsonl(str(p), 1)
        err = ei.value
        stored = list(seg.find(1))
        assert len(stored) == err.committed_events
        assert err.committed_events < 9
        assert err.lineno > err.committed_lines
        # resume recipe really resumes: import the remainder only
        rest = tmp_path / "rest.jsonl"
        remainder = text.splitlines()[err.committed_lines:-1]  # drop bad
        rest.write_text("\n".join(remainder) + "\n", encoding="utf-8")
        seg.import_jsonl(str(rest), 1)
        assert {e.entity_id for e in seg.find(1)} == \
            {f"e{i}" for i in range(8)}

    def test_duplicate_explicit_id_last_wins(self, tmp_path):
        p = tmp_path / "in.jsonl"
        eid = "ab" * 16
        p.write_text(
            json.dumps({"event": "$set", "entityType": "u",
                        "entityId": "x", "eventId": eid,
                        "properties": {"v": 1}}) + "\n" +
            json.dumps({"event": "$set", "entityType": "u",
                        "entityId": "x", "eventId": eid,
                        "properties": {"v": 2}}) + "\n",
            encoding="utf-8")
        seg = _seg_store(tmp_path / "seg")
        seg.import_jsonl(str(p), 1)
        (e,) = list(seg.find(1))
        assert e.properties.to_dict() == {"v": 2}

    def test_no_trailing_newline(self, tmp_path):
        p = tmp_path / "in.jsonl"
        p.write_bytes(json.dumps(
            {"event": "buy", "entityType": "u",
             "entityId": "x"}).encode())
        seg = _seg_store(tmp_path / "seg")
        assert seg.import_jsonl(str(p), 1) == 1

    def test_segment_bytes_match_python_insert(self, tmp_path):
        # fully-specified record -> the native segment line is
        # byte-identical to json.dumps({"op": "put", "event": to_json})
        src = {"event": "rate", "entityType": "user", "entityId": "u1",
               "eventId": "cd" * 16, "targetEntityType": "item",
               "targetEntityId": "i1", "properties": {"rating": 4.0},
               "eventTime": "2015-03-01T12:34:56.789Z",
               "creationTime": "2015-03-01T12:34:56.789Z"}
        p = tmp_path / "in.jsonl"
        p.write_text(json.dumps(src) + "\n", encoding="utf-8")
        root = tmp_path / "seg"
        seg = _seg_store(root)
        seg.import_jsonl(str(p), 1)
        d = os.path.join(str(root), "events", "app_1")
        (name,) = [n for n in os.listdir(d) if n.startswith("seg-")]
        with open(os.path.join(d, name), "rb") as f:
            line = f.read().rstrip(b"\n")
        want = json.dumps(
            {"op": "put",
             "event": Event.from_json(src).to_json()}).encode()
        assert line == want


@pytest.mark.skipif(codec() is None, reason="no native toolchain")
def test_out_of_range_datetimes_rejected_like_python(tmp_path):
    # year 0 / a 9999 pushed past the boundary by its offset must fail
    # the import (as the Python lane does), never publish a segment
    # that poisons later replays
    for bad in ("0000-01-01T00:00:00Z", "9999-12-31T23:59:59-01:00",
                "2015-01-01T00:00:00+24:00"):
        p = tmp_path / "in.jsonl"
        p.write_text(json.dumps(
            {"event": "buy", "entityType": "u", "entityId": "x",
             "eventTime": bad}) + "\n", encoding="utf-8")
        seg = _seg_store(tmp_path / f"seg-{bad[:4]}-{bad[-5:-3]}")
        with pytest.raises(JsonlImportError):
            seg.import_jsonl(str(p), 1)
        assert list(seg.find(1)) == []


def test_missing_file_is_clean_oserror(tmp_path):
    with pytest.raises(OSError):
        _seg_store(tmp_path / "seg").import_jsonl(
            str(tmp_path / "nope.jsonl"), 1)
    with pytest.raises(OSError):
        MemoryEventStore().import_jsonl(str(tmp_path / "nope2.jsonl"), 1)


class TestRemoteBulkImport:
    @pytest.fixture()
    def remote(self, tmp_path):
        from conftest import start_sqlite_backed_storage_server
        from predictionio_tpu.data.storage.remote import (
            RemoteClient,
            RemoteEventStore,
        )

        srv, backing = start_sqlite_backed_storage_server(tmp_path)
        store = RemoteEventStore(
            RemoteClient(f"http://127.0.0.1:{srv.port}"))
        yield store, backing
        srv.shutdown()

    def test_block_forwarding_parity(self, tmp_path, remote):
        store, backing = remote
        p = tmp_path / "in.jsonl"
        p.write_text(_lines(), encoding="utf-8")
        assert store.import_jsonl(str(p), 1) == 6
        got = sorted(store.find(1), key=lambda e: e.entity_id)
        assert len(got) == 6
        by_ent = {e.entity_id: e for e in got}
        # explicit eventId wins over the spliced one (last-wins JSON)
        assert by_ent["u4"].event_id == "feedbeef" * 4
        assert by_ent["u1"].properties.to_dict() == {"rating": 3.5}

    def test_replayed_block_is_idempotent(self, tmp_path, remote):
        # the spliced client-side ids make a retried block an id-keyed
        # upsert: POSTing the IDENTICAL spliced bytes twice through the
        # raw /import_jsonl endpoint (exactly what a transport retry
        # sends after a lost response) must not duplicate
        store, backing = remote
        rows = [json.dumps({"eventId": f"{i:032d}", "event": "buy",
                            "entityType": "u", "entityId": f"x{i}",
                            "eventTime": "2015-03-01T00:00:00.000Z"})
                for i in range(5)]
        block = ("\n".join(rows) + "\n").encode()
        for _ in range(2):
            _, _, body = store.c.request(
                "POST", "/v1/events/1/import_jsonl", block)
            assert json.loads(body)["imported"] == 5
        assert len(list(store.find(1))) == 5

    def test_404_falls_back_to_batch_lane(self, tmp_path, remote):
        # a NEWER client against an OLDER storage server (no
        # /import_jsonl route) must degrade to the inherited per-event
        # lane instead of failing the import
        from predictionio_tpu.data.storage.base import StorageError

        store, _ = remote
        real = store.c.request

        def no_bulk(method, path, *a, **kw):
            if "/import_jsonl" in path:
                err = StorageError("storage server 404 on " + path)
                err.status = 404
                raise err
            return real(method, path, *a, **kw)

        store.c.request = no_bulk
        p = tmp_path / "in.jsonl"
        p.write_text(_lines(), encoding="utf-8")
        assert store.import_jsonl(str(p), 1) == 6
        assert len(list(store.find(1))) == 6

    def test_error_reports_global_prefix(self, tmp_path, remote):
        store, _ = remote
        rows = [json.dumps({"event": "buy", "entityType": "u",
                            "entityId": f"e{i}"}) for i in range(3)]
        rows.append(json.dumps({"event": "$bogus", "entityType": "u",
                                "entityId": "bad"}))
        p = tmp_path / "in.jsonl"
        p.write_text("\n".join(rows) + "\n", encoding="utf-8")
        with pytest.raises(JsonlImportError) as ei:
            store.import_jsonl(str(p), 1)
        assert ei.value.lineno == 4
        assert ei.value.committed_events == len(list(store.find(1)))


def test_base_lane_chunked_commit(tmp_path):
    mem = MemoryEventStore()
    rows = [json.dumps({"event": "buy", "entityType": "u",
                        "entityId": f"e{i}"}) for i in range(7)]
    rows.insert(5, "this is not json")
    p = tmp_path / "in.jsonl"
    p.write_text("\n".join(rows) + "\n", encoding="utf-8")
    with pytest.raises(JsonlImportError) as ei:
        mem.import_jsonl(str(p), 1, chunk=2)
    err = ei.value
    assert err.lineno == 6
    assert err.committed_lines == 4
    assert err.committed_events == 4
    assert len(list(mem.find(1))) == 4
