"""Elastic reliability (ISSUE 11, docs/reliability.md): atomic
checkpoint writes + torn-checkpoint fallback, the distributed
checkpointer's commit protocol, kill -9 mid-train resume parity
(bitwise), lane supervision / degraded serving, storage 503s with
Retry-After, and the shared bounded-backoff retry helper."""

import json
import os
import pickle
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu import faults
from predictionio_tpu.faults import FaultError
from predictionio_tpu.utils.retrying import (
    RetryPolicy,
    backoff_delays,
    retry_call,
)
from predictionio_tpu.workflow.checkpoint import (
    Checkpointer,
    DistributedCheckpointer,
    TornCheckpointError,
    make_checkpointer,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# bounded-backoff retry helper
# ---------------------------------------------------------------------------

class TestRetrying:
    def test_success_first_try(self):
        calls = []
        assert retry_call(lambda: calls.append(1) or 7) == 7
        assert len(calls) == 1

    def test_bounded_attempts_then_raises_last(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError(f"attempt {len(calls)}")

        with pytest.raises(ValueError, match="attempt 3"):
            retry_call(boom, policy=RetryPolicy(max_attempts=3,
                                                base_ms=1.0))
        assert len(calls) == 3

    def test_retry_on_filters(self):
        def boom():
            raise KeyError("nope")

        with pytest.raises(KeyError):
            retry_call(boom, policy=RetryPolicy(max_attempts=5,
                                                base_ms=1.0),
                       retry_on=(ValueError,))

    def test_on_retry_observer(self):
        seen = []

        def flaky():
            if len(seen) < 2:
                raise OSError("blip")
            return "ok"

        out = retry_call(flaky,
                         policy=RetryPolicy(max_attempts=4, base_ms=1.0),
                         on_retry=lambda k, e: seen.append(k))
        assert out == "ok" and seen == [0, 1]

    def test_backoff_sequence_exponential_capped(self):
        policy = RetryPolicy(max_attempts=5, base_ms=100.0,
                             cap_ms=300.0, jitter=0.0)
        assert list(backoff_delays(policy)) == [0.1, 0.2, 0.3, 0.3]

    def test_jitter_seed_deterministic(self):
        p = RetryPolicy(max_attempts=4, base_ms=100.0, jitter=0.2,
                        seed=3)
        assert list(backoff_delays(p)) == list(backoff_delays(p))
        for d, base in zip(backoff_delays(p), (0.1, 0.2, 0.4)):
            assert abs(d - base) <= 0.2 * base + 1e-9


# ---------------------------------------------------------------------------
# atomic pickle writes + torn fallback (single-process Checkpointer)
# ---------------------------------------------------------------------------

def _pickle_checkpointer(path) -> Checkpointer:
    """A Checkpointer forced onto the pickle fallback (orbax may be
    installed in this environment; the atomicity contract under test is
    the pickle lane's)."""
    ck = Checkpointer(str(path))
    if ck._mgr is not None:
        ck._mgr.close()
    ck._mgr = None
    ck._ocp = None
    return ck


class TestAtomicPickleCheckpoints:
    def test_save_leaves_no_tmp_and_roundtrips(self, tmp_path):
        ck = _pickle_checkpointer(tmp_path / "ck")
        ck.save(1, {"a": np.arange(4.0)})
        names = sorted(os.listdir(ck.directory))
        assert names == ["step_1.pkl"]  # no .tmp residue
        got = ck.restore(1)
        np.testing.assert_array_equal(got["a"], np.arange(4.0))

    def test_torn_step_falls_back_to_previous_committed(self, tmp_path):
        ck = _pickle_checkpointer(tmp_path / "ck")
        ck.save(1, {"x": 1.0})
        ck.save(2, {"x": 2.0})
        # simulate a crash mid-write that somehow left a truncated
        # container at the newest step (pre-atomic-rename behavior)
        good = pickle.dumps({"x": 3.0}, protocol=4)
        with open(os.path.join(ck.directory, "step_3.pkl"), "wb") as f:
            f.write(good[: len(good) // 2])
        step, state = ck.restore_latest()
        assert step == 2 and state == {"x": 2.0}

    def test_restore_latest_empty_dir(self, tmp_path):
        ck = _pickle_checkpointer(tmp_path / "ck")
        assert ck.restore_latest() == (0, None)

    def test_metadata_roundtrip_atomic(self, tmp_path):
        ck = _pickle_checkpointer(tmp_path / "ck")
        ck.set_metadata({"fingerprint": "abc"})
        assert ck.get_metadata() == {"fingerprint": "abc"}
        assert not os.path.exists(
            os.path.join(ck.directory, "run_metadata.json.tmp"))

    def test_injected_crash_before_commit_preserves_previous(
            self, tmp_path):
        """mode=error at checkpoint.commit models the crash window
        after serialization, before the atomic rename: the step file
        never appears and the previous step still restores."""
        ck = _pickle_checkpointer(tmp_path / "ck")
        ck.save(1, {"x": 1.0})
        faults.inject("checkpoint.commit", "error")
        with pytest.raises(FaultError):
            ck.save(2, {"x": 2.0})
        faults.clear()
        assert ck.restore_latest() == (1, {"x": 1.0})


# ---------------------------------------------------------------------------
# distributed checkpointer: commit protocol + torn detection
# ---------------------------------------------------------------------------

class TestDistributedCheckpointer:
    def test_roundtrip_and_prune(self, tmp_path):
        ck = DistributedCheckpointer(str(tmp_path / "d"), keep=2,
                                     process_index=0, process_count=1)
        for step in (1, 2, 3):
            ck.save(step, {"U": np.full((4, 2), float(step)), "n": step})
        assert ck.all_steps() == [2, 3]  # keep=2 pruned step 1
        like = {"U": np.zeros((4, 2)), "n": 0}
        step, state = ck.restore_latest(like=like)
        assert step == 3
        np.testing.assert_array_equal(state["U"], np.full((4, 2), 3.0))
        assert int(state["n"]) == 3

    def test_missing_commit_marker_is_torn(self, tmp_path):
        ck = DistributedCheckpointer(str(tmp_path / "d"),
                                     process_index=0, process_count=1)
        ck.save(1, {"x": np.ones(3)})
        ck.save(2, {"x": np.ones(3) * 2})
        os.remove(os.path.join(ck._step_dir(2), "COMMIT.json"))
        assert ck.all_steps() == [1]
        with pytest.raises(TornCheckpointError):
            ck.restore(2, like={"x": np.zeros(3)})
        step, state = ck.restore_latest(like={"x": np.zeros(3)})
        assert step == 1
        np.testing.assert_array_equal(state["x"], np.ones(3))
        assert ck.discard_torn() == [2]
        assert not os.path.exists(ck._step_dir(2))

    def test_missing_shard_file_is_torn(self, tmp_path):
        ck = DistributedCheckpointer(str(tmp_path / "d"),
                                     process_index=0, process_count=1)
        ck.save(1, {"x": np.ones(3)})
        ck.save(2, {"x": np.ones(3) * 2})
        os.remove(os.path.join(ck._step_dir(2), "shard_p0.npz"))
        step, state = ck.restore_latest(like={"x": np.zeros(3)})
        assert step == 1

    def test_sharded_jax_leaves_roundtrip(self, tmp_path):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        x = jnp.arange(12.0).reshape(6, 2)
        ck = DistributedCheckpointer(str(tmp_path / "d"),
                                     process_index=0, process_count=1)
        ck.save(1, {"U": x})
        step, state = ck.restore_latest(like={"U": jnp.zeros((6, 2))})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(state["U"]),
                                      np.asarray(x))

    def test_injected_crash_window_yields_torn_step(self, tmp_path):
        """mode=error at checkpoint.commit fires AFTER the shards are
        durable but BEFORE the marker — exactly the kill -9 window the
        commit protocol exists for. The step must be invisible."""
        ck = DistributedCheckpointer(str(tmp_path / "d"),
                                     process_index=0, process_count=1)
        ck.save(1, {"x": np.ones(2)})
        faults.inject("checkpoint.commit", "error")
        with pytest.raises(FaultError):
            ck.save(2, {"x": np.ones(2) * 2})
        faults.clear()
        assert ck.all_steps() == [1]
        step, _ = ck.restore_latest(like={"x": np.zeros(2)})
        assert step == 1

    def test_make_checkpointer_env_force(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTPU_DIST_CKPT", "1")
        assert isinstance(make_checkpointer(str(tmp_path / "a")),
                          DistributedCheckpointer)
        monkeypatch.delenv("PTPU_DIST_CKPT")
        assert isinstance(make_checkpointer(str(tmp_path / "b")),
                          Checkpointer)


# ---------------------------------------------------------------------------
# kill -9 mid-train: resume parity (bitwise) via a crashed subprocess
# ---------------------------------------------------------------------------

_TRAIN_WORKER = textwrap.dedent("""
    import json, os, sys
    import numpy as np

    os.environ["JAX_PLATFORMS"] = "cpu"
    mode, ckdir, outfile = sys.argv[1], sys.argv[2], sys.argv[3]
    if mode == "crash":
        # preemption mid-save: the 4th checkpoint.save never completes
        # (crash mode is os._exit(42) — no atexit, no cleanup)
        os.environ["PTPU_FAULTS"] = "checkpoint.save=crash,after=3"

    from predictionio_tpu.models.als import (
        ALSParams, RatingsCOO, train_als)

    rng = np.random.default_rng(13)
    nnz = 600
    ratings = RatingsCOO(
        users=rng.integers(0, 24, nnz).astype(np.int32),
        items=rng.integers(0, 16, nnz).astype(np.int32),
        ratings=rng.uniform(1, 5, nnz).astype(np.float32),
        n_users=24, n_items=16)
    params = ALSParams(rank=4, num_iterations=6, seed=3)
    U, V = train_als(ratings, params, checkpoint_dir=ckdir,
                     checkpoint_every=1)
    np.savez(outfile, U=np.asarray(U), V=np.asarray(V))
    json.dump({"ok": True}, open(outfile + ".json", "w"))
""")


def _run_train_worker(tmp_path, mode: str, ckdir: str, tag: str):
    worker = tmp_path / "train_worker.py"
    worker.write_text(_TRAIN_WORKER)
    outfile = str(tmp_path / f"out_{tag}.npz")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PTPU_FAULTS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    proc = subprocess.run(
        [sys.executable, str(worker), mode, ckdir, outfile],
        env=env, capture_output=True, text=True, timeout=240)
    return proc, outfile


class TestKillMidTrainResume:
    def test_kill9_resume_bitwise_parity(self, tmp_path):
        """A run killed -9 mid-save resumes from the last committed
        step and finishes with factors BITWISE equal to a run that was
        never interrupted (both through the checkpointed stepper
        path)."""
        ck_a = str(tmp_path / "ck_uninterrupted")
        ck_b = str(tmp_path / "ck_crashed")

        ref, ref_out = _run_train_worker(tmp_path, "full", ck_a, "ref")
        assert ref.returncode == 0, ref.stdout + ref.stderr

        crashed, _ = _run_train_worker(tmp_path, "crash", ck_b, "crash")
        assert crashed.returncode == 42, \
            f"expected injected crash: rc={crashed.returncode}\n" \
            f"{crashed.stdout}{crashed.stderr}"
        # the crash hit during the 4th save; orbax writes are async
        # (save N waits only for save N-1), so the last COMMITTED step
        # is 2 or 3 — never 4, and never a torn 3
        saved = Checkpointer(ck_b).all_steps()
        assert saved and 2 <= max(saved) <= 3, saved

        resumed, res_out = _run_train_worker(tmp_path, "full", ck_b,
                                             "resumed")
        assert resumed.returncode == 0, resumed.stdout + resumed.stderr

        a, b = np.load(ref_out), np.load(res_out)
        assert np.array_equal(a["U"], b["U"])
        assert np.array_equal(a["V"], b["V"])


# ---------------------------------------------------------------------------
# lane supervision: detection, redistribution, restart, degraded state
# ---------------------------------------------------------------------------

from predictionio_tpu.obs import MetricsRegistry  # noqa: E402
from predictionio_tpu.server.engineserver import (  # noqa: E402
    QueryServer,
    ServerConfig,
    pick_live_lane,
)


class _LaneStub:
    """The lane-supervision surface of QueryServer without devices or
    models: the unbound methods run against this stub, so the
    detection/redistribution/restart state machine is tier-1-testable
    on one CPU device."""

    live_lane = QueryServer.live_lane
    lane_attempt_order = QueryServer.lane_attempt_order
    _lane_ok = QueryServer._lane_ok
    _lane_error = QueryServer._lane_error
    _lane_restarter = QueryServer._lane_restarter
    degraded_status = QueryServer.degraded_status

    def __init__(self, n_lanes=3, threshold=2):
        class _Inst:
            id = "inst-1"

        self.config = ServerConfig(
            lane_fail_threshold=threshold,
            lane_restart_backoff_ms=1.0,
            lane_restart_max_attempts=4)
        self._lock = threading.RLock()
        self._lane_health = threading.Lock()
        self._dead_lanes = {}
        self._lane_streaks = {}
        self.lane_models = [["m"] for _ in range(n_lanes)]
        self.lane_devices = list(range(n_lanes))
        self.algorithms = []
        self.models = []
        self.instance = _Inst()
        self.metrics = MetricsRegistry()
        self._lane_restarts = self.metrics.counter(
            "pio_lane_restarts_total", "t")
        self._lane_failures = self.metrics.counter(
            "pio_lane_failures_total", "t")


class TestLaneSupervision:
    def test_pick_live_lane(self):
        assert pick_live_lane(1, 4, set()) == 1
        assert pick_live_lane(1, 4, {1}) == 2      # alive [0,2,3], 1%3
        assert pick_live_lane(3, 4, {3}) == 0      # 3%3 -> alive[0]
        assert pick_live_lane(2, 3, {0, 1, 2}) == 2  # all dead: identity

    def test_streak_below_threshold_stays_alive(self):
        s = _LaneStub(threshold=3)
        s._lane_error(1, RuntimeError("x"))
        s._lane_error(1, RuntimeError("x"))
        assert not s.degraded_status()["active"]
        s._lane_ok(1)  # success resets the streak
        s._lane_error(1, RuntimeError("x"))
        s._lane_error(1, RuntimeError("x"))
        assert not s.degraded_status()["active"]

    def test_threshold_kills_lane_and_redistributes(self):
        s = _LaneStub(threshold=2)
        # keep the restarter down so the degraded state is observable
        faults.inject("serving.lane_restart", "error", match={"lane": "1"})
        s._lane_error(1, RuntimeError("boom"))
        s._lane_error(1, RuntimeError("boom"))
        st = s.degraded_status()
        assert st["active"] and [d["lane"] for d in st["deadLanes"]] == [1]
        assert "boom" in st["deadLanes"][0]["reason"]
        assert s.live_lane(1) != 1
        order = s.lane_attempt_order(1)
        assert order[0] != 1 and order[-1] == 1  # dead lane last resort
        assert sorted(order) == [0, 1, 2]
        assert st["laneFailures"] >= 2

    def test_restarter_recovers_lane_and_counts(self):
        s = _LaneStub(threshold=1)
        # first restart probes fail (injected), then the fault clears
        faults.inject("serving.lane_restart", "error", times=2)
        s._lane_error(2, RuntimeError("dead device"))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and s.degraded_status()["active"]:
            time.sleep(0.02)
        st = s.degraded_status()
        assert not st["active"], st
        assert st["laneRestarts"] == 1
        assert s.live_lane(2) == 2

    def test_rebind_lane_shrink_aborts_restarter(self):
        s = _LaneStub(threshold=1)
        faults.inject("serving.lane_restart", "error", times=1)
        s._lane_error(2, RuntimeError("x"))
        # a rebind shrank the lane set while the restarter backed off
        with s._lock:
            s.lane_devices = [0]
            s.lane_models = [["m"]]
        time.sleep(0.3)  # restarter must return without touching lanes
        assert s.lane_models == [["m"]]


# ---------------------------------------------------------------------------
# storage outage → 503 + Retry-After on the HTTP boundary
# ---------------------------------------------------------------------------

from predictionio_tpu.data.storage.base import AccessKey, App  # noqa: E402
from predictionio_tpu.data.storage.registry import Storage  # noqa: E402
from predictionio_tpu.server.eventserver import (  # noqa: E402
    create_event_server,
)

EVENT = {"event": "rate", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 4.0},
         "eventTime": "2024-01-02T03:04:05.678Z"}


def _call(srv, method, path, body=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"null")


class TestStorage503:
    @pytest.fixture()
    def server(self):
        st = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY"})
        app_id = st.apps().insert(App(id=0, name="app503",
                                      description=None))
        st.access_keys().insert(AccessKey(key="K", app_id=app_id,
                                          events=[]))
        srv = create_event_server(st, host="127.0.0.1", port=0)
        srv.start_background()
        yield srv
        srv.shutdown()

    def test_store_outage_returns_503_retry_after(self, server):
        faults.inject("storage.io", "error", match={"op": "insert"})
        status, headers, body = _call(
            server, "POST", "/events.json?accessKey=K", EVENT)
        assert status == 503
        assert headers.get("Retry-After") == "1"
        assert "unavailable" in body["message"]
        assert "Traceback" not in json.dumps(body)
        # recovery: the same request succeeds once the store is back
        faults.clear()
        status, _, body = _call(
            server, "POST", "/events.json?accessKey=K", EVENT)
        assert status == 201 and "eventId" in body

    def test_find_outage_503(self, server):
        faults.inject("storage.io", "error", match={"op": "find"})
        status, headers, _ = _call(
            server, "GET", "/events.json?accessKey=K")
        assert status == 503 and headers.get("Retry-After") == "1"
