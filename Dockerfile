# predictionio-tpu service image.
#
# This base serves the CONTROL PLANE (event server, storage server,
# admin, dashboard, engine serving on CPU). For TPU training/serving
# hosts, build FROM a TPU VM base image instead (one that ships libtpu,
# e.g. the Cloud TPU VM base) and pass --build-arg BASE=...:
#
#   docker build -t predictionio-tpu .
#   docker build -t predictionio-tpu:tpu --build-arg \
#       BASE=us-docker.pkg.dev/cloud-tpu-images/inference/tpu-vm-base .
ARG BASE=python:3.12-slim
FROM ${BASE}

WORKDIR /opt/predictionio-tpu
COPY pyproject.toml README.md ./
COPY predictionio_tpu ./predictionio_tpu
COPY bin ./bin
COPY examples ./examples
COPY docs ./docs

RUN pip install --no-cache-dir .

# PIO_HOME holds the default sqlite/localfs state; mount a volume here
ENV PIO_HOME=/var/lib/predictionio-tpu
RUN mkdir -p /var/lib/predictionio-tpu
VOLUME /var/lib/predictionio-tpu

# 7070 event server, 7077 storage server, 8000 engine, 7071 admin, 9000 dashboard
EXPOSE 7070 7077 8000 7071 9000

ENTRYPOINT ["ptpu"]
CMD ["eventserver", "--ip", "0.0.0.0", "--port", "7070"]
