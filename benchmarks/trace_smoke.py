"""Flight-recorder smoke (ISSUE 12) — the CI gate for end-to-end
tracing under real HTTP load.

1. deploy a synthetic device-budget model with tracing on, drive a
   concurrent load, and inject ONE latency fault into a device
   dispatch — that query must come back 200 (the fault is just delay)
   and its trace must be RETAINED (flagged ``fault``) while the
   healthy bulk of the load is dropped;
2. the retained trace's Perfetto export must validate: trace-event
   JSON with the full stage timeline (dispatch + readback present),
   every event carrying ``ph``/``ts``/``dur``, parented under the
   batch span;
3. ``pio_trace_*`` gauges are nonzero on /metrics and the OpenMetrics
   negotiation carries a ``pio_query_latency_seconds`` bucket exemplar
   pointing at a retained trace id.

Prints one JSON line; exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import urllib.request
from datetime import datetime, timezone

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from predictionio_tpu.controller import Context  # noqa: E402
from predictionio_tpu.data.bimap import BiMap  # noqa: E402
from predictionio_tpu.data.storage import App, Storage  # noqa: E402
from predictionio_tpu.data.storage.base import (  # noqa: E402
    STATUS_COMPLETED,
    EngineInstance,
)
from predictionio_tpu.faults import inject_spec, registry  # noqa: E402
from predictionio_tpu.models.als import ALSModel, ALSParams  # noqa: E402
from predictionio_tpu.obs.trace import (  # noqa: E402
    format_traceparent,
    parse_traceparent,
)
from predictionio_tpu.server.engineserver import (  # noqa: E402
    QueryServer,
    ServerConfig,
    create_engine_server,
)
from predictionio_tpu.templates.recommendation import (  # noqa: E402
    default_engine_params,
    recommendation_engine,
)

FAULT_TRACE_ID = "f0" * 16


def call(port, path, body=None, headers=None, timeout=120):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read()


def main() -> int:
    from predictionio_tpu.utils.platform import force_cpu_if_requested
    force_cpu_if_requested()

    rng = np.random.default_rng(0)
    n_users, n_items, rank = 5_000, 70_000, 32
    import jax

    model = ALSModel(
        user_factors=jax.device_put(rng.standard_normal(
            (n_users, rank)).astype(np.float32)),
        item_factors=jax.device_put(rng.standard_normal(
            (n_items, rank)).astype(np.float32)),
        n_users=n_users, n_items=n_items,
        user_ids=BiMap({f"u{i}": i for i in range(n_users)}),
        item_ids=BiMap({f"i{i}": i for i in range(n_items)}),
        params=ALSParams(rank=rank))

    storage = Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})
    storage.apps().insert(App(0, "tracesmoke"))
    ctx = Context(app_name="tracesmoke", _storage=storage)
    now = datetime.now(timezone.utc)
    inst = EngineInstance(
        id="smoke", status=STATUS_COMPLETED, start_time=now,
        end_time=now, engine_id="smoke", engine_version="1",
        engine_variant="engine.json", engine_factory="synthetic")
    storage.engine_instances().insert(inst)
    qs = QueryServer(
        ctx, recommendation_engine(),
        default_engine_params("tracesmoke", rank=rank),
        [model], inst,
        ServerConfig(batching=True, max_batch=16, warm_start=False))
    srv = create_engine_server(qs, "127.0.0.1", 0).start_background()
    port = srv.port
    checks = {}
    try:
        # warm the dispatch path so the injected-slow query is the
        # outlier, not the compile
        for u in (1, 2, 3):
            call(port, "/queries.json", {"user": f"u{u}", "num": 5})

        # ONE injected-slow dispatch, tagged with a known trace id so
        # retention is attributable; armed while nothing else is in
        # flight so the times=1 schedule hits THIS query's dispatch
        inject_spec("serving.dispatch=latency,delay_ms=400,times=1")
        try:
            status, headers, _ = call(
                port, "/queries.json", {"user": "u5", "num": 5},
                headers={"traceparent": format_traceparent(
                    FAULT_TRACE_ID, "11" * 8)})
        finally:
            registry().clear("serving.dispatch")

        # then a healthy concurrent load the tail sampler should DROP
        def load(i):
            try:
                call(port, "/queries.json",
                     {"user": f"u{10 + i}", "num": 5})
            except Exception:  # noqa: BLE001 — checks judge below
                pass

        threads = [threading.Thread(target=load, args=(i,))
                   for i in range(48)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        checks["slow_query_answered_200"] = status == 200
        echoed = parse_traceparent(headers.get("traceparent") or "")
        checks["traceparent_adopted"] = (
            echoed is not None and echoed[0] == FAULT_TRACE_ID)

        # 1) the injected-slow query is retained and retrievable
        status, _, body = call(port,
                               f"/trace.json?id={FAULT_TRACE_ID}")
        doc = json.loads(body)
        checks["injected_query_retained"] = (
            doc.get("otherData", {}).get("traceId") == FAULT_TRACE_ID)
        checks["retained_as_fault_or_slow"] = (
            doc.get("otherData", {}).get("retainedReason")
            in ("fault", "slow"))

        # 2) Perfetto export validates with the full stage timeline
        events = doc.get("traceEvents") or []
        checks["events_well_formed"] = bool(events) and all(
            e.get("ph") == "X" and isinstance(e.get("ts"), (int, float))
            and isinstance(e.get("dur"), (int, float))
            for e in events)
        names = {e["name"] for e in events}
        checks["stage_timeline_complete"] = (
            "dispatch" in names and "readback" in names
            and "batch" in names)
        batch = next((e for e in events if e["name"] == "batch"), {})
        dispatch = next((e for e in events
                         if e["name"] == "dispatch"), {})
        checks["stages_parented_on_batch"] = (
            dispatch.get("args", {}).get("parentId")
            == batch.get("args", {}).get("spanId"))

        # tail sampling actually sampled: most of the healthy load
        # was dropped
        _, _, body = call(port, "/trace.json")
        st = json.loads(body)
        checks["healthy_bulk_dropped"] = (
            st["requests"] >= 50
            and st["retained"] < st["requests"] / 2)

        # 3) pio_trace_* gauges nonzero + OpenMetrics exemplar
        _, _, body = call(port, "/metrics")
        text = body.decode()

        def series_value(name_prefix):
            for ln in text.splitlines():
                if ln.startswith(name_prefix):
                    try:
                        return float(ln.rsplit(" ", 1)[1])
                    except ValueError:
                        continue
            return 0.0

        checks["pio_trace_requests_nonzero"] = series_value(
            "pio_trace_requests_total") > 0
        checks["pio_trace_ring_nonzero"] = series_value(
            "pio_trace_ring_size") > 0
        checks["pio_trace_retained_nonzero"] = any(
            series_value(f'pio_trace_retained_total{{reason="{r}"}}')
            > 0 for r in ("fault", "slow", "error", "deadline"))
        _, om_headers, body = call(
            port, "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        om = body.decode()
        checks["openmetrics_negotiated"] = om_headers[
            "Content-Type"].startswith("application/openmetrics-text")
        ex = [ln for ln in om.splitlines()
              if "pio_query_latency_seconds_bucket" in ln
              and "# {" in ln]
        checks["exemplar_present"] = bool(ex) and bool(
            re.search(r'# \{trace_id="[0-9a-f]{32}"\}', ex[0]))
    finally:
        srv.shutdown()

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({"bench": "trace_smoke", "ok": ok, **checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
