"""External quality anchor: framework vs an INDEPENDENT MLlib-semantics
oracle on the ML-20M surrogate (VERDICT r4 missing #1 / next-round #3).

Two trainers run the same published algorithm (Hu-Koren-Volinsky
implicit ALS with ALS-WR weighted-lambda — what the reference template
trains through Spark MLlib, ``ALSAlgorithm.scala:75-85``) from
independent implementations:

- framework: ``predictionio_tpu.models.als.train_als`` (f32/bf16, TPU
  bucketed layouts, Pallas solver, jax threefry init);
- oracle: ``benchmarks/mllib_oracle.py`` (float64 numpy written from
  the papers, PCG64 init, no shared code).

Because the inits are independent, factors can't be compared — QUALITY
is: both factor sets are scored by the same top-K protocol and their
metrics must agree. The protocol is DISCRIMINATIVE (VERDICT r4 weak
#6): implicit training on star-confidence, train-item exclusion, and
binary relevance at >= 3.5 stars puts NDCG@10 near 0.1, not 0.01.

Protocols:
- ``holdout``: seeded random 10% of entries held out; metrics over a
  seeded sample of test users (same sample for both trainers).
- ``loo`` (leave-one-out): each user's LAST-timestamped rating held
  out; hit-rate@10 + NDCG@10 (the sequential template's protocol,
  ``tests/test_sequential.py``).

Usage:
  python benchmarks/quality_anchor.py --scale 1.0 \
      [--npz /tmp/ml20m_full.npz] [--rank 64] [--sample 16384]

Prints ONE JSON document (the PARITY_EVAL artifact). Exit 1 if the
holdout NDCG@10 relative delta exceeds --gate (default 2%).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def topk_excluding(U: np.ndarray, V: np.ndarray, users: np.ndarray,
                   train_lists, k: int, chunk: int = 2048) -> np.ndarray:
    """Top-k item ids per sampled user with that user's train items
    excluded from the ranking (score -> -inf). Chunked [B, n_items]
    host matmul in float32."""
    Uf = np.asarray(U, dtype=np.float32)
    Vf = np.asarray(V, dtype=np.float32)
    out = np.empty((len(users), k), dtype=np.int64)
    for s in range(0, len(users), chunk):
        block = users[s:s + chunk]
        scores = Uf[block] @ Vf.T
        for j, u in enumerate(block):
            scores[j, train_lists[int(u)]] = -np.inf
        part = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        row_scores = np.take_along_axis(scores, part, axis=1)
        out[s:s + chunk] = np.take_along_axis(
            part, np.argsort(-row_scores, kind="stable", axis=1), axis=1)
    return out


def ndcg_and_precision(recs: np.ndarray, rel_sets, k: int = 10):
    ndcgs, precs = [], []
    log2 = 1.0 / np.log2(np.arange(2, k + 2))
    for row, rel in zip(recs, rel_sets):
        if not rel:
            continue
        hits = np.fromiter((int(i) in rel for i in row[:k]), bool, k)
        dcg = float(log2[hits].sum())
        ideal = float(log2[:min(len(rel), k)].sum())
        ndcgs.append(dcg / ideal if ideal else 0.0)
        precs.append(hits.sum() / k)
    return (float(np.mean(ndcgs)) if ndcgs else 0.0,
            float(np.mean(precs)) if precs else 0.0,
            len(ndcgs))


def planted_ml20m(scale: float, latent_rank: int = 16, seed: int = 23,
                  beta: float = 3.0):
    """ML-20M-shaped ratings with planted low-rank taste structure.

    The crucial realism property: WHICH items a user rates is itself
    taste-tilted (softmax over ``beta * affinity + log popularity``,
    sampled without replacement via Gumbel-top-k). In real ML-20M
    users watch what they like, so observation alone carries taste —
    the signal implicit-feedback retrieval actually learns. A selector
    independent of taste (the marginals surrogate, or rating-values-
    only structure) caps ANY trainer's top-K retrieval near the
    popularity baseline. Stars come from the same latent dot plus
    noise; timestamps are per-user sequential (the LOO protocol
    needs an order)."""
    rng = np.random.default_rng(seed)
    n_users = max(int(138_493 * scale), 64)
    n_items = max(int(26_744 * scale), 48)
    nnz = int(20_000_263 * scale)
    Ut = (rng.normal(size=(n_users, latent_rank)) / np.sqrt(latent_rank)
          ).astype(np.float32)
    Vt = (rng.normal(size=(n_items, latent_rank)) / np.sqrt(latent_rank)
          ).astype(np.float32)
    # zipf-ish popularity, shuffled so item id carries no information
    pop = (np.arange(1, n_items + 1, dtype=np.float64) ** -0.8)
    rng.shuffle(pop)
    log_pop = np.log(pop / pop.sum()).astype(np.float32)
    # per-user activity: >=20 like the real inclusion filter, lognormal
    # excess, repaired to sum ~nnz
    n_u = 20 + np.clip(rng.lognormal(3.2, 1.0, n_users), 0,
                       n_items // 2 - 20).astype(np.int64)
    n_u = np.minimum((n_u * (nnz / n_u.sum())).astype(np.int64)
                     .clip(min=5), n_items - 1)
    users_parts, items_parts = [], []
    chunk = 512
    for s in range(0, n_users, chunk):
        e = min(s + chunk, n_users)
        logits = beta * (Ut[s:e] @ Vt.T) + log_pop[None, :]
        keys = logits + rng.gumbel(size=logits.shape).astype(np.float32)
        take = min(max(int(n_u[s:e].max()), 1), n_items)
        top = np.argpartition(-keys, take - 1, axis=1)[:, :take]
        kk = np.take_along_axis(keys, top, axis=1)
        top = np.take_along_axis(top, np.argsort(-kk, axis=1), axis=1)
        for j in range(e - s):
            cnt = int(n_u[s + j])
            items_parts.append(top[j, :cnt])
            users_parts.append(np.full(cnt, s + j, dtype=np.int64))
    users = np.concatenate(users_parts)
    items = np.concatenate(items_parts).astype(np.int64)
    raw = (Ut[users] * Vt[items]).sum(axis=1)
    raw = 3.0 + 1.6 * raw / max(np.abs(raw).std(), 1e-9)
    stars = np.clip(
        np.round((raw + 0.3 * rng.normal(size=raw.shape)) * 2) / 2,
        0.5, 5.0).astype(np.float32)
    ts = np.arange(len(users), dtype=np.int64)  # per-user increasing
    return users, items, stars, ts, n_users, n_items


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--npz", default="")
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--reg", type=float, default=0.01)
    ap.add_argument("--alpha", type=float, default=40.0)
    ap.add_argument("--sample", type=int, default=16384)
    ap.add_argument("--gate", type=float, default=0.02)
    ap.add_argument("--skip-loo", action="store_true")
    ap.add_argument("--beta", type=float, default=3.0,
                help="taste tilt of the planted selector")
    ap.add_argument("--planted", action="store_true",
                    help="ML-20M-dim dataset with PLANTED low-rank "
                         "taste structure instead of the marginals "
                         "surrogate: the surrogate's only learnable "
                         "signal is popularity (NDCG ~0.02 ceiling for "
                         "ANY trainer), while real ML-20M has user "
                         "taste; planting rank-16 structure restores a "
                         "discriminative regime (NDCG ~0.1) where the "
                         "two trainers' agreement is meaningful")
    args = ap.parse_args()

    from ml20m_surrogate import generate

    t0 = time.monotonic()
    if args.planted:
        users, items, stars, ts, n_users, n_items = \
            planted_ml20m(args.scale, beta=args.beta)
    elif args.npz and os.path.exists(args.npz):
        d = np.load(args.npz)
        users, items, stars, ts = (d["users"], d["items"], d["stars"],
                                   d["ts"])
        n_users, n_items = int(d["n_users"]), int(d["n_movies"])
    else:
        users, items, stars, ts, n_users, n_items = generate(args.scale)
    users = users.astype(np.int64)
    items = items.astype(np.int64)
    n = len(users)

    report = {
        "metric": "quality_anchor_ml20m",
        "dataset": ("planted_structure" if args.planted else
                    "marginals_surrogate"),
        "scale": args.scale, "rank": args.rank, "iters": args.iters,
        "reg": args.reg, "alpha": args.alpha,
        "protocol": {
            "training": "implicit HKV, confidence 1 + alpha*stars, "
                        "ALS-WR weighted lambda",
            "relevance": "held-out stars >= 3.5, train items excluded",
            "oracle": "benchmarks/mllib_oracle.py (independent numpy "
                      "f64, PCG64 init — no shared code with "
                      "models/als.py)",
        },
        "n_ratings": n, "n_users": n_users, "n_items": n_items,
    }

    from predictionio_tpu.models.als import (ALSParams, RatingsCOO,
                                             train_als)
    from mllib_oracle import train_implicit_als

    params = ALSParams(rank=args.rank, num_iterations=args.iters,
                       reg=args.reg, seed=3, implicit_prefs=True,
                       alpha=args.alpha)

    def run_both(tr_mask, label):
        tr_u, tr_i, tr_r = users[tr_mask], items[tr_mask], stars[tr_mask]
        t1 = time.monotonic()
        Uf, Vf = train_als(
            RatingsCOO(tr_u.astype(np.int32), tr_i.astype(np.int32),
                       tr_r.astype(np.float32), n_users, n_items),
            params)
        Uf = np.asarray(Uf)[:n_users]
        Vf = np.asarray(Vf)[:n_items]
        fw_s = time.monotonic() - t1
        t1 = time.monotonic()
        Uo, Vo = train_implicit_als(tr_u, tr_i, tr_r, n_users, n_items,
                                    rank=args.rank,
                                    iterations=args.iters, lam=args.reg,
                                    alpha=args.alpha)
        or_s = time.monotonic() - t1
        report[label + "_train_s"] = {"framework": round(fw_s, 1),
                                      "oracle": round(or_s, 1)}
        return (Uf, Vf), (Uo, Vo)

    # ---- protocol 1: random holdout --------------------------------------
    rng = np.random.default_rng(17)
    test = rng.random(n) < 0.10
    fw, orc = run_both(~test, "holdout")

    train_lists = [[] for _ in range(n_users)]
    for u, i in zip(users[~test], items[~test]):
        train_lists[int(u)].append(int(i))
    train_lists = [np.asarray(t, dtype=np.int64) for t in train_lists]
    rel_by_user = {}
    for u, i, r in zip(users[test], items[test], stars[test]):
        if r >= 3.5:
            rel_by_user.setdefault(int(u), set()).add(int(i))
    eligible = np.asarray(sorted(rel_by_user), dtype=np.int64)
    sample = eligible if len(eligible) <= args.sample else \
        np.sort(np.random.default_rng(13).choice(
            eligible, size=args.sample, replace=False))
    rel_sets = [rel_by_user[int(u)] for u in sample]

    out = {}
    for name, (U, V) in (("framework", fw), ("oracle", orc)):
        recs = topk_excluding(U, V, sample, train_lists, k=10)
        ndcg, prec, n_eval = ndcg_and_precision(recs, rel_sets, k=10)
        out[name] = {"ndcg10": round(ndcg, 5), "precision10":
                     round(prec, 5), "users_evaluated": n_eval}
    d_ndcg = abs(out["framework"]["ndcg10"] - out["oracle"]["ndcg10"]) \
        / max(out["oracle"]["ndcg10"], 1e-9)
    report["holdout"] = {**out, "ndcg10_rel_delta": round(d_ndcg, 5),
                         "sampled_users": len(sample)}

    # ---- protocol 2: leave-one-out by last timestamp ---------------------
    if not args.skip_loo:
        order = np.lexsort((ts, users))
        u_sorted = users[order]
        last_of_user = np.flatnonzero(
            np.r_[u_sorted[1:] != u_sorted[:-1], True])
        loo_rows = order[last_of_user]  # one held-out row per user
        loo_mask = np.zeros(n, dtype=bool)
        loo_mask[loo_rows] = True
        fw2, orc2 = run_both(~loo_mask, "loo")
        tr2_lists = [[] for _ in range(n_users)]
        for u, i in zip(users[~loo_mask], items[~loo_mask]):
            tr2_lists[int(u)].append(int(i))
        tr2_lists = [np.asarray(t, dtype=np.int64) for t in tr2_lists]
        # -1 sentinel: user ids with no ratings row (sparse id spaces
        # in real exports) must not contribute garbage "relevant" items
        held_item = np.full(n_users, -1, dtype=np.int64)
        held_item[users[loo_rows]] = items[loo_rows]
        eligible2 = np.flatnonzero(held_item >= 0)
        sample2 = eligible2 if len(eligible2) <= args.sample else \
            np.sort(np.random.default_rng(29).choice(
                eligible2, size=args.sample, replace=False))
        rel2 = [{int(held_item[u])} for u in sample2]
        out2 = {}
        for name, (U, V) in (("framework", fw2), ("oracle", orc2)):
            recs = topk_excluding(U, V, sample2, tr2_lists, k=10)
            ndcg, hit, n_eval = ndcg_and_precision(recs, rel2, k=10)
            out2[name] = {"ndcg10": round(ndcg, 5),
                          "hitrate10": round(hit * 10, 5),
                          "users_evaluated": n_eval}
        d2 = abs(out2["framework"]["ndcg10"] - out2["oracle"]["ndcg10"]) \
            / max(out2["oracle"]["ndcg10"], 1e-9)
        report["loo"] = {**out2, "ndcg10_rel_delta": round(d2, 5),
                         "sampled_users": len(sample2)}

    report["gate_rel"] = args.gate
    report["pass"] = bool(d_ndcg <= args.gate)
    report["total_s"] = round(time.monotonic() - t0, 1)
    report["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())
    print(json.dumps(report, indent=1))
    if not report["pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
