"""Roofline accounting for the fused ALS trainer on the attached device.

The round-4 on-chip gram profile showed every hot stage (gather, gram,
solve) running at multi-TF/s while the WHOLE iteration achieves only
0.83 TF/s — so the binding constraint is something the per-stage view
doesn't see. This probe asks XLA itself: it captures the exact
``_train_fused`` invocation ``train_als`` makes (shim capture — zero
argument-assembly duplication), lowers/compiles that same program, and
prints ``cost_analysis()`` (flops, bytes accessed, optimal seconds).

The output places the iteration on the DUAL roofline (ISSUE 7):

- ``arithmetic_intensity`` = XLA flops / XLA bytes accessed, the
  program's position on the x-axis;
- ``attainable_tflops`` = min(peak MXU, intensity x peak HBM GB/s) —
  the roof over that position — and ``bound`` says which segment
  ("hbm" left of the ridge, "mxu" right of it);
- ``hbm_gbps`` / ``hbm_utilization`` (achieved bandwidth) and
  ``achieved_tflops`` / ``mfu`` (achieved compute, padded-work FLOP
  model over the measured steady-state time) say how close the run
  sits to that roof.

``PROBE_GRAM`` selects the gram realization (einsum | pair | fused |
auto), so the bench can emit one block per mode and the fused kernel's
bytes-accessed drop is visible next to the einsum baseline.

With ``PROBE_SERVE=1`` the probe runs the SERVING roofline instead
(ISSUE 13): it lowers the batched top-k dispatch (`_serve_topk`) over
an f32 model and over the row-quantized (``PROBE_QUANT``, default
int8) tables, compares XLA's post-fusion bytes-accessed / arithmetic
intensity / bound for the two programs, and times both dispatches —
the block that proves where the serving bound moved when the wire
went int8 (the fused kernel's VMEM streaming is not visible to XLA's
cost model; its effect shows up in serving_bench's measured lane).

Usage: python benchmarks/roofline_probe.py   (from the repo root)
Env:   BENCH_SCALE, BENCH_RANK as for bench.py; PROBE_ITERS (default 1);
       PROBE_GRAM (default auto); PROBE_GATHER (float32|bfloat16);
       PROBE_REPEATS (default 3); PROBE_SERVE=1 (+ PROBE_QUANT,
       PROBE_SERVE_ITEMS, PROBE_SERVE_BATCH) for the serving block
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: public spec-sheet HBM bandwidth (GB/s) per generation
PEAK_BW = {"TPU v5 lite": 819, "TPU v5e": 819, "TPU v4": 1228,
           "TPU v5": 2765, "TPU v5p": 2765, "TPU v6e": 1640,
           "TPU v6 lite": 1640}


def _dual_roofline(flops: float, byts: float, bw, peak_fl,
                   wall_s: float | None) -> dict:
    """Shared dual-roofline block: where a program SITS (intensity)
    and which roof is over it, plus achieved bandwidth when timed."""
    out: dict = {"xla_flops": flops, "xla_bytes_accessed": byts}
    if byts and flops:
        ai = flops / byts
        out["arithmetic_intensity"] = round(ai, 3)
        if bw and peak_fl:
            attainable = min(peak_fl, ai * bw * 1e9)
            out["attainable_tflops"] = round(attainable / 1e12, 2)
            out["bound"] = "hbm" if ai * bw * 1e9 < peak_fl else "mxu"
    if wall_s and byts:
        gbps = byts / wall_s / 1e9
        out["hbm_gbps"] = round(gbps, 1)
        if bw:
            out["hbm_utilization"] = round(gbps / bw, 3)
    if wall_s is not None:
        out["wall_s_per_dispatch"] = round(wall_s, 6)
    return out


def serving_roofline() -> dict:
    """The serving-side roofline block (ISSUE 13): the batched top-k
    dispatch over f32 vs row-quantized tables. XLA's bytes-accessed
    for the einsum realization shows the table-read + score-matrix
    traffic the quantized wire shrinks — the `bound` field says
    whether the dispatch is still pinned to the HBM roof after the
    move."""
    import jax

    import predictionio_tpu.models.als as als

    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    rank = int(os.environ.get("BENCH_RANK", "64"))
    quant = os.environ.get("PROBE_QUANT", "int8")
    n_items = int(os.environ.get("PROBE_SERVE_ITEMS",
                                 str(int(1_200_000 * scale))))
    B = int(os.environ.get("PROBE_SERVE_BATCH", "2048"))
    n_users = max(int(138_000 * scale), B)
    k = 16
    rng = np.random.default_rng(0)
    U = rng.standard_normal((n_users, rank)).astype(np.float32)
    V = rng.standard_normal((n_items, rank)).astype(np.float32)
    idx = rng.integers(0, n_users, B)

    device = jax.devices()[0].device_kind
    bw = next((v for kk, v in PEAK_BW.items() if device.startswith(kk)),
              None)
    try:
        from bench import device_peak_flops

        peak_fl = device_peak_flops()
    except Exception:  # noqa: BLE001 — probe must not die on a moved
        peak_fl = None  # bench.py symbol

    def probe_tables(uf, itf):
        lowered = als._serve_topk.lower(uf, itf, idx, k=k,
                                        n_items=n_items)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        # measured dispatch: warm once, then best-of-3
        als._serve_topk(uf, itf, idx, k=k, n_items=n_items
                        )[0].block_until_ready()
        best = float("inf")
        for _ in range(3):
            t0 = time.monotonic()
            als._serve_topk(uf, itf, idx, k=k, n_items=n_items
                            )[0].block_until_ready()
            best = min(best, time.monotonic() - t0)
        return _dual_roofline(float(ca.get("flops", 0.0)),
                              float(ca.get("bytes accessed", 0.0)),
                              bw, peak_fl, best)

    Ud, Vd = jax.device_put(U), jax.device_put(V)
    f32_block = probe_tables(Ud, Vd)
    # ptpu: allow[quantize-without-parity-gate] — roofline probe
    # measures both table modes offline; nothing serves these tables
    qU = als.QuantizedFactors(*als._quantize_rows(U, quant),
                              quant=quant)
    # ptpu: allow[quantize-without-parity-gate] — same offline probe
    qV = als.QuantizedFactors(*als._quantize_rows(V, quant),
                              quant=quant)
    qU, qV = jax.device_put(qU), jax.device_put(qV)
    q_block = probe_tables(qU, qV)
    out = {
        "metric": "serving_topk_roofline",
        "device": device,
        "rank": rank, "n_items": n_items, "batch": B, "k": k,
        "quant": quant,
        "f32": f32_block,
        quant: q_block,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
    }
    if f32_block.get("xla_bytes_accessed") \
            and q_block.get("xla_bytes_accessed"):
        out["bytes_x"] = round(
            f32_block["xla_bytes_accessed"]
            / q_block["xla_bytes_accessed"], 2)
    if f32_block.get("wall_s_per_dispatch") \
            and q_block.get("wall_s_per_dispatch"):
        out["dispatch_x"] = round(
            f32_block["wall_s_per_dispatch"]
            / q_block["wall_s_per_dispatch"], 2)
    return out


def main() -> None:
    if os.environ.get("PROBE_SERVE") == "1":
        print(json.dumps(serving_roofline()))
        return
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    rank = int(os.environ.get("BENCH_RANK", "64"))
    iters = int(os.environ.get("PROBE_ITERS", "1"))
    gram = os.environ.get("PROBE_GRAM", "auto")
    gather = os.environ.get("PROBE_GATHER", "float32")
    n_users = int(138_000 * scale)
    n_items = int(27_000 * scale)
    nnz = int(20_000_000 * scale)

    import jax

    import predictionio_tpu.models.als as als

    rng = np.random.default_rng(0)
    items = (np.random.default_rng(1).zipf(1.3, size=nnz)
             % n_items).astype(np.int32)
    users = rng.integers(0, n_users, nnz).astype(np.int32)
    vals = np.ones(nnz, dtype=np.float32)
    ratings = als.RatingsCOO(users, items, vals, n_users, n_items)
    params = als.ALSParams(rank=rank, num_iterations=iters,
                           implicit_prefs=True, alpha=40.0, reg=0.01,
                           seed=3, gram_mode=gram, gather_dtype=gather)

    captured: dict = {}
    orig = als._train_fused

    def shim(*a, **k):
        captured["a"], captured["k"] = a, k
        return orig(*a, **k)

    packed = als.pack_ratings(ratings, params)
    als._train_fused = shim
    try:
        # warm run: compiles + ships the blocked layout
        U, V = als.train_als(ratings, params, packed=packed)
        np.asarray(jax.device_get(V[0, :1]))  # hard sync
        # steady state: best-of-N repeat runs on the SAME packed
        # problem — the pure compiled-loop time the bench headline
        # measures, no compile or transfer in the denominator
        best = float("inf")
        for _ in range(int(os.environ.get("PROBE_REPEATS", "3"))):
            t0 = time.monotonic()
            U, V = als.train_als(ratings, params, packed=packed)
            np.asarray(jax.device_get(V[0, :1]))
            best = min(best, time.monotonic() - t0)
    finally:
        als._train_fused = orig
    if "a" not in captured:
        print(json.dumps({"error": "train_als did not take the fused "
                                   "path (checkpointing active?)"}))
        return

    lowered = orig.lower(*captured["a"], **captured["k"])
    comp = lowered.compile()
    ca = comp.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    device = jax.devices()[0].device_kind
    bw = next((v for k, v in PEAK_BW.items() if device.startswith(k)),
              None)
    try:
        from bench import device_peak_flops

        peak_fl = device_peak_flops()
    except Exception:  # noqa: BLE001 — probe must not die on a moved
        peak_fl = None  # bench.py symbol
    per_iter_s = best / max(iters, 1)
    model_fl = als.als_flops_per_iter(packed[0], packed[1], params)
    achieved_fl = model_fl / per_iter_s if per_iter_s else None
    out = {
        "metric": "als_fused_roofline",
        "device": device,
        "gram_mode": gram,
        "gather_dtype": gather,
        "rank": rank, "nnz": nnz, "iters_in_program": iters,
        "xla_flops": flops,
        "xla_bytes_accessed": byts,
        "xla_optimal_seconds": ca.get("optimal_seconds"),
        "steady_state_s_per_iter": round(per_iter_s, 4),
        "model_flops_per_iter": model_fl,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
    }
    if achieved_fl:
        out["achieved_tflops"] = round(achieved_fl / 1e12, 3)
        if peak_fl:
            out["mfu"] = round(achieved_fl / peak_fl, 4)
    if byts and best:
        # bytes accessed is XLA's POST-fusion traffic model for the
        # compiled program (iters iterations): achieved bandwidth =
        # bytes / steady-state run time
        gbps = byts / best / 1e9
        out["hbm_gbps"] = round(gbps, 1)
        if bw:
            out["hbm_peak_gbps"] = bw
            out["hbm_utilization"] = round(gbps / bw, 3)
    if byts and flops:
        # dual-roofline position: where the program SITS (intensity)
        # and which roof is over it
        ai = flops / byts
        out["arithmetic_intensity"] = round(ai, 3)
        if bw and peak_fl:
            attainable = min(peak_fl, ai * bw * 1e9)
            out["attainable_tflops"] = round(attainable / 1e12, 2)
            out["bound"] = "hbm" if ai * bw * 1e9 < peak_fl else "mxu"
            if achieved_fl:
                out["roofline_fraction"] = round(
                    achieved_fl / attainable, 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
